"""Shared ink whiteboard over the TCP service — the canvas sample
(reference: examples/data-objects/canvas + the ink DDS): two artists
draw concurrent strokes, one clears the board mid-stroke, and an
ASCII render of the converged canvas is printed from both replicas.

Run: python examples/ink_whiteboard.py
(starts its own service subprocess on a free port)
"""
import math
import os
import re
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from fluidframework_tpu.drivers.socket_driver import (  # noqa: E402
    SocketDocumentService,
)
from fluidframework_tpu.loader import Container  # noqa: E402

W, H = 48, 14


def render(ink) -> str:
    grid = [[" "] * W for _ in range(H)]
    # paint in a replica-independent order: get_strokes() iterates
    # local insertion order, which differs between replicas for
    # concurrent strokes — sort by stroke id for a deterministic
    # z-order
    for stroke in sorted(ink.get_strokes(),
                         key=lambda s: s.get("id", "")):
        mark = stroke["pen"].get("mark", "*")
        for p in stroke["points"]:
            x, y = int(p["x"]), int(p["y"])
            if 0 <= x < W and 0 <= y < H:
                grid[y][x] = mark
    return "\n".join("".join(row) for row in grid)


def wait_converged(svc_a, ia, svc_b, ib, timeout=20.0):
    """Broadcast delivery is async: wait until both replicas hold the
    same stroke/point counts before comparing renders."""
    def counts(ink):
        return sorted((s["pen"].get("mark", "*"), len(s["points"]))
                      for s in ink.get_strokes())
    deadline = time.time() + timeout
    while time.time() < deadline:
        with svc_a.lock, svc_b.lock:
            if counts(ia) == counts(ib):
                return
        time.sleep(0.05)
    raise TimeoutError("replicas never converged")


def pump(svc, container, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        with svc.lock:
            if container.runtime.pending.count == 0:
                return
        time.sleep(0.02)
    raise TimeoutError("ops never acked")


def main() -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    server = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=repo, env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    line = server.stdout.readline()
    port = int(re.search(r":(\d+)", line).group(1))
    try:
        svc_a = SocketDocumentService("127.0.0.1", port, "board")
        with svc_a.lock:
            ca = Container.load(svc_a, client_id="ana")
            ia = ca.runtime.create_datastore("app").create_channel(
                "ink", "canvas")
            ca.flush()
        pump(svc_a, ca)

        svc_b = SocketDocumentService("127.0.0.1", port, "board")
        with svc_b.lock:
            cb = Container.load(svc_b, client_id="ben")
            ib = cb.runtime.get_datastore("app").get_channel("canvas")

        # ana draws a sine wave while ben draws a box — concurrently
        with svc_a.lock:
            s1 = ia.create_stroke({"mark": "~", "color": "blue"})
            for x in range(2, W - 2):
                ia.append_point(s1, {
                    "x": x, "y": int(H / 2 + 4 * math.sin(x / 4))})
            ca.flush()
        with svc_b.lock:
            s2 = ib.create_stroke({"mark": "#", "color": "red"})
            for x in range(8, 40):
                ib.append_point(s2, {"x": x, "y": 2})
                ib.append_point(s2, {"x": x, "y": H - 3})
            for y in range(2, H - 2):
                ib.append_point(s2, {"x": 8, "y": y})
                ib.append_point(s2, {"x": 39, "y": y})
            cb.flush()
        pump(svc_a, ca)
        pump(svc_b, cb)
        wait_converged(svc_a, ia, svc_b, ib)

        with svc_a.lock, svc_b.lock:
            ra, rb = render(ia), render(ib)
            assert ra == rb, "canvases diverged"
            n_str = len(ia.get_strokes())
        print(f"converged canvas ({n_str} strokes):")
        print(ra)

        # ben clears while ana keeps drawing: clear-wins on the
        # earlier strokes, ana's post-clear points survive
        with svc_b.lock:
            ib.clear()
            cb.flush()
        with svc_a.lock:
            s3 = ia.create_stroke({"mark": "o"})
            for x in range(20, 28):
                ia.append_point(s3, {"x": x, "y": 6})
            ca.flush()
        pump(svc_a, ca)
        pump(svc_b, cb)
        wait_converged(svc_a, ia, svc_b, ib)
        with svc_a.lock, svc_b.lock:
            ra, rb = render(ia), render(ib)
            assert ra == rb, "post-clear canvases diverged"
            assert all(s["pen"].get("mark") == "o"
                       for s in ia.get_strokes())
        print("after ben's clear + ana's new stroke (converged):")
        print(ra)
        print("OK: ink whiteboard converged over the TCP service, "
              "including a concurrent clear.")
        with svc_a.lock:
            ca.close()
        with svc_b.lock:
            cb.close()
        svc_a.close()
        svc_b.close()
        return 0
    finally:
        os.kill(server.pid, signal.SIGKILL)
        server.wait()


if __name__ == "__main__":
    raise SystemExit(main())
