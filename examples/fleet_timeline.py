"""Fleet observability walkthrough: kill a replicated sequencer's
leader under the step clock and read the incident back three ways.

1. THE TIMELINE: every cross-node lifecycle event (lease grants and
   renewals, the lapse, anti-entropy pulls, the epoch fence advance,
   the promotion, the first post-failover ack) lands on ONE causally
   ordered FleetTimeline (obs/timeline.py), and `failover_phases()`
   decomposes the opaque failover number into detection /
   anti-entropy / promotion / first-ack — summing to the total
   exactly.
2. THE FEDERATED SNAPSHOT: leader and followers each keep their OWN
   metrics registry (no double-counting into one process aggregate);
   obs.federation.FederatedView merges them back — counters sum,
   gauges keep per-node identity under a `node` label.
3. THE SPAN TREE: the whole incident exported as an OTLP-JSON trace
   (obs/spans.py timeline_to_otlp) next to the per-op spans, and one
   replicated op's own breakdown showing the quorum barrier as its
   repl:forward -> repl:quorum_ack hops.

Everything rides an injected step clock, so the printed numbers are
bit-identical on every run.

Run: python examples/fleet_timeline.py
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from fluidframework_tpu.drivers import (  # noqa: E402
    LocalDocumentServiceFactory,
)
from fluidframework_tpu.loader import Container  # noqa: E402
from fluidframework_tpu.obs.federation import FederatedView  # noqa: E402
from fluidframework_tpu.obs.metrics import MetricsRegistry  # noqa: E402
from fluidframework_tpu.obs.spans import timeline_to_otlp  # noqa: E402
from fluidframework_tpu.obs.timeline import FleetTimeline  # noqa: E402
from fluidframework_tpu.service.replication import (  # noqa: E402
    ReplicatedSequencerGroup,
)


class StepClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def drive(container, n, tag):
    ds = container.runtime.datastores.get("app") or \
        container.runtime.create_datastore("app")
    if "text" not in ds.channels:
        ds.create_channel("sharedstring", "text")
    text = ds.get_channel("text")
    for i in range(n):
        text.insert_text(0, f"{tag}{i}.")
        container.flush()
    return text.get_text()


def main():
    clock = StepClock()
    registries = {f"node-{i}": MetricsRegistry(node=f"node-{i}")
                  for i in range(3)}
    timeline = FleetTimeline(clock=clock,
                             registry=registries["node-0"])
    fleet = FederatedView(clock=clock)
    for node, reg in registries.items():
        fleet.add_registry(node, reg)

    root = tempfile.mkdtemp(prefix="fleet-timeline-")
    group = ReplicatedSequencerGroup(
        root, n_followers=2, clock=clock, lease_ttl=0.3,
        registry=registries["node-0"],
        follower_registries=[registries["node-1"],
                             registries["node-2"]],
        timeline=timeline,
        server_kwargs=dict(clock=clock),
    )

    print("== act 1: steady serving on the replicated plane ==")
    writer = Container.load(
        LocalDocumentServiceFactory(group.server)
        .create_document_service("doc"),
        client_id="writer")
    for _ in range(5):
        clock.t += 0.05
        drive(writer, 1, "w")
    print(f"  5 ops quorum-acked; committed head ="
          f" {group.committed('doc')}")
    print("  one op's breakdown (the quorum barrier is its own hop):")
    hops = [h["hop"] for h in writer.op_trace()["hops"]]
    print("   ", " -> ".join(h for h in hops if h.startswith("repl")))

    print("\n== act 2: host loss, lease lapse, promotion ==")
    timeline.record("leader_kill", node=group.leader_id,
                    mode="example")
    group.kill_leader()
    clock.t += group.lease.ttl + 0.01  # nobody renews; TTL lapses
    group.failover()
    print(f"  promoted {group.leader_id} at epoch {group.epoch}")
    reader = Container.load(
        LocalDocumentServiceFactory(group.server)
        .create_document_service("doc"),
        client_id="reader")
    clock.t += 0.05
    drive(reader, 1, "post")
    timeline.record("first_ack", node=group.leader_id)

    print("\n== act 3: the causal timeline, decomposed ==")
    print(timeline.format())
    phases = timeline.failover_phases()
    print("  failover phases (sum == total, within one step):")
    for key in ("detection_s", "anti_entropy_s", "promotion_s",
                "first_ack_s", "total_s"):
        print(f"    {key:<15} {phases[key]:.3f}s")
    total = sum(phases[k] for k in ("detection_s", "anti_entropy_s",
                                    "promotion_s", "first_ack_s"))
    assert abs(total - phases["total_s"]) < 1e-9

    print("\n== act 4: the federated fleet snapshot ==")
    merged = fleet.refresh()
    for name in ("sequencer_failovers_total",
                 "sequencer_fenced_writes_total",
                 "timeline_events_total", "repl_epoch",
                 "fleet_nodes"):
        fam = merged.get(name)
        if fam is None:
            continue
        for labels, value in sorted(fam["values"].items()):
            if isinstance(value, dict):
                value = value["count"]
            print(f"  {name}{labels} = {value}")

    doc = timeline_to_otlp(timeline.events())
    spans = doc["resourceSpans"][0]["scopeSpans"][0]["spans"]
    print(f"\n  incident exported as {len(spans)} OTLP spans "
          f"(root + one per event)")
    assert doc == timeline_to_otlp(timeline.events())

    writer.close()
    reader.close()
    print("\nOK")


if __name__ == "__main__":
    main()
