"""Collaborative spreadsheet: SharedMatrix rows/cols/cells (the
table-document sample, examples/data-objects/table-document).

Run: python examples/table_grid.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Container
from fluidframework_tpu.service.local_server import LocalServer


def main() -> int:
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    a = Container.load(factory.create_document_service("sheet"),
                       client_id="a")
    grid_a = (a.runtime.create_datastore("table")
              .create_channel("sharedmatrix", "grid"))
    a.flush()
    grid_a.insert_rows(0, 3)
    grid_a.insert_cols(0, 3)
    for r in range(3):
        for c in range(3):
            grid_a.set_cell(r, c, r * 3 + c)
    a.flush()

    b = Container.load(factory.create_document_service("sheet"),
                       client_id="b")
    grid_b = b.runtime.get_datastore("table").get_channel("grid")

    # concurrent structural edits: a inserts a row while b inserts a
    # column — permutation vectors merge them
    grid_a.insert_rows(1, 1)
    grid_b.insert_cols(0, 1)
    grid_b.set_cell(0, 0, "hdr")
    a.flush()
    b.flush()

    assert grid_a.row_count == grid_b.row_count == 4
    assert grid_a.col_count == grid_b.col_count == 4
    for r in range(grid_a.row_count):
        row = [grid_a.get_cell(r, c, default="·")
               for c in range(grid_a.col_count)]
        print(" | ".join(f"{v!s:>4}" for v in row))
        for c in range(grid_a.col_count):
            assert grid_a.get_cell(r, c) == grid_b.get_cell(r, c)
    a.close()
    b.close()
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
