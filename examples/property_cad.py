"""Schema-typed property-tree collaboration with Materialized History
publishing — the PropertyDDS sample (reference:
experimental/PropertyDDS example apps + the moira lambda pipeline).

Two engineers edit a typed parts tree (SharedPropertyTree: schemas,
squashed working changesets, commit()); every committed changeset is
published by the Moira lambda as a commit on the channel's branch in a
Materialized History service running in ANOTHER PROCESS, and the
branch's commit graph is read back over TCP.

Run: python examples/property_cad.py
"""
import os
import re
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from fluidframework_tpu.service.moira import (  # noqa: E402
    MaterializedHistoryClient,
    MoiraLambda,
    derived_guid,
)
from fluidframework_tpu.testing.runtime_mocks import (  # noqa: E402
    ContainerSession,
)

PART = {
    "typeid": "demo:part-1.0.0",
    "properties": [
        {"id": "x", "typeid": "Float64"},
        {"id": "y", "typeid": "Float64"},
        {"id": "label", "typeid": "String"},
    ],
}


def main() -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    mh = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service.moira",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=repo, env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    line = mh.stdout.readline()
    port = int(re.search(r":(\d+)", line).group(1))
    try:
        # collaborative session with a moira tap on the stream
        s = ContainerSession(["ana", "ben"])
        log = []
        orig = s._broadcast
        s._broadcast = lambda m: (log.append(m), orig(m))[1]
        for cid in ("ana", "ben"):
            s.runtime(cid).create_datastore("cad").create_channel(
                "sharedpropertytree", "parts")
            t = s.runtime(cid).get_datastore("cad").get_channel(
                "parts")
            t.schemas.register(PART)
        s.process_all()
        ana = s.runtime("ana").get_datastore("cad").get_channel(
            "parts")
        ben = s.runtime("ben").get_datastore("cad").get_channel(
            "parts")

        ana.insert_property("base", "demo:part-1.0.0")
        ana.set_value("base.label", "baseplate")
        ana.commit()
        s.process_all()
        ben.insert_property("arm", "demo:part-1.0.0")
        ben.set_value("arm.x", 12.5)
        ben.commit()
        s.process_all()
        ana.set_value("arm.y", -3.25)   # edit ben's part
        ana.commit()
        s.process_all()
        assert ana.signature() == ben.signature()
        print(f"converged parts: base={ana.get_value('base.label')!r}"
              f" arm=({ana.get_value('arm.x')}, "
              f"{ana.get_value('arm.y')})")

        # publish the sequenced changesets to the MH process
        client = MaterializedHistoryClient("127.0.0.1", port)
        lam = MoiraLambda(client, "cad-doc")
        for i, msg in enumerate(log):
            lam.handler(msg, offset=i)
        n = lam.flush()
        branch = derived_guid("cad-doc", "cad/parts")
        state = client.get_branch(branch)
        print(f"moira published {n} commits on branch "
              f"{branch[:13]}…")
        parent = state["rootCommitGuid"]
        for c in state["commits"]:
            assert c["parentGuid"] == parent  # linear history
            parent = c["guid"]
            meta = c["meta"]
            print(f"  commit {c['guid'][:8]} seq="
                  f"{meta['sequenceNumber']} "
                  f"msn={meta['minimumSequenceNumber']}")
        assert n == 3 and len(state["commits"]) == 3
        client.close()
        print("OK: property tree converged and its history is "
              "queryable from the Materialized History service.")
        return 0
    finally:
        mh.kill()
        mh.wait()


if __name__ == "__main__":
    raise SystemExit(main())
