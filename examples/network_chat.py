"""Networked collaboration: the runnable dev service + socket driver
end to end in one process (the collaborative-textarea sample over
tinylicious).

Run: python examples/network_chat.py
"""
import asyncio
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from fluidframework_tpu.drivers.socket_driver import (
    SocketDocumentService,
)
from fluidframework_tpu.loader import Container
from fluidframework_tpu.service.ingress import AlfredServer


def main() -> int:
    server = AlfredServer()
    loop = asyncio.new_event_loop()
    started = threading.Event()

    async def run():
        await server.start()
        started.set()
        await server.serve_forever()

    threading.Thread(
        target=lambda: loop.run_until_complete(run()), daemon=True
    ).start()
    assert started.wait(10)
    print(f"dev service on 127.0.0.1:{server.port} "
          "(same protocol as python -m fluidframework_tpu.service)")

    svc_a = SocketDocumentService("127.0.0.1", server.port, "chat")
    with svc_a.lock:
        alice = Container.load(svc_a, client_id="alice")
        log_a = (alice.runtime.create_datastore("room")
                 .create_channel("sharedstring", "log"))
        alice.flush()
        log_a.insert_text(0, "alice: hello over TCP\n")
        alice.flush()

    svc_b = SocketDocumentService("127.0.0.1", server.port, "chat")
    with svc_b.lock:
        bob = Container.load(svc_b, client_id="bob")
        log_b = bob.runtime.get_datastore("room").get_channel("log")
        log_b.insert_text(len(log_b.get_text()),
                          "bob: hi, got your message\n")
        bob.flush()

    deadline = time.time() + 10
    while time.time() < deadline:
        with svc_a.lock:
            if "bob:" in log_a.get_text():
                break
        time.sleep(0.05)
    with svc_a.lock, svc_b.lock:
        transcript = log_a.get_text()
        assert transcript == log_b.get_text()
    print(transcript.rstrip())
    with svc_a.lock:
        alice.close()
    with svc_b.lock:
        bob.close()
    svc_a.close()
    svc_b.close()
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
