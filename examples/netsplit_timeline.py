"""Partition-tolerance walkthrough: split a replicated sequencer's
network on the step clock and read the incident's phase decomposition
off the fleet timeline.

1. THE SPLIT: the leader lands alone in a minority island (the lease
   service with the majority). Its quorum barrier discovers the loss
   by DEADLINE — one submit pays the wait, every later one fast-nacks
   with the retriable "unavailable" refusal (shed_class rides the
   nack's optional wire fields) while reads stay served, clamped at
   the committed watermark: the read-only brownout.
2. THE ELECTION: the lease lapses (renewals are lost across the
   split); the majority elects a follower; the deposed minority
   leader is refused by the epoch fence on its next write.
3. THE HEAL + REJOIN: the old leader rejoins as a follower via full
   anti-entropy behind the fence; membership grows back.
4. THE SCRUB: a planted mid-file bit-flip (parseable record, wrong
   crc) is read-repaired from a quorum peer, loudly counted.

Every phase lands on ONE causally ordered FleetTimeline
(partition / degraded_enter / lease_expire / promotion /
fenced_write / heal / rejoin / scrub_repair), and the printed
decomposition is bit-identical on every run — everything rides the
injected step clock.

Run: python examples/netsplit_timeline.py
"""
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from fluidframework_tpu.drivers import (  # noqa: E402
    LocalDocumentServiceFactory,
)
from fluidframework_tpu.loader import Container  # noqa: E402
from fluidframework_tpu.obs.metrics import MetricsRegistry  # noqa: E402
from fluidframework_tpu.obs.timeline import FleetTimeline  # noqa: E402
from fluidframework_tpu.service.replication import (  # noqa: E402
    NetworkTopology,
    QuorumUnavailableError,
    ReplicatedSequencerGroup,
)


class StepClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def drive(container, n, tag):
    ds = container.runtime.datastores.get("app") or \
        container.runtime.create_datastore("app")
    if "text" not in ds.channels:
        ds.create_channel("sharedstring", "text")
    text = ds.get_channel("text")
    for i in range(n):
        text.insert_text(0, f"{tag}{i}.")
        container.flush()
    return text.get_text()


def main():
    clock = StepClock()
    registry = MetricsRegistry(node="node-0")
    timeline = FleetTimeline(clock=clock, registry=registry)
    network = NetworkTopology(timeline=timeline)
    root = tempfile.mkdtemp(prefix="netsplit-timeline-")
    group = ReplicatedSequencerGroup(
        root, n_followers=2, clock=clock, lease_ttl=0.3,
        registry=registry, timeline=timeline, network=network,
        quorum_timeout_s=0.2, retry_interval_s=0.05,
        sleep=lambda dt: setattr(clock, "t", clock.t + dt),
        server_kwargs=dict(clock=clock),
    )

    print("== act 1: steady serving, then the split ==")
    writer = Container.load(
        LocalDocumentServiceFactory(group.server)
        .create_document_service("doc"),
        client_id="writer")
    writer._backoff_clock = clock
    for _ in range(4):
        clock.t += 0.05
        drive(writer, 1, "w")
    print(f"  4 ops quorum-acked; committed = {group.committed('doc')}")
    network.partition([["node-0"], ["node-1", "node-2"]],
                      lease_island=1)
    nacks = []
    writer.on("nack", nacks.append)
    clock.t += 0.05
    drive(writer, 1, "lost")  # pays the deadline, comes back nacked
    print(f"  minority-side write refused: {len(nacks)} retriable "
          f"nack(s), shed_class={nacks[0].shed_class!r}")
    reads = group.server.read_ops("doc", 0)
    print(f"  reads still served, clamped at committed "
          f"({reads[-1].sequence_number} == {group.committed('doc')})")

    print("\n== act 2: the majority elects; the minority is fenced ==")
    while not group.lease.expired():
        clock.t += 0.05
    old_server = group.server
    group.failover()  # the majority side observes the lapse
    print(f"  promoted {group.leader_id} at epoch {group.epoch}")
    try:
        old_server.read_ops("doc", 0)
    except Exception as e:
        print(f"  deposed minority leader refused: "
              f"{type(e).__name__}")

    print("\n== act 3: heal, rejoin, scrub ==")
    network.heal()
    rejoined = group.rejoin("node-0")
    print(f"  node-0 rejoined as a follower at head "
          f"{rejoined.head('doc')}; quorum back to {group.quorum}")
    # plant one mid-file bit-rot state on a follower and repair it
    target = group.followers[0]
    path = target._log_path("doc")
    lines = open(path).readlines()
    row = json.loads(lines[1])
    row["contents"] = {"bitrot": True}  # stale _crc: mismatch
    lines[1] = json.dumps(row) + "\n"
    fh = target._fhs.pop("doc", None)
    if fh is not None:
        fh.close()
    open(path, "w").writelines(lines)
    repaired = group.scrub()
    print(f"  scrubber read-repaired {repaired} bit-rotted record(s) "
          "from a quorum peer")

    print("\n== act 4: the causal timeline ==")
    print(timeline.format())
    kinds = [e.kind for e in timeline.events()]
    for expected in ("partition", "degraded_enter", "lease_expire",
                     "promotion", "heal", "rejoin", "scrub_repair"):
        assert expected in kinds, (expected, kinds)
    order = [kinds.index(k) for k in ("partition", "degraded_enter",
                                      "promotion", "heal", "rejoin")]
    assert order == sorted(order), kinds
    assert repaired == 1
    writer.close()
    print("\nOK")


if __name__ == "__main__":
    main()
