"""Cost attribution walkthrough: where did the device time go, and
who is spending it?

1. THE LEDGER: a deterministic HeatLedger (obs/heat.py) charged by
   the sidecar attribution plane — each dispatch round's wall-ms is
   split across the documents in the round proportional to the ops
   each contributed, at the settle boundary (counts come off the
   pack metadata; no mid-loop device sync). The sum of per-doc
   charges equals the round total: device time is conserved.
2. THE TENANT ROLLUP: every doc charge also rolls up to the doc's
   tenant on a usage ledger, so "hot tenants" rank by the same
   device-ms unit as "hot documents" — next to the ingress counters
   (ops offered/ticketed, bytes, sheds) that explain the bill.
3. THE FLEET VIEW: two nodes each serve their own top-k heat cut
   (the wire-1.4 ``heat`` frame; ``--dump-heat HOST:PORT`` on the
   CLI); obs.federation merges the cuts — per-key sums, re-ranked
   by the deterministic heat ordering.

Run: python examples/heat_dump.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from fluidframework_tpu.obs.federation import FederatedView
from fluidframework_tpu.obs.heat import (
    HeatLedger,
    attribute_round,
    usage_ledger,
)
from fluidframework_tpu.tools.serve_bench import (
    ServeBenchConfig,
    run_serve_bench,
)


def tenant_of(doc: str) -> str:
    return "tenant-%s" % (int(doc.rsplit("-", 1)[1]) % 3)


def main() -> int:
    # -- 1. the ledger, charged by hand to show the mechanics --------
    heat = HeatLedger(clock=iter(range(1, 10**6)).__next__)
    usage = usage_ledger(clock=iter(range(1, 10**6)).__next__)
    rounds = [
        ({"doc-0": 6, "doc-1": 2, "doc-2": 2}, 5.0),
        ({"doc-0": 1, "doc-3": 3}, 2.0),
        ({"doc-1": 4, "doc-2": 4}, 4.0),
    ]
    charged = 0.0
    for counts, round_ms in rounds:
        charged += attribute_round(heat, counts, round_ms,
                                   usage=usage, tenant_of=tenant_of)
    total_ms = sum(ms for _, ms in rounds)
    print(f"attributed {charged:g}ms of {total_ms:g}ms "
          f"across {len(heat)} documents (conserved: "
          f"{abs(charged - total_ms) < 1e-9})")
    print("hot documents (accumulated device-ms):")
    for doc, ms in heat.top_k(4):
        print(f"  {doc:<8} {ms:7.3f}ms  tenant={tenant_of(doc)}")
    print("hot tenants:")
    for tenant, ms in usage.top_k(3, by="device_ms"):
        print(f"  {tenant:<10} {ms:7.3f}ms")

    # -- 2. the real plumbing: the serve_bench sidecar slice with the
    #       attribution plane on (the config16 shape) ----------------
    report = run_serve_bench(ServeBenchConfig(
        n_docs=16, readers_per_doc=2, duration_s=1.5,
        capacity_ops_per_s=200.0, seed=7,
        sidecar_docs=4, sidecar_steps=30, heat=True))
    print(f"\nserve_bench sidecar: {report.sidecar_rounds} rounds, "
          f"{report.heat_attributed_ms:g}ms attributed")
    print(f"  top docs:    {report.heat_top_docs[:3]}")
    print(f"  top tenants: {report.heat_top_tenants[:3]}")
    assert report.heat_top_docs, "attribution plane produced no heat"

    # -- 3. federate two nodes' served cuts --------------------------
    fleet = FederatedView()
    fleet.add_heat("node-a",
                   docs=[["doc-0", 4.0], ["doc-1", 3.0]],
                   tenants=[["tenant-0", 4.0], ["tenant-1", 3.0]])
    fleet.add_heat("node-b",
                   docs=[["doc-1", 3.5], ["doc-9", 1.0]],
                   tenants=[["tenant-1", 3.5], ["tenant-0", 1.0]])
    merged = fleet.heat_top_k(k=3)
    print("\nfleet heat (two nodes merged):")
    print(f"  docs:    {merged['docs']}")
    print(f"  tenants: {merged['tenants']}")
    assert merged["docs"][0] == ["doc-1", 6.5], merged["docs"]

    print("\nOK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
