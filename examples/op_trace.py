"""Per-op trace demo: pick any op and print its ordered submit→ack
hop list with per-hop latencies — the "where is op X right now"
answer the PR-2 ack stall lacked.

One process, three planes:

- a real TCP ingress (AlfredServer) on a background thread,
- a TPU merge sidecar (trace_ops on) subscribed server-side to the
  document's broadcaster,
- two socket clients editing concurrently.

For a chosen op the CLIENT sees its wire-path hops (submit,
driver-send, ingress, sequenced, fanout, deliver, ack) from its own
deserialized copy; the SIDECAR's copy carries the dispatch hops
(pack, settle). The script merges both by sequence number and prints
the combined breakdown, then the metrics-registry exposition and the
sidecar's flight-recorder tail.

Run: python examples/op_trace.py [seq]
"""
import asyncio
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from fluidframework_tpu.drivers.socket_driver import (  # noqa: E402
    SocketDocumentService,
)
from fluidframework_tpu.loader import Container  # noqa: E402
from fluidframework_tpu.obs import (  # noqa: E402
    REGISTRY,
    breakdown,
    format_breakdown,
    total_ms,
)
from fluidframework_tpu.service.ingress import AlfredServer  # noqa: E402
from fluidframework_tpu.service.tpu_sidecar import (  # noqa: E402
    TpuMergeSidecar,
)

DOC = "traced"


def start_server():
    server = AlfredServer()
    loop = asyncio.new_event_loop()
    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)
        loop.run_until_complete(server.start())
        started.set()
        loop.run_forever()

    thread = threading.Thread(target=run, daemon=True)
    thread.start()
    assert started.wait(10)
    return server, loop


def pump(svc, container, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        with svc.lock:
            if container.runtime.pending.count == 0:
                return
        time.sleep(0.02)
    raise TimeoutError("ops never acked")


def main() -> int:
    server, loop = start_server()
    sidecar = TpuMergeSidecar(max_docs=8, capacity=256,
                              trace_ops=True)
    sidecar.subscribe(server.local, DOC, "app", "s")

    svc_a = SocketDocumentService("127.0.0.1", server.port, DOC)
    with svc_a.lock:
        ca = Container.load(svc_a, client_id="ana")
        sa = ca.runtime.create_datastore("app").create_channel(
            "sharedstring", "s")
        ca.flush()
    pump(svc_a, ca)

    svc_b = SocketDocumentService("127.0.0.1", server.port, DOC)
    with svc_b.lock:
        cb = Container.load(svc_b, client_id="ben")
        sb = cb.runtime.get_datastore("app").get_channel("s")

    # concurrent edits so the trace crosses real interleaving
    with svc_a.lock:
        for i in range(4):
            sa.insert_text(0, f"a{i} ")
        ca.flush()
    with svc_b.lock:
        sb.insert_text(0, "ben-was-here ")
        cb.flush()
    pump(svc_a, ca)
    pump(svc_b, cb)

    # flush the sidecar's accumulated window; sync() settles it so
    # the pack/settle hops are stamped
    sidecar.apply()
    sidecar.sync()

    # choose an op: newest of ana's acked ops, or by sequence number
    # from argv
    entry = ca.op_trace()
    if len(sys.argv) > 1:
        want = int(sys.argv[1])
        entry = next(
            (ca.op_trace(csn) for csn in range(1, ca._csn + 1)
             if (ca.op_trace(csn) or {}).get("sequenceNumber") == want),
            None,
        )
        if entry is None:
            print(f"no acked op with seq {want}")
            return 1

    seq = entry["sequenceNumber"]
    print(f"=== client-side trace of op seq={seq} "
          f"(csn={entry['clientSequenceNumber']}) ===")
    print(ca.op_breakdown(entry["clientSequenceNumber"]))

    # merge in the sidecar's dispatch hops for the same op
    sidecar_msg = next(
        (m for m in sidecar.last_settled_msgs
         if m.sequence_number == seq), None,
    )
    if sidecar_msg is not None:
        merged = list(entry["traces"])
        have = {(t.service, t.action, t.timestamp) for t in merged}
        merged += [
            t for t in sidecar_msg.traces
            if (t.service, t.action, t.timestamp) not in have
        ]
        print(f"\n=== merged with sidecar dispatch hops "
              f"({total_ms(merged):.3f} ms first→last) ===")
        print(format_breakdown(merged))
        hops = [h["hop"] for h in breakdown(merged)]
        assert "sidecar:pack" in hops and "sidecar:settle" in hops, (
            "sidecar hops missing from the merged trace"
        )

    print("\n=== per-hop summary over the ledgered ops ===")
    for hop, agg in sorted(ca.op_ledger.summary().items()):
        print(f"  {hop:<22} n={agg['count']:<4} "
              f"mean={agg['mean_ms']:8.3f}ms "
              f"max={agg['max_ms']:8.3f}ms")

    print("\n=== metrics registry (excerpt) ===")
    for line in REGISTRY.render_prometheus().splitlines():
        if line.startswith(("container_", "sidecar_", "sequencer_",
                            "ingress_")) and not line.endswith(" 0.0"):
            print(" ", line)

    print("\n=== sidecar flight recorder ===")
    print(sidecar.flight.dump(reason="example", last=8))

    with svc_a.lock:
        ca.close()
    with svc_b.lock:
        cb.close()
    svc_a.close()
    svc_b.close()
    loop.call_soon_threadsafe(loop.stop)
    print("\nOK: full submit→ack hop attribution for a live op over "
          "the TCP service, including sidecar dispatch hops.")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
