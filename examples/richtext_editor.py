"""Rich-text collaborative editor over the TCP service — the
prosemirror-class sample (reference:
examples/data-objects/prosemirror): two live editor sessions with
paragraphs, headings, bold/italic runs, sliding comments, stable
cursors through remote edits, and a reconnect mid-session.

Run: python examples/richtext_editor.py
(starts its own service subprocess on a free port)
"""
import os
import re
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from fluidframework_tpu.drivers.socket_driver import (  # noqa: E402
    SocketDocumentService,
)
from fluidframework_tpu.framework.richtext import (  # noqa: E402
    RichTextEditor,
)
from fluidframework_tpu.loader import Container  # noqa: E402


def show(title, editor):
    print(f"--- {title} ---")
    for p in editor.render():
        head = f"h{p.style['heading']} " if p.style.get("heading") \
            else ""
        runs = " + ".join(
            f"{t!r}{sorted(m) if m else ''}" for t, m in p.runs
        )
        print(f"  {head}{runs or '(empty)'}")
    for c in editor.comments():
        quoted = editor.text_span(c["start"], c["end"])
        print(f"  [comment by {c['author']}: {c['text']!r} "
              f"on {quoted!r}]")


def pump(svc, container, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        with svc.lock:
            if container.runtime.pending.count == 0:
                return
        time.sleep(0.02)
    raise TimeoutError("ops never acked")


def main() -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    server = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=repo, env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    line = server.stdout.readline()
    port = int(re.search(r":(\d+)", line).group(1))
    try:
        svc_a = SocketDocumentService("127.0.0.1", port, "article")
        with svc_a.lock:
            ca = Container.load(svc_a, client_id="alice")
            sa = ca.runtime.create_datastore("app").create_channel(
                "sharedstring", "body")
            ca.flush()
            alice = RichTextEditor(sa, "alice")
            alice.type_text("Collaborative Editing")
            alice.split_paragraph()
            alice.type_text("Two people can write one document.")
            ca.flush()
        pump(svc_a, ca)

        svc_b = SocketDocumentService("127.0.0.1", port, "article")
        with svc_b.lock:
            cb = Container.load(svc_b, client_id="bob")
            sb = cb.runtime.get_datastore("app").get_channel("body")
            bob = RichTextEditor(sb, "bob")
            show("bob joins and sees", bob)

        # bob sets his caret mid-sentence; alice edits BEFORE it;
        # bob's caret slides, his typing lands where he intended
        with svc_b.lock:
            bob.set_cursor(bob.doc_pos(
                bob.plain_text().index("one document")))
        with svc_a.lock:
            alice.set_cursor(0)
            alice.type_text(">> ")
            ca.flush()
        pump(svc_a, ca)
        time.sleep(0.3)  # let the broadcast reach bob
        with svc_b.lock:
            bob.type_text("exactly ")
            cb.flush()
        pump(svc_b, cb)

        # formatting + a comment anchored to sliding text
        with svc_a.lock:
            text = alice.plain_text()
            i = alice.doc_pos(text.index("Collaborative"))
            alice.set_cursor(i)
            alice.set_cursor(i + len("Collaborative Editing"),
                             extend=True)
            alice.toggle_mark("bold")
            j = alice.doc_pos(text.index("Two people"))
            alice.set_cursor(j)
            alice.set_heading(1)
            k = alice.doc_pos(text.index("one document"))
            alice.add_comment(k, k + len("one document"),
                              "define 'document'?")
            ca.flush()
        pump(svc_a, ca)

        # reconnect: bob goes offline, keeps typing, comes back
        with svc_b.lock:
            cb.disconnect()
            bob.set_cursor(bob.length)
            bob.split_paragraph(heading=2)
            bob.type_text("Offline section")
            bob.set_cursor(bob.length - len("section"))
            bob.set_cursor(bob.length, extend=True)
            bob.toggle_mark("italic")
        with svc_a.lock:
            alice.set_cursor(alice.length)
            alice.type_text(" (alice kept going)")
            ca.flush()
        pump(svc_a, ca)
        with svc_b.lock:
            cb.connect()
            cb.flush()
        pump(svc_b, cb)
        time.sleep(0.5)
        with svc_a.lock:
            ca.flush()
        pump(svc_a, ca)
        time.sleep(0.5)

        with svc_a.lock, svc_b.lock:
            ta, tb = alice.plain_text(), bob.plain_text()
            assert ta == tb, (ta, tb)
            assert [p.runs for p in alice.render()] == \
                [p.runs for p in bob.render()]
            assert alice.comments() == bob.comments()
            show("converged document (both editors identical)", alice)
        print("OK: rich-text session converged over the TCP "
              "service, including a reconnect.")
        with svc_a.lock:
            ca.close()
        with svc_b.lock:
            cb.close()
        svc_a.close()
        svc_b.close()
        return 0
    finally:
        os.kill(server.pid, signal.SIGKILL)
        server.wait()


if __name__ == "__main__":
    raise SystemExit(main())
