"""Todo app: SharedMap of items + undo/redo (the todo sample,
examples/data-objects/todo).

Run: python examples/todo_app.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.framework.undo_redo import (
    SharedMapUndoRedoHandler,
    UndoRedoStackManager,
)
from fluidframework_tpu.loader import Container
from fluidframework_tpu.service.local_server import LocalServer


def main() -> int:
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    app = Container.load(factory.create_document_service("todos"),
                         client_id="app")
    ds = app.runtime.create_datastore("todo")
    items = ds.create_channel("sharedmap", "items")
    app.flush()

    undo = UndoRedoStackManager()
    SharedMapUndoRedoHandler(undo, items)

    items.set("1", {"title": "write the framework", "done": True})
    items.set("2", {"title": "beat the baseline", "done": False})
    items.set("3", {"title": "ship examples", "done": False})
    app.flush()

    # a collaborator marks one done
    peer = Container.load(factory.create_document_service("todos"),
                          client_id="peer")
    peer_items = peer.runtime.get_datastore("todo").get_channel("items")
    entry = dict(peer_items.get("3"))
    entry["done"] = True
    peer_items.set("3", entry)
    peer.flush()

    for key in sorted(items.keys()):
        item = items.get(key)
        mark = "x" if item["done"] else " "
        print(f"[{mark}] {item['title']}")
    assert items.get("3")["done"] is True

    # undo the last local change on the app client
    undo.close_current_operation()
    items.set("2", {"title": "beat the baseline", "done": True})
    app.flush()
    undo.undo_operation()
    app.flush()
    assert items.get("2")["done"] is False
    print("undo restored item 2")
    app.close()
    peer.close()
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
