"""Collaborative text editing: two live clients over the in-proc
service (the shared-text sample, examples/data-objects/shared-text).

Run: python examples/collaborative_text.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Container
from fluidframework_tpu.service.local_server import LocalServer


def main() -> int:
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)

    alice = Container.load(factory.create_document_service("doc"),
                           client_id="alice")
    text_a = (alice.runtime.create_datastore("app")
              .create_channel("sharedstring", "story"))
    alice.flush()
    text_a.insert_text(0, "Collaboration works.")
    alice.flush()

    bob = Container.load(factory.create_document_service("doc"),
                         client_id="bob")
    text_b = bob.runtime.get_datastore("app").get_channel("story")
    print(f"bob loads: {text_b.get_text()!r}")

    # concurrent edits: both type before seeing each other
    text_a.insert_text(13, " really")
    text_b.annotate_range(0, 13, {"bold": True})
    text_b.insert_text(0, ">> ")
    alice.flush()
    bob.flush()

    assert text_a.get_text() == text_b.get_text()
    print(f"converged: {text_a.get_text()!r}")

    # interval collection: a comment anchored to a range slides with
    # edits (intervalCollection.ts semantics)
    comments = text_a.get_interval_collection("comments")
    interval = comments.add(3, 16)
    alice.flush()
    text_b.insert_text(0, "## ")
    bob.flush()
    start, end = comments.endpoints(interval)
    print(f"comment interval now at [{start}, {end}): "
          f"{text_a.get_text()[start:end]!r}")
    assert text_a.get_text()[start:end].startswith("Collaboration")

    alice.close()
    bob.close()
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
