"""SLO report walkthrough: declare objectives, serve open-loop
traffic, read the burn-rate verdicts — then watch the same
objectives breach under overload, with the profiler and span export
riding along.

Three acts, all deterministic (manual clock, seeded Poisson):

1. STEADY: the open-loop serving harness (tools/serve_bench.py)
   offers 0.8x capacity through the real ingress dispatch path; the
   SLO engine grades a submit→ack p99 budget and a goodput floor
   with multi-window burn rates — both hold.
2. OVERLOAD: the same config at 3x capacity. The backlog grows
   without bound, p99 collapses, both objectives burn through their
   budgets in BOTH windows -> breach, and the report cites the qos
   pressure context the breach happened under.
3. TOOLING: the continuous profiler's per-component attribution for
   the steady run, and one op's hop table exported as an OTLP-JSON
   span tree (obs/spans.py) and read back bit-exact.

Run: python examples/slo_report.py
"""
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from fluidframework_tpu.obs.spans import (  # noqa: E402
    FileSpanExporter,
    otlp_to_hops,
)
from fluidframework_tpu.obs.trace import stamp  # noqa: E402
from fluidframework_tpu.tools.serve_bench import (  # noqa: E402
    ServeBenchConfig,
    run_serve_bench,
)


def show_report(title, report):
    print(f"\n=== {title} ===")
    for o in report.slo_report["objectives"]:
        bound = (f" (p99 budget {o['effective_threshold_ms']}ms)"
                 if o["kind"] == "latency"
                 else f" (floor {o['target']:.0%})")
        print(f"  {o['name']:<16} {o['verdict']:>6}  "
              f"burn fast={o['fast']['burn']:<7} "
              f"slow={o['slow']['burn']:<7}{bound}")
    ctx = report.slo_report["context"]["pressure"]
    print(f"  offered={report.offered_ops} acked={report.acked_ops} "
          f"p99={report.latency_p99_ms:.1f}ms "
          f"backlog_peak={report.backlog_peak} "
          f"pressure={ctx['tier_name']}")


def main():
    cfg = dict(n_docs=32, readers_per_doc=2, duration_s=4.0,
               capacity_ops_per_s=300.0, seed=11)

    # Act 1 — steady state, profiler riding along
    steady = run_serve_bench(ServeBenchConfig(
        offered_multiple=0.8, profile=True, **cfg))
    show_report("steady (0.8x capacity)", steady)
    verdicts = {o["name"]: o["verdict"]
                for o in steady.slo_report["objectives"]}
    assert set(verdicts.values()) == {"ok"}, verdicts

    print("\n  profiler attribution (thread-name -> component):")
    for comp, n in steady.profiler["by_component"].items():
        print(f"    {comp:<10} {n} samples")
    print(f"    sampler own cost: "
          f"{steady.profiler['overhead_pct']:.2f}%")

    # Act 2 — overload: the objectives must SEE it
    overload = run_serve_bench(ServeBenchConfig(
        offered_multiple=3.0, **cfg))
    show_report("overload (3x capacity)", overload)
    assert "submit-ack-p99" in overload.slo_breached_objectives
    assert "goodput-floor" in overload.slo_breached_objectives

    # Act 3 — span export: one op's path as an OTLP trace document
    t0 = 1722700000.125
    traces = stamp([], "client", "submit", timestamp=t0)
    stamp(traces, "ingress", "receive", timestamp=t0 + 0.0021)
    stamp(traces, "sequencer", "ticket", timestamp=t0 + 0.0038)
    stamp(traces, "broadcaster", "fanout", timestamp=t0 + 0.0049)
    stamp(traces, "client", "ack", timestamp=t0 + 0.0112)
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "spans.jsonl")
        doc = FileSpanExporter(path).export(
            traces, document_id="doc", client_id="alice", csn=1)
        with open(path, encoding="utf-8") as f:
            reread = json.loads(f.readline())
    spans = doc["resourceSpans"][0]["scopeSpans"][0]["spans"]
    print(f"\n=== span export ({len(spans)} spans, "
          f"trace {spans[0]['traceId'][:12]}…) ===")
    for s in spans[1:]:
        ms = (int(s["endTimeUnixNano"])
              - int(s["startTimeUnixNano"])) / 1e6
        print(f"  {s['name']:<20} +{ms:.3f} ms")
    back = otlp_to_hops(reread)
    assert [(t.service, t.action, t.timestamp) for t in back] == \
        [(t.service, t.action, t.timestamp) for t in traces]
    print("  round-trip through disk: bit-exact")

    print("\nOK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
