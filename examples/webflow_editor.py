"""Flowed-document editor over the TCP service — the webflow-class
sample (reference: examples/data-objects/webflow): two live sessions
editing one FLOWED document — nested inline tag ranges (em/strong as
paired markers), paragraphs and line breaks as tiles, css-class
token-list formatting, sliding comments — with a removal that crosses
a tag pair (the partner tag is cleaned up) and a disconnect/reconnect
mid-session.

Run: python examples/webflow_editor.py
(starts its own service subprocess on a free port)
"""
import os
import re
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from fluidframework_tpu.drivers.socket_driver import (  # noqa: E402
    SocketDocumentService,
)
from fluidframework_tpu.framework.flowdoc import (  # noqa: E402
    FlowDocument,
)
from fluidframework_tpu.loader import Container  # noqa: E402


def show(title, doc):
    print(f"--- {title} ---")
    for b in doc.render():
        head = f"h{b.heading} " if b.heading else \
            ("~ " if b.kind == "br" else "")
        runs = " + ".join(
            f"{t!r}"
            + (f"<{'/'.join(tags)}>" if tags else "")
            + (f".{'.'.join(sorted(cls))}" if cls else "")
            for t, tags, cls in b.runs
        )
        print(f"  {head}{runs or '(empty)'}")
    for c in doc.comments():
        quoted = doc.text_span(c["start"], c["end"] + 1)
        print(f"  [comment by {c['author']}: {c['text']!r} "
              f"on {quoted!r}]")


def pump(svc, container, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        with svc.lock:
            if container.runtime.pending.count == 0:
                return
        time.sleep(0.02)
    raise TimeoutError("ops never acked")


def main() -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    server = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=repo, env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    line = server.stdout.readline()
    port = int(re.search(r":(\d+)", line).group(1))
    try:
        svc_a = SocketDocumentService("127.0.0.1", port, "flowpage")
        with svc_a.lock:
            ca = Container.load(svc_a, client_id="alice")
            sa = ca.runtime.create_datastore("app").create_channel(
                "sharedstring", "body")
            ca.flush()
            alice = FlowDocument(sa, "alice")
            alice.insert_text(0, "Flowed documents nest inline "
                                 "ranges inside block tiles.")
            alice.insert_paragraph(0, heading=1)
            alice.insert_text(0, "Webflow sample")
            ca.flush()
        pump(svc_a, ca)

        svc_b = SocketDocumentService("127.0.0.1", port, "flowpage")
        with svc_b.lock:
            cb = Container.load(svc_b, client_id="bob")
            sb = cb.runtime.get_datastore("app").get_channel("body")
            bob = FlowDocument(sb, "bob")
            show("bob joins and sees", bob)

        # concurrent inline structure: alice emphasizes a span while
        # bob strongs a different one; both nest cleanly
        with svc_a.lock:
            i = alice.doc_pos(
                alice.plain_text().index("inline ranges"))
            alice.insert_tags(i, i + len("inline ranges"), "em")
            ca.flush()
        pump(svc_a, ca)
        time.sleep(0.3)
        with svc_b.lock:
            bob.insert_tags(bob.length - 1 - len("block tiles."),
                            bob.length - 1, "strong")
            bob.add_css_class(0, len("Webflow sample") + 1, "hero")
            cb.flush()
        pump(svc_b, cb)
        time.sleep(0.3)

        # a comment anchored to text that will slide
        with svc_a.lock:
            ca.flush()
            # comments take DOC positions (markers occupy positions):
            # map the plain-text index through doc_pos
            k = alice.doc_pos(alice.plain_text().index("block"))
            alice.add_comment(k, k + len("block"), "tiles = markers")
            alice.insert_text(0, ">> ")
            ca.flush()
        pump(svc_a, ca)
        time.sleep(0.3)

        # removal crossing a tag pair: bob deletes a range containing
        # an END tag marker; the orphaned BEGIN is cleaned up
        with svc_b.lock:
            cb.flush()
            bob.remove(bob.length - 3, bob.length)
            cb.flush()
        pump(svc_b, cb)
        time.sleep(0.3)

        # reconnect: alice goes offline, keeps editing, returns
        with svc_a.lock:
            ca.disconnect()
            alice.insert_line_break(alice.length)
            alice.insert_text(alice.length, "offline flow addendum")
            alice.add_css_class(alice.length - 8, alice.length,
                                "muted")
        with svc_b.lock:
            bob.insert_text(bob.length, " (bob kept going)")
            cb.flush()
        pump(svc_b, cb)
        with svc_a.lock:
            ca.connect()
            ca.flush()
        pump(svc_a, ca)
        time.sleep(0.5)
        with svc_b.lock:
            cb.flush()
        pump(svc_b, cb)
        time.sleep(0.5)

        with svc_a.lock, svc_b.lock:
            ta, tb = alice.plain_text(), bob.plain_text()
            assert ta == tb, (ta, tb)
            assert alice.signature() == bob.signature()
            assert [(b.kind, b.heading, b.runs)
                    for b in alice.render()] == \
                [(b.kind, b.heading, b.runs) for b in bob.render()]
            assert alice.comments() == bob.comments()
            show("converged flowed document (both sessions "
                 "identical)", alice)
        print("OK: webflow-class session converged over the TCP "
              "service, including a pair-crossing removal and a "
              "reconnect.")
        with svc_a.lock:
            ca.close()
        with svc_b.lock:
            cb.close()
        svc_a.close()
        svc_b.close()
        return 0
    finally:
        os.kill(server.pid, signal.SIGKILL)
        server.wait()


if __name__ == "__main__":
    raise SystemExit(main())
