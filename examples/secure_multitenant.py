"""Secure multi-tenant deployment: token-gated ingress over the
PARTITIONED ordering pipeline, write + read-only clients.

Shows the service-plane features end to end: riddler-style tenancy
(signed claims tokens, scopes), the kafka-shaped partitioned pipeline
behind the front door, and a doc:read connection that observes without
joining the quorum.

Run: python examples/secure_multitenant.py
"""
import asyncio
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from fluidframework_tpu.drivers.socket_driver import (
    SocketDocumentService,
)
from fluidframework_tpu.loader import Container
from fluidframework_tpu.service import TenantManager, sign_token
from fluidframework_tpu.service.ingress import AlfredServer
from fluidframework_tpu.service.partitioning import PartitionedServer
from fluidframework_tpu.service.tenancy import SCOPE_READ


def main() -> int:
    # --- operator side: tenants + the partitioned service -------------
    tenants = TenantManager()
    acme = tenants.create_tenant("acme", "Acme Inc")
    server = AlfredServer(
        PartitionedServer(n_partitions=3), tenants=tenants)
    loop = asyncio.new_event_loop()
    started = threading.Event()

    async def run():
        await server.start()
        started.set()
        await server.serve_forever()

    threading.Thread(
        target=lambda: loop.run_until_complete(run()), daemon=True
    ).start()
    assert started.wait(10)
    print(f"secure service on 127.0.0.1:{server.port} "
          "(3 queue partitions, token-gated)")

    doc = "quarterly-report"

    # --- no token: rejected -------------------------------------------
    intruder = SocketDocumentService(
        "127.0.0.1", server.port, doc, timeout=5)
    try:
        intruder.connect_to_delta_stream("eve", lambda m: None)
        raise AssertionError("unauthenticated connect must fail")
    except PermissionError as e:
        print(f"unauthenticated connect rejected: {e}")
    intruder.close()

    # --- writer with a doc:write token --------------------------------
    writer_token = sign_token(acme.key, "acme", doc, "alice")
    svc_w = SocketDocumentService(
        "127.0.0.1", server.port, doc,
        tenant_id="acme", token=writer_token)
    with svc_w.lock:
        alice = Container.load(svc_w, client_id="alice")
        text = (alice.runtime.create_datastore("d")
                .create_channel("sharedstring", "body"))
        alice.flush()
        text.insert_text(0, "Q3 numbers are up.")
        alice.flush()

    # --- read-only observer (doc:read scope, never joins quorum) ------
    ro_token = sign_token(acme.key, "acme", doc, "auditor",
                          scopes=[SCOPE_READ])
    svc_r = SocketDocumentService(
        "127.0.0.1", server.port, doc,
        tenant_id="acme", token=ro_token, mode="read")
    seen = []
    svc_r.connect_to_delta_stream("auditor", seen.append)
    ops = svc_r.read_ops(0)
    print(f"auditor read {len(ops)} sequenced ops with a read token")
    assert any("Q3" in str(getattr(m, "contents", "")) for m in ops)

    # the read connection cannot pin the msn or write
    inner = server.local.svc
    assert "auditor" not in inner.orderer(doc).sequencer.clients

    # --- the queue really sequenced it --------------------------------
    part = inner.partition_of(doc)
    print(f"doc routed to partition {part}, committed offset "
          f"{inner.queue.committed(part)}")
    assert inner.queue.committed(part) >= 1

    with svc_w.lock:
        final = text.get_text()
    print(f"document body: {final!r}")
    svc_w.close()
    svc_r.close()
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
