"""Structured outline document: SharedTree with stored schema,
transactions, anchors and the editable surface (the tree-structured
document samples, e.g. examples/data-objects/webflow).

Run: python examples/tree_outline.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Container
from fluidframework_tpu.models.tree import (
    FieldSchema,
    NodeSchema,
    SchemaViolation,
    StoredSchema,
    node,
)
from fluidframework_tpu.service.local_server import LocalServer


def main() -> int:
    server = LocalServer()
    factory = LocalDocumentServiceFactory(server)
    a = Container.load(factory.create_document_service("outline"),
                       client_id="a")
    tree_a = (a.runtime.create_datastore("doc")
              .create_channel("sharedtree", "outline"))
    a.flush()

    # build via the typed editable surface
    root = tree_a.editable()
    root.field("sections").insert(0, [
        node("section", value="Intro"),
        node("section", value="Design"),
    ])
    sections = root.field("sections")
    sections[1].field("bullets").append([
        node("bullet", value="SoA segment tables"),
        node("bullet", value="doc-parallel mesh"),
    ])
    a.flush()

    # adopt a schema; from now on every client validates edits
    schema = StoredSchema(
        nodes={
            "section": NodeSchema("section", value="string", fields={
                "bullets": FieldSchema("sequence",
                                       allowed_types=("bullet",)),
            }),
            "bullet": NodeSchema("bullet", value="string"),
        },
        root_fields={"sections": FieldSchema(
            "sequence", allowed_types=("section",))},
    )
    tree_a.set_stored_schema(schema)
    a.flush()
    try:
        tree_a.insert_nodes(("sections",), 0, [node("rogue")])
        raise AssertionError("schema should have rejected this")
    except SchemaViolation as e:
        print(f"schema rejected: {e}")

    # anchor survives sibling edits; transaction commits atomically
    design = sections[1].anchor()
    with tree_a.transaction():
        sections.insert(0, [node("section", value="Abstract")])
        sections[0].field("bullets").append(
            [node("bullet", value="tl;dr")])
    a.flush()
    loc = tree_a.locate_anchor(design)
    print(f"'Design' slid to index {loc[-1]}")
    assert tree_a.get_field(("sections",))[loc[-1]]["value"] == "Design"

    b = Container.load(factory.create_document_service("outline"),
                       client_id="b")
    tree_b = b.runtime.get_datastore("doc").get_channel("outline")
    for i, section in enumerate(tree_b.editable().field("sections")):
        print(f"{i + 1}. {section.value}")
        for bullet in section.field("bullets"):
            print(f"   - {bullet.value}")
    assert tree_b.stored_schema is not None
    assert tree_a.signature() == tree_b.signature()
    a.close()
    b.close()
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
