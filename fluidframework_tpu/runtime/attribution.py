"""Attribution: who wrote what, keyed by sequence number.

Reference: packages/framework/attributor/src — ``Attributor``
(attributor.ts:79), ``OpStreamAttributor`` (:122) mapping op sequence
numbers to (user, timestamp); summary encoders with string interning +
compression (encoders.ts, lz4Encoder.ts — zlib here,
stringInterner.ts); runtime mixin (mixinAttributor.ts) — here a plain
observer attached to a Container.

Merge-tree integration: a segment's attribution key IS its insert seq
(attributionCollection.ts keys), so
``SharedString.attribution_at(pos)`` -> seq -> attributor lookup gives
per-character authorship with no extra per-segment state.
"""
from __future__ import annotations

import base64
import json
import zlib
from dataclasses import dataclass
from typing import TYPE_CHECKING, Optional

from ..protocol.messages import MessageType, SequencedMessage

if TYPE_CHECKING:  # pragma: no cover
    from ..loader.container import Container


@dataclass(frozen=True)
class AttributionInfo:
    user: str
    timestamp: float


class Attributor:
    """attributor.ts:79 — key -> AttributionInfo."""

    def __init__(self, entries: Optional[dict[int, AttributionInfo]] = None):
        self._entries: dict[int, AttributionInfo] = dict(entries or {})

    def get(self, key: int) -> Optional[AttributionInfo]:
        return self._entries.get(key)

    def record(self, key: int, info: AttributionInfo) -> None:
        self._entries[key] = info

    def __len__(self) -> int:
        return len(self._entries)

    # ---- summary encoding (encoders.ts: interning + compression)

    def encode(self) -> str:
        users = []
        index: dict[str, int] = {}
        rows = []
        for key in sorted(self._entries):
            info = self._entries[key]
            if info.user not in index:
                index[info.user] = len(users)
                users.append(info.user)
            rows.append([key, index[info.user], info.timestamp])
        payload = json.dumps({"users": users, "rows": rows})
        return base64.b64encode(
            zlib.compress(payload.encode("utf-8"))
        ).decode("ascii")

    @classmethod
    def decode(cls, data: str) -> "Attributor":
        payload = json.loads(
            zlib.decompress(base64.b64decode(data)).decode("utf-8")
        )
        users = payload["users"]
        return cls({
            key: AttributionInfo(users[uidx], ts)
            for key, uidx, ts in payload["rows"]
        })


class OpStreamAttributor(Attributor):
    """attributor.ts:122 — records every sequenced op's author as it
    streams through a container."""

    def __init__(self, container: "Container",
                 entries: Optional[dict[int, AttributionInfo]] = None):
        super().__init__(entries)
        self._off = container.on("processed", self._on_processed)

    def dispose(self) -> None:
        self._off()

    def _on_processed(self, msg: SequencedMessage) -> None:
        if msg.type == MessageType.OPERATION and msg.client_id:
            self.record(msg.sequence_number, AttributionInfo(
                user=msg.client_id, timestamp=msg.timestamp,
            ))
