"""Runtime layers: container/datastore orchestration, op lifecycle,
pending state, channel plugin boundary.

Reference analogue: packages/runtime/*, packages/loader.
"""
from .container_runtime import ContainerRuntime, PendingStateManager
from .datastore import DataStoreRuntime
from .shared_object import (
    ChannelFactory,
    ChannelRegistry,
    SharedObject,
    simple_factory,
)
from .summarizer import (
    OrderedClientElection,
    RunningSummarizer,
    SummarizerHeuristics,
    SummaryCollection,
    SummaryManager,
)

__all__ = [
    "ChannelFactory",
    "ChannelRegistry",
    "ContainerRuntime",
    "DataStoreRuntime",
    "OrderedClientElection",
    "PendingStateManager",
    "RunningSummarizer",
    "SharedObject",
    "SummarizerHeuristics",
    "SummaryCollection",
    "SummaryManager",
    "simple_factory",
]
