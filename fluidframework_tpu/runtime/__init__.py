"""Runtime layers: container/datastore orchestration, op lifecycle,
pending state, channel plugin boundary.

Reference analogue: packages/runtime/*, packages/loader.
"""
from .container_runtime import ContainerRuntime, PendingStateManager
from .datastore import DataStoreRuntime
from .shared_object import (
    ChannelFactory,
    ChannelRegistry,
    SharedObject,
    simple_factory,
)

__all__ = [
    "ChannelFactory",
    "ChannelRegistry",
    "ContainerRuntime",
    "DataStoreRuntime",
    "PendingStateManager",
    "SharedObject",
    "simple_factory",
]
