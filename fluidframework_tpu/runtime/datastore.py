"""Data-store runtime: hosts channels (DDS instances) and routes ops.

Reference: packages/runtime/datastore/src/dataStoreRuntime.ts
(``FluidDataStoreRuntime`` :101; inbound ``process`` :535 ->
``processChannelOp`` :947; outbound ``submitChannelOp`` :869).
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Any

from ..protocol.messages import SequencedMessage
from .shared_object import ChannelRegistry, SharedObject

if TYPE_CHECKING:
    from .container_runtime import ContainerRuntime


class _ChannelServices:
    """IChannelServices: binds one channel's submits to the container
    outbox with the right addressing envelope."""

    def __init__(self, datastore: "DataStoreRuntime", channel_id: str):
        self._datastore = datastore
        self._channel_id = channel_id

    def submit(self, contents: Any, metadata: Any = None) -> None:
        self._datastore.submit_channel_op(
            self._channel_id, contents, metadata
        )

    @property
    def client_id(self) -> str:
        return self._datastore.container.client_id

    @property
    def connected(self) -> bool:
        return self._datastore.container.connected

    @property
    def reconnect_epoch(self) -> int:
        return self._datastore.container.reconnect_epoch


class DataStoreRuntime:
    def __init__(self, container: "ContainerRuntime", datastore_id: str,
                 registry: ChannelRegistry, root: bool = True):
        self.container = container
        self.id = datastore_id
        self.registry = registry
        self.root = root  # GC root (aliased store)
        self.channels: dict[str, SharedObject] = {}

    # ------------------------------------------------------------------
    # channel lifecycle

    def create_channel(self, type_name: str, channel_id: str) -> SharedObject:
        if channel_id in self.channels:
            raise ValueError(f"channel {channel_id!r} exists")
        channel = self.registry.get(type_name).create(channel_id)
        self.channels[channel_id] = channel
        channel.connect(_ChannelServices(self, channel_id))
        # announce to remote containers (Attach op)
        self.container.submit_attach(
            self.id, channel_id, type_name, channel.summarize_core()
        )
        return channel

    def load_channel(self, type_name: str, channel_id: str,
                     summary: dict) -> SharedObject:
        channel = self.registry.get(type_name).load(channel_id, summary)
        self.channels[channel_id] = channel
        channel.connect(_ChannelServices(self, channel_id))
        return channel

    def get_channel(self, channel_id: str) -> SharedObject:
        route = f"/{self.id}/{channel_id}"
        if route in self.container.tombstones:
            raise KeyError(
                f"channel {route} is tombstoned (GC): unreferenced "
                "past the tombstone timeout"
            )
        return self.channels[channel_id]

    # ------------------------------------------------------------------
    # op routing

    def submit_channel_op(self, channel_id: str, contents: Any,
                          metadata: Any) -> None:
        """dataStoreRuntime.ts:869."""
        self.container.submit_op(
            self.id, channel_id, contents, metadata
        )

    def process(self, msg: SequencedMessage, channel_id: str, contents: Any,
                local: bool, local_op_metadata: Any) -> None:
        """dataStoreRuntime.ts:535 -> :947."""
        channel = self.channels[channel_id]
        inner = SequencedMessage(
            client_id=msg.client_id,
            sequence_number=msg.sequence_number,
            minimum_sequence_number=msg.minimum_sequence_number,
            client_sequence_number=msg.client_sequence_number,
            reference_sequence_number=msg.reference_sequence_number,
            type=msg.type,
            contents=contents,
            metadata=msg.metadata,
            timestamp=msg.timestamp,
        )
        if not local:
            # remote edits dirty the channel (local ones were counted
            # at submit time)
            channel.change_count += 1
        channel.process_core(inner, local, local_op_metadata)

    # ------------------------------------------------------------------
    # summary

    def summarize(self, skip_channels: frozenset = frozenset()
                  ) -> dict:
        """``skip_channels``: channel ids whose serialization is
        skipped in favor of a summary handle into the previous acked
        summary — the incremental path (SummaryType.Handle); the
        service storage expands them (service/storage.py)."""
        return {
            "root": self.root,
            "channels": {
                cid: (
                    {"__summary_handle__":
                     f"runtime/datastores/{self.id}/channels/{cid}"}
                    if cid in skip_channels else
                    {
                        "type": ch.type_name,
                        "content": ch.summarize_core(),
                    }
                )
                for cid, ch in self.channels.items()
            },
        }

    def load(self, summary: dict) -> None:
        for cid, entry in summary.get("channels", {}).items():
            self.load_channel(entry["type"], cid, entry["content"])
