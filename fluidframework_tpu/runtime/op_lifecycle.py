"""Outbound/inbound op lifecycle: batching marks, compression, chunking.

Reference: packages/runtime/container-runtime/src/opLifecycle/ —
``Outbox`` (outbox.ts:35), ``BatchManager`` (batchManager.ts:22),
``OpCompressor`` (opCompressor.ts:18, lz4 there; zlib here — same
boundary, different codec), ``OpSplitter`` (opSplitter.ts:18, chunked
ops for >1MB messages), ``OpDecompressor`` (:20) and
``RemoteMessageProcessor`` (remoteMessageProcessor.ts:11) as the
inbound inverse.

Stages compose outbound as: envelope -> compress (if large) -> split
(if still large); inbound: reassemble chunks -> decompress -> decode.
The wire form is the JSON encoding from ``protocol.serialization`` so
payload sizes are measured on real serialized bytes.
"""
from __future__ import annotations

import base64
import json
import uuid
import zlib
from typing import Any, Optional

from ..obs import metrics as obs_metrics

# the ledger -> histogram bridge: every acked op's per-hop deltas
# feed ONE labelled histogram, so SLO objectives can bind to a
# single hop's latency budget (per-hop budgets rather than one
# end-to-end number — the collab-window framing). Label values are
# the CANONICAL hop names (bounded vocabulary by construction).
_HOP_MS = obs_metrics.REGISTRY.histogram(
    "op_hop_ms",
    "per-hop submit→ack latency attribution from the op ledger",
    labelnames=("hop",))
_SUBMIT_ACK_MS = obs_metrics.REGISTRY.histogram(
    "op_submit_ack_ms",
    "full submit→ack wall latency of ledgered ops")
# the replicated plane's share of the critical path: repl:forward ->
# repl:quorum_ack on every acked op that crossed the quorum barrier
# (fed from the same ledger bridge, so the quorum wait is its own
# series instead of silently inflating the sequencer-ticket hop)
_QUORUM_WAIT_MS = obs_metrics.REGISTRY.histogram(
    "repl_quorum_wait_ms",
    "repl:forward→repl:quorum_ack wait of ledgered replicated ops")


def _encode(envelope: dict) -> str:
    from ..protocol.serialization import encode_contents
    return json.dumps(encode_contents(envelope))


def _decode(payload: str) -> dict:
    from ..protocol.serialization import decode_contents
    return decode_contents(json.loads(payload))


class OpCompressor:
    """Compress large op envelopes (opCompressor.ts:18)."""

    def __init__(self, min_size: int = 4 * 1024):
        self.min_size = min_size

    def maybe_compress(self, envelope: dict) -> dict:
        try:
            payload = _encode(envelope)
        except TypeError:
            return envelope  # not wire-encodable: leave in-proc form
        return self.compress_encoded(envelope, payload)[0]

    def compress_encoded(self, envelope: dict, payload: str
                         ) -> tuple[dict, str]:
        """Same, reusing an already-encoded payload; returns the
        (possibly new) envelope and its encoding."""
        if len(payload) < self.min_size:
            return envelope, payload
        data = base64.b64encode(
            zlib.compress(payload.encode("utf-8"))
        ).decode("ascii")
        if len(data) >= len(payload):
            return envelope, payload  # incompressible; keep plain
        compressed = {"kind": "compressed", "data": data}
        return compressed, _encode(compressed)


class OpDecompressor:
    """Inbound inverse (opDecompressor.ts:20)."""

    @staticmethod
    def decompress(envelope: dict) -> dict:
        if envelope.get("kind") != "compressed":
            return envelope
        payload = zlib.decompress(
            base64.b64decode(envelope["data"])
        ).decode("utf-8")
        return _decode(payload)


class OpSplitter:
    """Split oversized envelopes into chunked ops (opSplitter.ts:18).
    Each chunk rides its own message; the op takes effect at the final
    chunk's sequence number."""

    def __init__(self, chunk_size: int = 768 * 1024):
        self.chunk_size = chunk_size

    def split(self, envelope: dict) -> list[dict]:
        try:
            payload = _encode(envelope)
        except TypeError:
            return [envelope]  # not wire-encodable: leave in-proc form
        return self.split_encoded(envelope, payload)

    def split_encoded(self, envelope: dict, payload: str) -> list[dict]:
        if len(payload) <= self.chunk_size:
            return [envelope]
        chunk_id = uuid.uuid4().hex
        pieces = [
            payload[i:i + self.chunk_size]
            for i in range(0, len(payload), self.chunk_size)
        ]
        return [
            {
                "kind": "chunk",
                "chunkId": chunk_id,
                "index": i,
                "total": len(pieces),
                "data": piece,
            }
            for i, piece in enumerate(pieces)
        ]


class ChunkReassembler:
    """Collects chunk pieces per (client, chunkId); returns the
    original envelope when the final piece arrives."""

    def __init__(self) -> None:
        self._buffers: dict[tuple, list[Optional[str]]] = {}

    def add(self, client_id: str, envelope: dict) -> Optional[dict]:
        key = (client_id, envelope["chunkId"])
        buf = self._buffers.setdefault(key, [None] * envelope["total"])
        buf[envelope["index"]] = envelope["data"]
        if any(piece is None for piece in buf):
            return None
        del self._buffers[key]
        return _decode("".join(buf))


class RemoteMessageProcessor:
    """Inbound pipeline (remoteMessageProcessor.ts:11): reassemble,
    then decompress. Returns the logical envelope, or None while a
    chunked op is still incomplete."""

    def __init__(self) -> None:
        self._reassembler = ChunkReassembler()
        self._decompressor = OpDecompressor()

    def process(self, client_id: str, envelope: Any) -> Optional[dict]:
        if isinstance(envelope, dict) and envelope.get("kind") == "chunk":
            envelope = self._reassembler.add(client_id, envelope)
            if envelope is None:
                return None
        if isinstance(envelope, dict):
            envelope = self._decompressor.decompress(envelope)
        return envelope


def stage_outbound(envelope: dict, compressor: OpCompressor,
                   splitter: OpSplitter) -> list[dict]:
    """Outbound staging with a single wire encoding shared by both
    stages: encode once -> compress if beneficial -> chunk if large."""
    try:
        payload = _encode(envelope)
    except TypeError:
        return [envelope]  # in-proc-only payload: send as-is
    envelope, payload = compressor.compress_encoded(envelope, payload)
    return splitter.split_encoded(envelope, payload)


class OpLatencyLedger:
    """Bounded per-op submit→ack latency attribution.

    The container feeds it when one of its OWN ops comes back
    sequenced: the op's full trace (submit, driver-send, ingress,
    sequencer ticket, fanout, deliver, ack — whatever hops the path
    stamped) is kept per clientSequenceNumber, newest ``capacity``
    entries retained. This is the per-op half of observability; the
    metrics registry keeps the aggregates."""

    def __init__(self, capacity: int = 128):
        self.capacity = capacity
        # csn -> entry; insertion-ordered so eviction drops the oldest
        self._entries: dict[int, dict] = {}

    def record(self, csn: int, sequence_number: int,
               traces: list) -> dict:
        from ..obs.trace import breakdown, total_ms

        entry = {
            "clientSequenceNumber": csn,
            "sequenceNumber": sequence_number,
            "traces": list(traces),
            "hops": breakdown(traces),
            "total_ms": total_ms(traces),
        }
        # ledger -> histogram bridge: the per-op record doubles as
        # the aggregate sample (one observe per hop; hop names come
        # from the canonical table, so the label set stays bounded)
        for hop in entry["hops"]:
            _HOP_MS.labels(hop=hop["hop"]).observe(hop["delta_ms"])
        if entry["hops"]:
            _SUBMIT_ACK_MS.observe(entry["total_ms"])
        forward = next((t.timestamp for t in traces
                        if t.service == "repl"
                        and t.action == "forward"), None)
        acked = [t.timestamp for t in traces
                 if t.service == "repl" and t.action == "quorum_ack"]
        if forward is not None and acked:
            _QUORUM_WAIT_MS.observe((max(acked) - forward) * 1000.0)
        self._entries[csn] = entry
        while len(self._entries) > self.capacity:
            self._entries.pop(next(iter(self._entries)))
        return entry

    def get(self, csn: Optional[int] = None) -> Optional[dict]:
        """The entry for ``csn``, or the newest one when omitted."""
        if csn is not None:
            return self._entries.get(csn)
        if not self._entries:
            return None
        return self._entries[next(reversed(self._entries))]

    def format(self, csn: Optional[int] = None) -> str:
        from ..obs.trace import format_breakdown

        entry = self.get(csn)
        if entry is None:
            return "(no acked op recorded)"
        return (
            f"op csn={entry['clientSequenceNumber']} "
            f"seq={entry['sequenceNumber']} "
            f"({entry['total_ms']:.3f} ms submit→ack)\n"
            + format_breakdown(entry["traces"])
        )

    def summary(self) -> dict:
        """Per-hop mean/max delta over the retained entries."""
        agg: dict[str, list[float]] = {}
        for entry in self._entries.values():
            for hop in entry["hops"]:
                agg.setdefault(hop["hop"], []).append(hop["delta_ms"])
        return {
            hop: {
                "count": len(ds),
                "mean_ms": sum(ds) / len(ds),
                "max_ms": max(ds),
            }
            for hop, ds in agg.items()
        }

    def __len__(self) -> int:
        return len(self._entries)


# batch boundary marks moved to the protocol layer (they are a wire
# contract the drivers also consume); re-exported here for the
# runtime-side users
from ..protocol.constants import batch_flag, mark_batch  # noqa: E402,F401
