"""Fluid handles: serializable cross-object references.

Reference: packages/common/core-interfaces (``IFluidHandle``) — handles
are how one DDS's data points at another datastore/channel/blob, and
they are the edges of the GC reference graph (SURVEY §2.1: "handles =
cross-object references, needed for GC").

A handle is just an absolute route (``/datastore``, ``/datastore/channel``
or ``/_blobs/<id>``) plus equality; the wire encoding is the tagged dict
``{"__handle__": route}`` (protocol.serialization round-trips it).
"""
from __future__ import annotations

from typing import Any


class FluidHandle:
    __slots__ = ("route",)

    def __init__(self, route: str):
        assert route.startswith("/"), f"handle route must be absolute: {route!r}"
        self.route = route

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, FluidHandle) and other.route == self.route

    def __hash__(self) -> int:
        return hash(("FluidHandle", self.route))

    def __repr__(self) -> str:
        return f"FluidHandle({self.route!r})"


def handle_to(*parts: str) -> FluidHandle:
    return FluidHandle("/" + "/".join(parts))


def collect_handles(value: Any) -> list[str]:
    """All handle routes reachable inside a JSON-ish value — the
    outbound GC edges of a stored value (getGCData leaf scan)."""
    out: list[str] = []
    _collect(value, out)
    return out


def _collect(value: Any, out: list[str]) -> None:
    if isinstance(value, FluidHandle):
        out.append(value.route)
    elif isinstance(value, dict):
        for v in value.values():
            _collect(v, out)
    elif isinstance(value, (list, tuple)):
        for v in value:
            _collect(v, out)
