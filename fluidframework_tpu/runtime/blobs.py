"""Attachment blobs: content-addressed binary payloads with handles.

Reference: packages/runtime/container-runtime/src/blobManager.ts
(``BlobManager`` :118) — upload, dedup by content, handle-based
referencing, GC of unreferenced blobs.

Divergence: the reference uploads blob content to storage out-of-band
and sends only the storage id in the BlobAttach op; here the content
rides the op itself (base64) — the op-lifecycle compressor/splitter
handles size, and every harness (runtime mocks, local server, replay)
gets blobs for free. The handle namespace (``/_blobs/<sha>``), dedup,
and GC semantics match the reference.
"""
from __future__ import annotations

import base64
import hashlib
from typing import TYPE_CHECKING, Optional

from .handles import FluidHandle

if TYPE_CHECKING:  # pragma: no cover
    from .container_runtime import ContainerRuntime

BLOB_ROUTE_PREFIX = "/_blobs/"


class BlobManager:
    def __init__(self, runtime: "ContainerRuntime"):
        self.runtime = runtime
        self._blobs: dict[str, bytes] = {}

    # ---- public API

    def create_blob(self, data: bytes) -> FluidHandle:
        """Store + announce a blob; returns its handle. Content
        dedup: the same bytes always yield the same handle."""
        blob_id = hashlib.sha256(data).hexdigest()[:32]
        route = BLOB_ROUTE_PREFIX + blob_id
        if blob_id not in self._blobs:
            self._blobs[blob_id] = data
            self.runtime.submit_blob_attach(
                blob_id, base64.b64encode(data).decode("ascii")
            )
        # re-creating revives a tombstoned blob immediately (the next
        # GC run observes the new reference and agrees)
        self.runtime.tombstones.discard(route)
        if self.runtime.gc is not None:
            self.runtime.gc.tombstones.discard(route)
        return FluidHandle(route)

    def get_blob(self, handle_or_id) -> bytes:
        blob_id = self._to_id(handle_or_id)
        route = BLOB_ROUTE_PREFIX + blob_id
        if route in self.runtime.tombstones:
            raise KeyError(f"blob {blob_id} is tombstoned (GC)")
        return self._blobs[blob_id]

    def has_blob(self, handle_or_id) -> bool:
        return self._to_id(handle_or_id) in self._blobs

    def ids(self) -> tuple[str, ...]:
        return tuple(self._blobs)

    @staticmethod
    def _to_id(handle_or_id) -> str:
        if isinstance(handle_or_id, FluidHandle):
            assert handle_or_id.route.startswith(BLOB_ROUTE_PREFIX)
            return handle_or_id.route[len(BLOB_ROUTE_PREFIX):]
        return handle_or_id

    # ---- runtime integration

    def process_attach(self, blob_id: str, data_b64: str) -> None:
        self._blobs.setdefault(blob_id, base64.b64decode(data_b64))

    def delete_blob(self, blob_id: str) -> bool:
        return self._blobs.pop(blob_id, None) is not None

    def summarize(self) -> dict:
        return {
            blob_id: base64.b64encode(data).decode("ascii")
            for blob_id, data in self._blobs.items()
        }

    def load(self, summary: dict) -> None:
        for blob_id, data_b64 in summary.items():
            self._blobs[blob_id] = base64.b64decode(data_b64)
