"""Summarizer subsystem: election, heuristics, ack tracking.

Reference: packages/runtime/container-runtime/src —
- ``OrderedClientElection`` (orderedClientElection.ts:262, collection
  :77) + ``summarizerClientElection.ts:161``: the oldest eligible
  (write-mode) client is the elected summarizer; election advances
  when it leaves.
- ``SummaryManager`` (summaryManager.ts:72): per-client observer that
  runs a summarizer when its own client wins the election. (The
  reference spawns a hidden second container for isolation; in-proc we
  run against the live container — same protocol traffic.)
- ``RunningSummarizer`` (runningSummarizer.ts:53) with heuristics
  (summarizerHeuristics.ts): summarize after N ops or T seconds,
  only when quiescent; retry on nack.
- ``SummaryCollection`` (summaryCollection.ts:206): watches
  summarize/ack/nack traffic for everyone.
"""
from __future__ import annotations

import time
from typing import TYPE_CHECKING, Optional

from ..protocol.messages import MessageType, SequencedMessage
from ..utils.events import EventEmitter

if TYPE_CHECKING:  # pragma: no cover
    from ..loader.container import Container


class OrderedClientElection(EventEmitter):
    """orderedClientElection.ts:262 — eligible clients in join order;
    the head is elected."""

    def __init__(self) -> None:
        super().__init__()
        self._clients: list[str] = []  # eligible, join order

    @property
    def elected(self) -> Optional[str]:
        return self._clients[0] if self._clients else None

    @property
    def eligible(self) -> tuple[str, ...]:
        return tuple(self._clients)

    def add_client(self, client_id: str, eligible: bool = True) -> None:
        if not eligible or client_id in self._clients:
            return
        was = self.elected
        self._clients.append(client_id)
        if self.elected != was:
            self.emit("electedChange", self.elected)

    def remove_client(self, client_id: str) -> None:
        if client_id not in self._clients:
            return
        was = self.elected
        self._clients.remove(client_id)
        if self.elected != was:
            self.emit("electedChange", self.elected)


class SummaryCollection(EventEmitter):
    """summaryCollection.ts:206 — everyone's view of summary traffic."""

    def __init__(self) -> None:
        super().__init__()
        self.last_ack: Optional[dict] = None     # {proposal, handle}
        self.pending_proposals: dict[int, dict] = {}

    @property
    def last_ack_seq(self) -> int:
        return self.last_ack["summaryProposal"] if self.last_ack else 0

    def process(self, msg: SequencedMessage) -> None:
        if msg.type == MessageType.SUMMARIZE:
            self.pending_proposals[msg.sequence_number] = (
                msg.contents or {}
            )
            self.emit("summarize", msg.sequence_number)
        elif msg.type == MessageType.SUMMARY_ACK:
            ack = msg.contents or {}
            self.pending_proposals.pop(ack.get("summaryProposal"), None)
            self.last_ack = ack
            self.emit("summaryAck", ack)
        elif msg.type == MessageType.SUMMARY_NACK:
            nack = msg.contents or {}
            self.pending_proposals.pop(nack.get("summaryProposal"), None)
            self.emit("summaryNack", nack)


class SummarizerHeuristics:
    """summarizerHeuristics.ts — summarize after ``max_ops`` ops or
    ``max_time_s`` seconds since the last acked summary."""

    def __init__(self, max_ops: int = 100,
                 max_time_s: Optional[float] = None,
                 clock=time.monotonic):
        self.max_ops = max_ops
        self.max_time_s = max_time_s
        self._clock = clock
        self.ops_since_summary = 0
        self._last_summary_time = clock()

    def record_op(self) -> None:
        self.ops_since_summary += 1

    def record_summary(self) -> None:
        self.ops_since_summary = 0
        self._last_summary_time = self._clock()

    def should_summarize(self) -> bool:
        if self.ops_since_summary >= self.max_ops:
            return True
        return (
            self.max_time_s is not None
            and self.ops_since_summary > 0
            and self._clock() - self._last_summary_time >= self.max_time_s
        )


class RunningSummarizer(EventEmitter):
    """runningSummarizer.ts:53 — drives summaries on one (elected)
    client: heuristics decide when; a summary is only attempted while
    quiescent (no local pending ops) and while no prior attempt is
    outstanding; nacks retry on the next op."""

    def __init__(self, container: "Container",
                 heuristics: Optional[SummarizerHeuristics] = None):
        super().__init__()
        self.container = container
        self.heuristics = heuristics or SummarizerHeuristics()
        self.attempt_pending = False
        self._attempt_proposal: Optional[int] = None
        self.summaries_produced = 0
        # sticky auth failure: the upload plane rejected our token for
        # write scope — retrying every tick cannot succeed, and
        # re-raising would unwind into the driver's dispatch pump and
        # kill delta processing for every document on the connection
        self.auth_failed = False

    def on_op(self, msg: SequencedMessage) -> None:
        if msg.type == MessageType.SUMMARIZE:
            if (self.attempt_pending and self._attempt_proposal is None
                    and msg.client_id == self.container.client_id):
                # our in-flight attempt got its proposal number
                self._attempt_proposal = msg.sequence_number
            return
        if msg.type == MessageType.SUMMARY_ACK:
            ack = msg.contents or {}
            # ANY acked summary refreshes the document's summary state
            self.heuristics.record_summary()
            if (self._attempt_proposal is not None
                    and ack.get("summaryProposal")
                    == self._attempt_proposal):
                self.attempt_pending = False
                self._attempt_proposal = None
                self.summaries_produced += 1
                self.emit("summarized", ack)
            return
        if msg.type == MessageType.SUMMARY_NACK:
            nack = msg.contents or {}
            if (self._attempt_proposal is not None
                    and nack.get("summaryProposal")
                    == self._attempt_proposal):
                self.attempt_pending = False  # retry on a later tick
                self._attempt_proposal = None
            return
        if msg.type == MessageType.OPERATION:
            self.heuristics.record_op()
        self.maybe_summarize()

    def tick(self) -> None:
        """Re-evaluate outside the op stream: hosts call this
        periodically so the time heuristic (and attempts deferred
        while dirty) fire on quiet documents — the in-proc stand-in
        for the reference's summarizer timers."""
        self.maybe_summarize()

    def maybe_summarize(self) -> None:
        if self.auth_failed or self.attempt_pending \
                or not self.heuristics.should_summarize():
            return
        if self.container.runtime.is_dirty or not self.container.connected:
            return  # wait for quiescence (summarize requires it)
        self.attempt_pending = True
        try:
            self.container.summarize()
        except PermissionError as e:
            # surfaced by Container.summarize (ADVICE r4) — on the
            # AUTO path there is no caller to catch it: record it
            # loudly, stop attempting (sticky until re-election /
            # reconnect builds a new summarizer), keep the pump alive
            self.attempt_pending = False
            self.auth_failed = True
            self.container.mc.logger.send_error_event(
                "summarizeAuthFailed", error=e,
            )
            self.emit("authFailed", e)
        except Exception:
            # no proposal was submitted, so no ack/nack will ever
            # clear the flag — reset it or summaries stop forever
            self.attempt_pending = False
            raise


class SummaryManager(EventEmitter):
    """summaryManager.ts:72 — each client runs one of these; the one
    whose client wins the election drives summaries."""

    def __init__(self, container: "Container",
                 heuristics_factory=SummarizerHeuristics):
        super().__init__()
        self.container = container
        self.election = OrderedClientElection()
        self.collection = SummaryCollection()
        self._heuristics_factory = heuristics_factory
        self.running: Optional[RunningSummarizer] = None
        # seed from the quorum: members who joined before this manager
        # existed (catch-up processed their joins already); dict order
        # is join order
        for cid, detail in container.protocol.quorum.members.items():
            self.election.add_client(cid, eligible=detail.mode == "write")
        self._reconcile_role()
        self._off = container.on("processed", self._on_processed)
        self.disposed = False

    def dispose(self) -> None:
        """Detach from the container (the reference SummaryManager is
        IDisposable); safe to call repeatedly."""
        if not self.disposed:
            self._off()
            self.running = None
            self.disposed = True

    def tick(self) -> None:
        """Periodic re-evaluation for time-based heuristics and
        deferred attempts (see RunningSummarizer.tick)."""
        if self.running is not None:
            self.running.tick()

    @property
    def is_summarizer(self) -> bool:
        return self.running is not None

    def _on_processed(self, msg: SequencedMessage) -> None:
        if msg.type == MessageType.CLIENT_JOIN:
            detail = msg.contents
            self.election.add_client(
                detail.client_id, eligible=detail.mode == "write"
            )
        elif msg.type == MessageType.CLIENT_LEAVE:
            self.election.remove_client(msg.contents)
        self.collection.process(msg)
        self._reconcile_role()
        if self.running is not None:
            self.running.on_op(msg)

    def _reconcile_role(self) -> None:
        elected_us = self.election.elected == self.container.client_id
        if elected_us and self.running is None:
            self.running = RunningSummarizer(
                self.container, self._heuristics_factory()
            )
            self.emit("summarizerStart")
        elif not elected_us and self.running is not None:
            self.running = None
            self.emit("summarizerStop")
