"""Distributed garbage collection: mark, sweep-ready, tombstone.

Reference:
- ``runGarbageCollection`` (packages/runtime/garbage-collector/src/
  garbageCollector.ts:15): BFS over the handle-reference graph.
- ``GarbageCollector`` (packages/runtime/container-runtime/src/
  garbageCollection.ts:340): per-node unreferenced timestamps (mark
  phase), sweep-ready detection after a configurable timeout
  (gcSweepReadyUsageDetection.ts), and tombstones
  (garbageCollectionTombstoneUtils.ts) — tombstoned routes fail on
  access before they are deleted, surfacing use-after-unreference bugs.

GC runs on the summarizer client alongside summaries (§3.4: GC data is
collected with ``getGCData`` during the summary walk) and its results
ride the summary so every client agrees on unreferenced state.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Optional

if TYPE_CHECKING:  # pragma: no cover
    from .container_runtime import ContainerRuntime


def run_garbage_collection(
    graph: dict[str, list[str]], roots: list[str]
) -> tuple[set[str], set[str]]:
    """(referenced, unreferenced) node sets via BFS from ``roots``
    (garbage-collector/src/garbageCollector.ts:15)."""
    referenced: set[str] = set()
    queue = deque(r for r in roots if r in graph)
    referenced.update(queue)
    while queue:
        node = queue.popleft()
        for target in graph.get(node, ()):  # outbound routes
            if target in graph and target not in referenced:
                referenced.add(target)
                queue.append(target)
    return referenced, set(graph) - referenced


@dataclass
class GCResult:
    referenced: set[str] = field(default_factory=set)
    unreferenced: set[str] = field(default_factory=set)
    sweep_ready: set[str] = field(default_factory=set)
    tombstoned: set[str] = field(default_factory=set)
    deleted: set[str] = field(default_factory=set)


class GarbageCollector:
    """garbageCollection.ts:340 — tracks unreferenced-since timestamps
    across GC runs; nodes unreferenced longer than
    ``tombstone_timeout_s`` become tombstones (access traps), and past
    ``sweep_timeout_s`` they are sweep-ready (deletable)."""

    def __init__(self, runtime: "ContainerRuntime",
                 tombstone_timeout_s: float = 7 * 24 * 3600,
                 sweep_timeout_s: Optional[float] = None,
                 clock=None):
        import time as _time
        self.runtime = runtime
        self.tombstone_timeout_s = tombstone_timeout_s
        self.sweep_timeout_s = (
            sweep_timeout_s if sweep_timeout_s is not None
            else tombstone_timeout_s + 24 * 3600
        )
        self._clock = clock or _time.time
        # route -> timestamp first observed unreferenced
        self.unreferenced_since: dict[str, float] = {}
        self.tombstones: set[str] = set()
        runtime.gc = self  # summaries now carry this collector's state
        if runtime._loaded_gc_state is not None:
            self.load(runtime._loaded_gc_state)

    def collect(self, sweep: bool = False) -> GCResult:
        """One mark (+ optional sweep) pass over the live runtime."""
        now = self._clock()
        graph, roots = self.runtime.get_gc_graph()
        referenced, unreferenced = run_garbage_collection(graph, roots)

        # mark phase: maintain unreferenced-since timestamps
        for route in list(self.unreferenced_since):
            if route in referenced or route not in graph:
                del self.unreferenced_since[route]  # revived or gone
                self.tombstones.discard(route)
        for route in unreferenced:
            self.unreferenced_since.setdefault(route, now)

        result = GCResult(referenced=referenced,
                          unreferenced=unreferenced)
        for route, since in self.unreferenced_since.items():
            age = now - since
            if age >= self.tombstone_timeout_s:
                self.tombstones.add(route)
            if age >= self.sweep_timeout_s:
                result.sweep_ready.add(route)
        result.tombstoned = set(self.tombstones)

        if sweep and result.sweep_ready:
            for route in sorted(result.sweep_ready, reverse=True):
                if self.runtime.delete_route(route):
                    result.deleted.add(route)
                self.unreferenced_since.pop(route, None)
                self.tombstones.discard(route)
        self.runtime.set_tombstones(self.tombstones)
        return result

    # ---- summary persistence (GC state rides the summary, §3.4)

    def snapshot(self) -> dict:
        return {
            "unreferencedSince": dict(self.unreferenced_since),
            "tombstones": sorted(self.tombstones),
        }

    def load(self, state: dict) -> None:
        self.unreferenced_since = dict(state.get("unreferencedSince", {}))
        self.tombstones = set(state.get("tombstones", []))
        self.runtime.set_tombstones(self.tombstones)
