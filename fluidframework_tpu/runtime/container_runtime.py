"""Container runtime: datastore management, outbound batching, pending
state, reconnect replay.

Reference: packages/runtime/container-runtime/src/containerRuntime.ts
(``ContainerRuntime`` :631; inbound ``process`` :1701; outbound
``submitDataStoreOp`` :2549 -> ``Outbox``/``BatchManager``
(opLifecycle/outbox.ts:35, batchManager.ts:22); ``flush`` :1852;
``replayPendingStates`` :1573 with ``PendingStateManager``
(pendingStateManager.ts:75); ``orderSequentially`` :1860).
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Optional

from ..obs import metrics as obs_metrics
from ..protocol.messages import SequencedMessage
from ..utils.events import EventEmitter
from .datastore import DataStoreRuntime
from .op_lifecycle import (
    OpCompressor,
    OpSplitter,
    RemoteMessageProcessor,
    mark_batch,
    stage_outbound,
)
from .shared_object import ChannelRegistry

_RESUBMITS = obs_metrics.REGISTRY.counter(
    "container_resubmits_total",
    "pending ops replayed (rebased) on reconnect")


@dataclass
class PendingOp:
    """One locally-submitted op awaiting its ack
    (pendingStateManager.ts pending message). ``kind`` is "op" for
    channel ops, "attach" for channel-attach announcements
    (ContainerMessageType.Attach, containerRuntime.ts:1701 switch)."""

    datastore_id: str
    channel_id: str
    contents: Any
    metadata: Any
    kind: str = "op"


class PendingStateManager:
    """Exactly-once resubmit across reconnects
    (pendingStateManager.ts:75): a deque of pending ops; acks pop the
    head; on reconnect every entry replays through its channel's
    ``resubmit_core`` (the rebase hook)."""

    def __init__(self) -> None:
        self._pending: deque[PendingOp] = deque()

    def on_submit(self, op: PendingOp) -> None:
        self._pending.append(op)

    def on_local_ack(self, msg: SequencedMessage) -> PendingOp:
        assert self._pending, "ack with no pending ops"
        return self._pending.popleft()

    def drain(self) -> list[PendingOp]:
        out = list(self._pending)
        self._pending.clear()
        return out

    @property
    def count(self) -> int:
        return len(self._pending)


class ContainerRuntime(EventEmitter):
    """One client's container: datastores + op lifecycle.

    The host (loader/driver/test session) wires ``submit_fn`` — called
    with the container-level op contents for each outbound message —
    and feeds inbound sequenced messages to ``process``.
    """

    def __init__(self, registry: ChannelRegistry,
                 submit_fn: Optional[Callable[[Any, Any], None]] = None):
        super().__init__()
        self.registry = registry
        self._submit_fn = submit_fn
        self.datastores: dict[str, DataStoreRuntime] = {}
        self.pending = PendingStateManager()
        self._outbox: list[PendingOp] = []
        self.client_id: str = ""
        self.connected = False
        self.reconnect_epoch = 0  # bumped on every reconnect
        # op lifecycle stages (opLifecycle/): outbound compress+chunk,
        # inbound reassemble+decompress
        self.compressor = OpCompressor()
        self.splitter = OpSplitter()
        self._inbound = RemoteMessageProcessor()
        # blobs + GC (blobManager.ts:118, garbageCollection.ts:340)
        from .blobs import BlobManager
        self.blobs = BlobManager(self)
        self.tombstones: set[str] = set()
        # GC state: set by an attached GarbageCollector, or loaded
        # from a summary produced by the (summarizer's) collector —
        # this is how GC results reach every replica (§3.4)
        self.gc: Any = None
        self._loaded_gc_state: Optional[dict] = None

    # ------------------------------------------------------------------
    # wiring

    def set_submit_fn(self, fn: Callable[[Any, Any], None]) -> None:
        self._submit_fn = fn

    def set_connection_state(self, connected: bool,
                             client_id: str = "") -> None:
        """containerRuntime.ts:1307 setConnectionState; on reconnect,
        replay pending states (:1573)."""
        was_connected = self.connected
        self.connected = connected
        if client_id:
            self.client_id = client_id
        if connected:
            # (re-)announce identity to every channel — channels created
            # by load() connected before the client id was known
            for ds in self.datastores.values():
                for channel in ds.channels.values():
                    channel._on_connect()
        if connected and not was_connected and self.pending.count:
            self._replay_pending()
        self.emit("connected" if connected else "disconnected")

    # ------------------------------------------------------------------
    # datastores

    def create_datastore(self, datastore_id: str,
                         root: bool = True) -> DataStoreRuntime:
        """``root=True`` (aliased in the reference) makes the store a
        GC root; non-root stores stay alive only while a handle to
        them (or a channel of theirs) is stored somewhere reachable."""
        if datastore_id in self.datastores:
            raise ValueError(f"datastore {datastore_id!r} exists")
        ds = DataStoreRuntime(self, datastore_id, self.registry, root=root)
        self.datastores[datastore_id] = ds
        return ds

    def get_datastore(self, datastore_id: str) -> DataStoreRuntime:
        route = f"/{datastore_id}"
        if route in self.tombstones:
            raise KeyError(
                f"datastore {datastore_id!r} is tombstoned (GC): "
                "it has been unreferenced past the tombstone timeout"
            )
        return self.datastores[datastore_id]

    # ------------------------------------------------------------------
    # outbound (submitDataStoreOp :2549 -> Outbox -> flush :1852)

    def submit_op(self, datastore_id: str, channel_id: str, contents: Any,
                  metadata: Any = None) -> None:
        op = PendingOp(datastore_id, channel_id, contents, metadata)
        self._outbox.append(op)

    def submit_attach(self, datastore_id: str, channel_id: str,
                      channel_type: str, summary: dict) -> None:
        """Announce a locally-created channel so remote containers can
        materialize it (the Attach op: a new channel's type + initial
        snapshot travel in the op stream)."""
        ds = self.datastores[datastore_id]
        self._outbox.append(PendingOp(
            datastore_id, channel_id,
            {"channelType": channel_type, "summary": summary,
             "root": ds.root},
            None, kind="attach",
        ))

    def flush(self) -> int:
        """Send every batched op (outbox.ts:102). Returns count sent.

        Drains atomically up front: with an in-proc synchronous service
        a submit can deliver (and re-enter flush) before this call
        returns, and the op must not be sent twice."""
        ops, self._outbox = self._outbox, []
        # Stage every wire message first (compress -> chunk), so batch
        # boundary marks land on the true first/last wire message.
        staged: list[tuple[dict, Any]] = []
        for op in ops:
            self.pending.on_submit(op)
            envelope = {
                "kind": op.kind,
                "address": op.datastore_id,
                "channel": op.channel_id,
                "contents": op.contents,
            }
            for wire in stage_outbound(
                envelope, self.compressor, self.splitter
            ):
                staged.append((wire, op.metadata))
        if len(staged) > 1:
            staged[0] = (staged[0][0], mark_batch(staged[0][1], True))
            staged[-1] = (staged[-1][0], mark_batch(staged[-1][1], False))
        if self._submit_fn is not None:
            for wire, metadata in staged:
                self._submit_fn(wire, metadata)
        return len(ops)

    def order_sequentially(self, callback: Callable[[], None]) -> None:
        """containerRuntime.ts:1860: run ``callback``, then flush its
        ops as one batch."""
        callback()
        self.flush()

    def submit_blob_attach(self, blob_id: str, data_b64: str) -> None:
        """BlobAttach op (ContainerMessageType.BlobAttach)."""
        self._outbox.append(PendingOp(
            "", "", {"id": blob_id, "data": data_b64}, None,
            kind="blobAttach",
        ))

    # ------------------------------------------------------------------
    # GC surface (garbageCollection.ts:340 consumes this)

    def get_gc_graph(self) -> tuple[dict[str, list[str]], list[str]]:
        """(node -> outbound routes, roots). Nodes: datastores,
        channels, blobs. A channel references its parent store (child
        keeps parent alive, as in the reference's node hierarchy)."""
        graph: dict[str, list[str]] = {}
        roots: list[str] = []
        for ds_id, ds in self.datastores.items():
            ds_route = f"/{ds_id}"
            graph[ds_route] = [
                f"{ds_route}/{cid}" for cid in ds.channels
            ]
            if ds.root:
                roots.append(ds_route)
            for cid, channel in ds.channels.items():
                graph[f"{ds_route}/{cid}"] = (
                    channel.gc_routes() + [ds_route]
                )
        for blob_id in self.blobs.ids():
            graph[f"/_blobs/{blob_id}"] = []
        return graph, roots

    def set_tombstones(self, tombstones: set[str]) -> None:
        self.tombstones = set(tombstones)

    def delete_route(self, route: str) -> bool:
        """Sweep: physically delete an unreferenced node."""
        parts = route.lstrip("/").split("/")
        if parts[0] == "_blobs":
            return self.blobs.delete_blob(parts[1])
        if len(parts) == 1:
            return self.datastores.pop(parts[0], None) is not None
        ds = self.datastores.get(parts[0])
        if ds is None:
            return False
        return ds.channels.pop(parts[1], None) is not None

    # ------------------------------------------------------------------
    # inbound (process :1701)

    def process(self, msg: SequencedMessage) -> None:
        # Inbound lifecycle: chunks buffer until complete, compressed
        # envelopes inflate (remoteMessageProcessor.ts:11). A chunked
        # op takes effect — and acks — at its FINAL chunk's seq.
        envelope = self._inbound.process(msg.client_id, msg.contents)
        if envelope is None:
            self._advance_all(msg)  # mid-chunk: window still advances
            return
        # Own ops are acks even when they arrive during catch-up while
        # reconnecting (the connection flag is down but the op is ours).
        local = bool(self.client_id) and msg.client_id == self.client_id
        local_metadata = None
        if local:
            pending_op = self.pending.on_local_ack(msg)
            local_metadata = pending_op.metadata
        if envelope.get("kind") == "attach":
            if not local:
                self._process_attach(envelope)
            self._advance_all(msg)
            return
        if envelope.get("kind") == "blobAttach":
            contents = envelope["contents"]
            self.blobs.process_attach(contents["id"], contents["data"])
            self._advance_all(msg)
            return
        ds = self.datastores[envelope["address"]]
        ds.process(
            msg, envelope["channel"], envelope["contents"], local,
            local_metadata,
        )
        self._advance_all(msg)
        self.emit("op", msg, local)

    def observe_system(self, msg: SequencedMessage) -> None:
        """Window progression from messages that carry no runtime op
        (joins/leaves/noops): broadcast seq/msn advance to channels."""
        self._advance_all(msg)

    def _advance_all(self, msg: SequencedMessage) -> None:
        for ds in self.datastores.values():
            for channel in ds.channels.values():
                channel.on_sequence_advance(
                    msg.sequence_number, msg.minimum_sequence_number
                )

    def _process_attach(self, envelope: dict) -> None:
        """Materialize a remotely-created channel (lazy realization —
        RemoteChannelContext). A same-id channel both sides created is
        deduplicated: first attach wins, later ones no-op."""
        ds_id, ch_id = envelope["address"], envelope["channel"]
        if ds_id not in self.datastores:
            self.create_datastore(
                ds_id, root=envelope["contents"].get("root", True)
            )
        ds = self.datastores[ds_id]
        if ch_id in ds.channels:
            return
        contents = envelope["contents"]
        ds.load_channel(
            contents["channelType"], ch_id, contents["summary"]
        )

    # ------------------------------------------------------------------
    # reconnect (replayPendingStates :1573)

    def _replay_pending(self) -> None:
        self.reconnect_epoch += 1
        # fold unflushed outbox ops into the pending queue FIRST (they
        # are strictly newer than every flushed-pending entry, so
        # append order is submit order): a reconnect that interrupted
        # a flush — the service refusing the reconnect's join during
        # a quorum-loss degraded window — leaves raw envelopes here,
        # and flushing them AFTER this replay would double-submit ops
        # the channels are about to regenerate (found by the netsplit
        # differential as a merge-tree pending-queue-out-of-order
        # assert on the post-heal resubmit)
        for op in self._outbox:
            self.pending.on_submit(op)
        self._outbox.clear()
        for op in self.pending.drain():
            _RESUBMITS.inc()
            if op.kind in ("attach", "blobAttach"):
                self._outbox.append(op)  # announcements replay verbatim
                continue
            channel = self.datastores[op.datastore_id].channels[
                op.channel_id
            ]
            channel.resubmit_core(op.contents, op.metadata)
        self.flush()

    # ------------------------------------------------------------------
    # offline stash (closeAndGetPendingLocalState / applyStashedOp,
    # container.ts getPendingLocalState + sharedObject.ts:510)

    def get_pending_state(self) -> list:
        """JSON-safe serialization of every pending local op (the
        runtime half of IPendingLocalState)."""
        from ..protocol.serialization import encode_contents

        self.flush()
        return [
            {
                "kind": op.kind,
                "datastore": op.datastore_id,
                "channel": op.channel_id,
                "contents": encode_contents(op.contents),
                "metadata": encode_contents(op.metadata),
            }
            for op in self.pending._pending
        ]

    def apply_stashed_state(self, entries: list) -> None:
        """Rehydrate stashed pending ops into a freshly loaded
        runtime: attaches materialize their channels (dedup applies if
        they sequenced after the stash), channel ops re-apply as
        pending local state via each DDS's applyStashedOp hook; the
        next connect resubmits everything through the normal
        reconnect-rebase path."""
        from ..protocol.serialization import decode_contents

        for entry in entries:
            contents = decode_contents(entry["contents"])
            metadata = decode_contents(entry.get("metadata"))
            op = PendingOp(entry["datastore"], entry["channel"],
                           contents, metadata, kind=entry["kind"])
            if op.kind == "attach":
                self._process_attach({
                    "address": op.datastore_id,
                    "channel": op.channel_id,
                    "contents": contents,
                })
                self.pending.on_submit(op)
                continue
            if op.kind != "op":
                self.pending.on_submit(op)  # e.g. blobAttach: verbatim
                continue
            channel = self.datastores[op.datastore_id].channels[
                op.channel_id
            ]
            new_meta = channel.apply_stashed_op(contents)
            self.pending.on_submit(PendingOp(
                op.datastore_id, op.channel_id, contents,
                new_meta if new_meta is not None else metadata,
            ))

    # ------------------------------------------------------------------
    # summary (§3.4 client side)

    def summarize(self, unchanged: frozenset = frozenset()) -> dict:
        """``unchanged``: (datastore_id, channel_id) pairs to emit as
        summary handles instead of re-serializing (incremental
        summaries — the container tracks which channels are unchanged
        since the last ACKED summary)."""
        out = {
            "datastores": {
                ds_id: ds.summarize(frozenset(
                    cid for d, cid in unchanged if d == ds_id
                ))
                for ds_id, ds in self.datastores.items()
            },
            "blobs": self.blobs.summarize(),
        }
        # GC state rides the summary (garbageCollection.ts gcState in
        # the summary tree): an attached collector contributes fresh
        # state; otherwise loaded state is carried forward verbatim
        if self.gc is not None:
            out["gc"] = self.gc.snapshot()
        elif self._loaded_gc_state is not None:
            out["gc"] = self._loaded_gc_state
        return out

    def load(self, summary: dict) -> None:
        for ds_id, ds_summary in summary.get("datastores", {}).items():
            ds = self.create_datastore(
                ds_id, root=ds_summary.get("root", True)
            )
            ds.load(ds_summary)
        self.blobs.load(summary.get("blobs", {}))
        gc_state = summary.get("gc")
        if gc_state is not None:
            self._loaded_gc_state = gc_state
            self.set_tombstones(set(gc_state.get("tombstones", [])))

    @property
    def is_dirty(self) -> bool:
        """Unacked local state exists (containerRuntime dirty flag)."""
        return bool(self._outbox) or self.pending.count > 0
