"""Container runtime: datastore management, outbound batching, pending
state, reconnect replay.

Reference: packages/runtime/container-runtime/src/containerRuntime.ts
(``ContainerRuntime`` :631; inbound ``process`` :1701; outbound
``submitDataStoreOp`` :2549 -> ``Outbox``/``BatchManager``
(opLifecycle/outbox.ts:35, batchManager.ts:22); ``flush`` :1852;
``replayPendingStates`` :1573 with ``PendingStateManager``
(pendingStateManager.ts:75); ``orderSequentially`` :1860).
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Optional

from ..protocol.messages import SequencedMessage
from ..utils.events import EventEmitter
from .datastore import DataStoreRuntime
from .op_lifecycle import (
    OpCompressor,
    OpSplitter,
    RemoteMessageProcessor,
    mark_batch,
    stage_outbound,
)
from .shared_object import ChannelRegistry


@dataclass
class PendingOp:
    """One locally-submitted op awaiting its ack
    (pendingStateManager.ts pending message). ``kind`` is "op" for
    channel ops, "attach" for channel-attach announcements
    (ContainerMessageType.Attach, containerRuntime.ts:1701 switch)."""

    datastore_id: str
    channel_id: str
    contents: Any
    metadata: Any
    kind: str = "op"


class PendingStateManager:
    """Exactly-once resubmit across reconnects
    (pendingStateManager.ts:75): a deque of pending ops; acks pop the
    head; on reconnect every entry replays through its channel's
    ``resubmit_core`` (the rebase hook)."""

    def __init__(self) -> None:
        self._pending: deque[PendingOp] = deque()

    def on_submit(self, op: PendingOp) -> None:
        self._pending.append(op)

    def on_local_ack(self, msg: SequencedMessage) -> PendingOp:
        assert self._pending, "ack with no pending ops"
        return self._pending.popleft()

    def drain(self) -> list[PendingOp]:
        out = list(self._pending)
        self._pending.clear()
        return out

    @property
    def count(self) -> int:
        return len(self._pending)


class ContainerRuntime(EventEmitter):
    """One client's container: datastores + op lifecycle.

    The host (loader/driver/test session) wires ``submit_fn`` — called
    with the container-level op contents for each outbound message —
    and feeds inbound sequenced messages to ``process``.
    """

    def __init__(self, registry: ChannelRegistry,
                 submit_fn: Optional[Callable[[Any, Any], None]] = None):
        super().__init__()
        self.registry = registry
        self._submit_fn = submit_fn
        self.datastores: dict[str, DataStoreRuntime] = {}
        self.pending = PendingStateManager()
        self._outbox: list[PendingOp] = []
        self.client_id: str = ""
        self.connected = False
        self.reconnect_epoch = 0  # bumped on every reconnect
        # op lifecycle stages (opLifecycle/): outbound compress+chunk,
        # inbound reassemble+decompress
        self.compressor = OpCompressor()
        self.splitter = OpSplitter()
        self._inbound = RemoteMessageProcessor()

    # ------------------------------------------------------------------
    # wiring

    def set_submit_fn(self, fn: Callable[[Any, Any], None]) -> None:
        self._submit_fn = fn

    def set_connection_state(self, connected: bool,
                             client_id: str = "") -> None:
        """containerRuntime.ts:1307 setConnectionState; on reconnect,
        replay pending states (:1573)."""
        was_connected = self.connected
        self.connected = connected
        if client_id:
            self.client_id = client_id
        if connected:
            # (re-)announce identity to every channel — channels created
            # by load() connected before the client id was known
            for ds in self.datastores.values():
                for channel in ds.channels.values():
                    channel._on_connect()
        if connected and not was_connected and self.pending.count:
            self._replay_pending()
        self.emit("connected" if connected else "disconnected")

    # ------------------------------------------------------------------
    # datastores

    def create_datastore(self, datastore_id: str) -> DataStoreRuntime:
        if datastore_id in self.datastores:
            raise ValueError(f"datastore {datastore_id!r} exists")
        ds = DataStoreRuntime(self, datastore_id, self.registry)
        self.datastores[datastore_id] = ds
        return ds

    def get_datastore(self, datastore_id: str) -> DataStoreRuntime:
        return self.datastores[datastore_id]

    # ------------------------------------------------------------------
    # outbound (submitDataStoreOp :2549 -> Outbox -> flush :1852)

    def submit_op(self, datastore_id: str, channel_id: str, contents: Any,
                  metadata: Any = None) -> None:
        op = PendingOp(datastore_id, channel_id, contents, metadata)
        self._outbox.append(op)

    def submit_attach(self, datastore_id: str, channel_id: str,
                      channel_type: str, summary: dict) -> None:
        """Announce a locally-created channel so remote containers can
        materialize it (the Attach op: a new channel's type + initial
        snapshot travel in the op stream)."""
        self._outbox.append(PendingOp(
            datastore_id, channel_id,
            {"channelType": channel_type, "summary": summary},
            None, kind="attach",
        ))

    def flush(self) -> int:
        """Send every batched op (outbox.ts:102). Returns count sent.

        Drains atomically up front: with an in-proc synchronous service
        a submit can deliver (and re-enter flush) before this call
        returns, and the op must not be sent twice."""
        ops, self._outbox = self._outbox, []
        # Stage every wire message first (compress -> chunk), so batch
        # boundary marks land on the true first/last wire message.
        staged: list[tuple[dict, Any]] = []
        for op in ops:
            self.pending.on_submit(op)
            envelope = {
                "kind": op.kind,
                "address": op.datastore_id,
                "channel": op.channel_id,
                "contents": op.contents,
            }
            for wire in stage_outbound(
                envelope, self.compressor, self.splitter
            ):
                staged.append((wire, op.metadata))
        if len(staged) > 1:
            staged[0] = (staged[0][0], mark_batch(staged[0][1], True))
            staged[-1] = (staged[-1][0], mark_batch(staged[-1][1], False))
        if self._submit_fn is not None:
            for wire, metadata in staged:
                self._submit_fn(wire, metadata)
        return len(ops)

    def order_sequentially(self, callback: Callable[[], None]) -> None:
        """containerRuntime.ts:1860: run ``callback``, then flush its
        ops as one batch."""
        callback()
        self.flush()

    # ------------------------------------------------------------------
    # inbound (process :1701)

    def process(self, msg: SequencedMessage) -> None:
        # Inbound lifecycle: chunks buffer until complete, compressed
        # envelopes inflate (remoteMessageProcessor.ts:11). A chunked
        # op takes effect — and acks — at its FINAL chunk's seq.
        envelope = self._inbound.process(msg.client_id, msg.contents)
        if envelope is None:
            self._advance_all(msg)  # mid-chunk: window still advances
            return
        # Own ops are acks even when they arrive during catch-up while
        # reconnecting (the connection flag is down but the op is ours).
        local = bool(self.client_id) and msg.client_id == self.client_id
        local_metadata = None
        if local:
            pending_op = self.pending.on_local_ack(msg)
            local_metadata = pending_op.metadata
        if envelope.get("kind") == "attach":
            if not local:
                self._process_attach(envelope)
            self._advance_all(msg)
            return
        ds = self.datastores[envelope["address"]]
        ds.process(
            msg, envelope["channel"], envelope["contents"], local,
            local_metadata,
        )
        self._advance_all(msg)
        self.emit("op", msg, local)

    def observe_system(self, msg: SequencedMessage) -> None:
        """Window progression from messages that carry no runtime op
        (joins/leaves/noops): broadcast seq/msn advance to channels."""
        self._advance_all(msg)

    def _advance_all(self, msg: SequencedMessage) -> None:
        for ds in self.datastores.values():
            for channel in ds.channels.values():
                channel.on_sequence_advance(
                    msg.sequence_number, msg.minimum_sequence_number
                )

    def _process_attach(self, envelope: dict) -> None:
        """Materialize a remotely-created channel (lazy realization —
        RemoteChannelContext). A same-id channel both sides created is
        deduplicated: first attach wins, later ones no-op."""
        ds_id, ch_id = envelope["address"], envelope["channel"]
        if ds_id not in self.datastores:
            self.create_datastore(ds_id)
        ds = self.datastores[ds_id]
        if ch_id in ds.channels:
            return
        contents = envelope["contents"]
        ds.load_channel(
            contents["channelType"], ch_id, contents["summary"]
        )

    # ------------------------------------------------------------------
    # reconnect (replayPendingStates :1573)

    def _replay_pending(self) -> None:
        self.reconnect_epoch += 1
        for op in self.pending.drain():
            if op.kind == "attach":
                self._outbox.append(op)  # attach replays verbatim
                continue
            channel = self.datastores[op.datastore_id].channels[
                op.channel_id
            ]
            channel.resubmit_core(op.contents, op.metadata)
        self.flush()

    # ------------------------------------------------------------------
    # summary (§3.4 client side)

    def summarize(self) -> dict:
        return {
            "datastores": {
                ds_id: ds.summarize()
                for ds_id, ds in self.datastores.items()
            }
        }

    def load(self, summary: dict) -> None:
        for ds_id, ds_summary in summary.get("datastores", {}).items():
            ds = self.create_datastore(ds_id)
            ds.load(ds_summary)

    @property
    def is_dirty(self) -> bool:
        """Unacked local state exists (containerRuntime dirty flag)."""
        return bool(self._outbox) or self.pending.count > 0
