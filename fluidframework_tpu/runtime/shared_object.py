"""SharedObject base + channel plugin boundary.

Reference: packages/dds/shared-object-base/src/sharedObject.ts
(``SharedObjectCore`` :42 — abstract contract ``loadCore`` :305,
``processCore`` :329, ``reSubmitCore`` :378, ``applyStashedOp`` :510,
``summarizeCore``; submit path ``submitLocalMessage`` :343) and the
``IChannelFactory`` registry (packages/runtime/datastore-definitions) —
the plugin boundary the north star keeps: new channel types (including
TPU-backed ones) register a factory, nothing else changes.
"""
from __future__ import annotations

import abc
from typing import Any, Callable, Optional, Protocol

from ..protocol.messages import SequencedMessage


class ChannelServices(Protocol):
    """What a connected channel can do (IChannelServices): submit ops
    into the container's outbox."""

    def submit(self, contents: Any, metadata: Any = None) -> None: ...

    @property
    def client_id(self) -> str: ...

    @property
    def connected(self) -> bool: ...


class SharedObject(abc.ABC):
    """A distributed data structure instance (one channel)."""

    # set by subclasses: the factory type name, e.g. "sharedstring"
    type_name: str = ""

    def __init__(self, channel_id: str):
        self.id = channel_id
        self._services: Optional[ChannelServices] = None
        # monotonic edit counter driving incremental summaries: a
        # channel whose count equals its last-ACKED-summary capture is
        # unchanged and summarizes as a SummaryType.Handle
        # (summary.ts:55-59)
        self.change_count = 0

    # ------------------------------------------------------------------
    # wiring

    @property
    def connected(self) -> bool:
        return self._services is not None and self._services.connected

    @property
    def client_id(self) -> Optional[str]:
        return self._services.client_id if self._services else None

    def connect(self, services: ChannelServices) -> None:
        """Attach to a datastore runtime (sharedObject.ts connect)."""
        self._services = services
        self._on_connect()

    def _on_connect(self) -> None:
        """Hook for subclasses (start collaboration etc.)."""

    def submit_local_message(self, contents: Any,
                             metadata: Any = None) -> None:
        """sharedObject.ts:343 — route a local op to the service via
        the runtime; detached objects apply locally only."""
        self.change_count += 1
        if self._services is not None:
            self._services.submit(contents, metadata)

    # ------------------------------------------------------------------
    # the abstract DDS contract (sharedObject.ts:305-510)

    @abc.abstractmethod
    def process_core(self, msg: SequencedMessage, local: bool,
                     local_op_metadata: Any = None) -> None:
        """Apply one sequenced op. ``local`` means our own op came back
        (ack), not a re-application."""

    @abc.abstractmethod
    def summarize_core(self) -> dict:
        """Produce this channel's summary blob (JSON-safe)."""

    @abc.abstractmethod
    def load_core(self, summary: dict) -> None:
        """Initialize state from a summary blob."""

    def resubmit_core(self, contents: Any, metadata: Any = None) -> None:
        """Rebase + resubmit a pending op after reconnect
        (sharedObject.ts:378). Default: resubmit unchanged."""
        self.submit_local_message(contents, metadata)

    def apply_stashed_op(self, contents: Any) -> Any:
        """Apply an op from stashed offline state (sharedObject.ts:510).
        Default: subclasses override."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support stashed ops yet"
        )

    def gc_routes(self) -> list[str]:
        """Outbound GC edges: handle routes stored in this channel's
        data (getGCData, garbageCollection.ts:121). Default scans the
        summary tree for handles; hot channels can override with a
        cheaper direct scan. Raises if the channel cannot summarize —
        a failed GC run must abort rather than silently dropping edges
        (which would eventually sweep live data)."""
        from .handles import collect_handles
        return collect_handles(self.summarize_core())

    def on_sequence_advance(self, seq: int, min_seq: int) -> None:
        """Called for EVERY sequenced message the container processes
        (not just this channel's ops): collab-window progression. The
        reference surfaces this via the runtime's deltaManager events;
        consensus-style DDSes (quorum, register-collection) key their
        accept logic off msn advancing past their op's seq."""

    def signature(self) -> Any:
        """Canonical user-visible content, for convergence checks.
        Replica-local artifacts (tombstone granularity, intern order)
        must not appear. Default: the summary blob."""
        return self.summarize_core()


class ChannelFactory(Protocol):
    """IChannelFactory: how the runtime instantiates channel types."""

    @property
    def type_name(self) -> str: ...

    def create(self, channel_id: str) -> SharedObject: ...

    def load(self, channel_id: str, summary: dict) -> SharedObject: ...


class ChannelRegistry:
    """Maps channel type names to factories (ISharedObjectRegistry)."""

    def __init__(self, factories: Optional[list[ChannelFactory]] = None):
        self._factories: dict[str, ChannelFactory] = {}
        for f in factories or []:
            self.register(f)

    def register(self, factory: ChannelFactory) -> None:
        self._factories[factory.type_name] = factory

    def get(self, type_name: str) -> ChannelFactory:
        if type_name not in self._factories:
            raise KeyError(f"unknown channel type {type_name!r}")
        return self._factories[type_name]

    @property
    def types(self) -> tuple[str, ...]:
        return tuple(self._factories)


def simple_factory(cls) -> ChannelFactory:
    """Factory for SharedObject subclasses with (channel_id) ctor and
    load_core — the common case."""

    class _Factory:
        type_name = cls.type_name

        def create(self, channel_id: str) -> SharedObject:
            return cls(channel_id)

        def load(self, channel_id: str, summary: dict) -> SharedObject:
            obj = cls(channel_id)
            obj.load_core(summary)
            return obj

    return _Factory()
