"""fluidchaos — the deterministic process-wide fault-injection plane.

Reference: packages/test/test-service-load/src/faultInjectionDriver.ts
(injected disconnects/nacks exercised failure paths under load) and
the crash-state enumeration discipline of "All File Systems Are Not
Created Equal" (PAPERS.md): faults are not random monkey-testing —
they are a SEEDED, REPLAYABLE schedule fired at NAMED seams, and the
set of reachable crash states is bounded by the write barriers the
storage layer actually has (fsync-before-ack, write-temp+rename).

Every recovery seam in the serving stack registers an
:class:`InjectionSite` here (the catalog lives in
docs/ROBUSTNESS.md): socket frame in/out, broker queue
append/consume, checkpoint + op-log writes, sidecar dispatch, pool
dispatch/admission/migration, summary upload. A site consults the
plane at its seam; when a :class:`FaultSchedule` is armed, the
plane's seeded per-site decision stream says which fault kind (if
any) fires at that event. Disarmed, a site costs one attribute read.

Determinism contract (the config9 discipline): decisions are drawn
from an INDEPENDENT seeded stream per site, keyed by (schedule seed,
site name) and consumed one draw per site event — so the injection
sequence depends only on each site's own event order, never on how
unrelated sites interleave. A harness whose per-site event order is
deterministic (tests/test_chaos.py drives everything synchronously)
gets a bit-identical fault sequence per seed; ``plane.fired`` is that
sequence, and a failing run reproduces from the printed seed alone.

Loudness: every injected fault increments
``chaos_injected_total{site,kind}`` and lands in the plane's flight
recorder (which carries the schedule seed from arm time), so a chaos
run can never fire silently.

Layering: qos sits above obs/protocol only — this module imports
nothing it injects into; the seams pull the plane in (drivers,
service, parallel, testing may all import qos).
"""
from __future__ import annotations

import random
from typing import Optional

from ..obs import metrics as obs_metrics
from ..obs.flight_recorder import FlightRecorder

# ----------------------------------------------------------------------
# the one injection vocabulary (testing/fault_injection.py speaks it
# too — satellite fold; docs/ROBUSTNESS.md has the kind x site matrix)

KIND_DROP = "drop"              # frame vanishes (slow-consumer shape)
KIND_DUPLICATE = "duplicate"    # delivered twice (at-least-once shape)
KIND_REORDER = "reorder"        # held past the next frame
KIND_DELAY = "delay"            # held until the next pump
KIND_DISCONNECT = "disconnect"  # transport torn down, no goodbye
KIND_NACK = "nack"              # injected throttle nack, op dropped
KIND_ERROR = "error"            # one transient exception
KIND_ERROR_BURST = "error_burst"  # N consecutive errors (breaker trip)
KIND_DEFER = "defer"            # skip this opportunity, retry later
KIND_TORN_WRITE = "torn_write"  # prefix-truncated bytes (crash state)
KIND_CORRUPT = "corrupt"        # insane length prefix on the wire
KIND_PARTITION = "partition"    # links between islands go dark
KIND_HEAL = "heal"              # a partition's links come back

#: how many consecutive events an ``error_burst`` poisons once fired —
#: sized past every breaker failure_threshold in the tree (3) so one
#: burst provably trips it
BURST_LENGTH = 4

_M_INJECTED = obs_metrics.REGISTRY.counter(
    "chaos_injected_total",
    "faults the chaos plane injected, by site and kind",
    labelnames=("site", "kind"))
_M_ARMED = obs_metrics.REGISTRY.gauge(
    "chaos_armed", "1 while a fault schedule is armed")
_M_SITES = obs_metrics.REGISTRY.gauge(
    "chaos_sites_registered", "injection sites registered")


class TransientFault(Exception):
    """The exception ``error``/``error_burst`` faults raise — shaped
    like the transient faults the seams already survive (the sidecar
    breaker records it; storage paths catch OSError subclasses where
    they must, so sites that need OSError semantics raise
    :class:`TransientIOFault`)."""


class TransientIOFault(TransientFault, OSError):
    """Transient fault for seams whose recovery contract is keyed on
    OSError (checkpoint writes behind the storage breaker)."""


class FaultSchedule:
    """A seeded, replayable fault schedule.

    ``rates`` maps site name -> {kind: probability per site event}.
    Kinds a site does not support are ignored at fire time (the site
    declares its vocabulary), so one schedule can carry a standard
    rate table across harnesses with different site subsets.
    ``max_per_site`` bounds injections per site so a long run cannot
    drown in faults; ``None`` = unbounded.
    """

    def __init__(self, seed: int,
                 rates: Optional[dict[str, dict[str, float]]] = None,
                 max_per_site: Optional[int] = None):
        self.seed = seed
        self.rates = dict(rates or {})
        self.max_per_site = max_per_site

    def stream_for(self, site_name: str) -> random.Random:
        """The site's independent decision stream. Keyed by (seed,
        site) so cross-site interleaving cannot perturb decisions."""
        return random.Random(f"{self.seed}:{site_name}")

    def rng_for(self, purpose: str) -> random.Random:
        """A seeded stream for HARNESS decisions derived from the same
        seed (crash step, tear mode, reconnect delays) — everything a
        failing seed needs to reproduce rides the one number."""
        return random.Random(f"{self.seed}/{purpose}")

    def __repr__(self) -> str:
        return (f"FaultSchedule(seed={self.seed}, "
                f"rates={self.rates!r}, "
                f"max_per_site={self.max_per_site})")


class InjectionSite:
    """One named seam. ``fire()`` at the seam returns the fault kind
    to apply (or None); ``push()`` queues a scripted injection (the
    faultInjectionDriver vocabulary: injectNack/injectDisconnect) that
    fires at the next event regardless of any armed schedule;
    ``force()`` records an injection the caller already decided on
    (the harness's crash-time torn writes)."""

    def __init__(self, plane: "FaultPlane", name: str,
                 kinds: tuple[str, ...]):
        self.plane = plane
        self.name = name
        self.kinds = tuple(kinds)
        self.events = 0          # seam consultations (armed or not)
        self.injected = 0
        self._scripted: list[str] = []
        self._burst_remaining = 0
        # per-arm decision stream (None while disarmed)
        self._stream: Optional[random.Random] = None

    # -- scripted injections (fault_injection.py fold) ------------------

    def push(self, kind: str, count: int = 1) -> None:
        if kind not in self.kinds:
            raise ValueError(
                f"site {self.name!r} does not speak {kind!r} "
                f"(kinds: {self.kinds})")
        self._scripted.extend([kind] * count)

    @property
    def scripted_pending(self) -> int:
        return len(self._scripted)

    # -- the seam consultation ------------------------------------------

    def fire(self, **context) -> Optional[str]:
        """Consult the seam: one event, at most one fault."""
        self.events += 1
        if self._scripted:
            return self._record(self._scripted.pop(0), context)
        if self._burst_remaining > 0:
            self._burst_remaining -= 1
            return self._record(KIND_ERROR, context, burst=True)
        schedule = self.plane.schedule
        if schedule is None or self._stream is None:
            return None
        rates = schedule.rates.get(self.name)
        if not rates:
            return None
        if (schedule.max_per_site is not None
                and self.injected >= schedule.max_per_site):
            return None
        # ONE draw per event, consumed whether or not a fault fires —
        # the decision stream's position is a pure function of the
        # site's event count, so adding a kind to the rate table
        # never shifts later decisions of other kinds
        r = self._stream.random()
        acc = 0.0
        for kind in self.kinds:
            p = rates.get(kind, 0.0)
            if p <= 0.0:
                continue
            acc += p
            if r < acc:
                if kind == KIND_ERROR_BURST:
                    self._burst_remaining = BURST_LENGTH - 1
                return self._record(kind, context)
        return None

    def force(self, kind: str, **context) -> str:
        """Record an injection the caller performs itself (crash-time
        torn writes enumerated by the harness): counted and
        flight-recorded like any fired fault."""
        self.events += 1
        return self._record(kind, context)

    def _record(self, kind: str, context: dict,
                burst: bool = False) -> str:
        self.injected += 1
        _M_INJECTED.labels(site=self.name, kind=kind).inc()
        self.plane.fired.append((self.name, self.events, kind))
        self.plane.flight.record(
            "inject", site=self.name, fault=kind, event=self.events,
            burst=burst, **{k: v for k, v in context.items()
                            if isinstance(v, (int, float, str, bool))})
        return kind

    def transient(self, kind: str) -> TransientFault:
        """The exception an ``error`` fault raises at this seam."""
        return TransientFault(
            f"chaos[{self.name}]: injected {kind} "
            f"(event {self.events})")

    def _arm(self, schedule: Optional[FaultSchedule]) -> None:
        self._stream = (schedule.stream_for(self.name)
                        if schedule is not None else None)
        self._burst_remaining = 0
        self.events = 0
        self.injected = 0


class FaultPlane:
    """The process-wide site registry + armed schedule."""

    def __init__(self) -> None:
        self._sites: dict[str, InjectionSite] = {}
        self.schedule: Optional[FaultSchedule] = None
        #: (site, site-event-index, kind) in firing order — the
        #: replayable injection sequence the determinism test pins
        self.fired: list[tuple[str, int, str]] = []
        self.flight = FlightRecorder(512, name="chaos")
        #: observer hooks (testing/failsan.py registers here — qos
        #: imports nothing above itself, so the observers come to the
        #: plane): ``on_arm`` callbacks get the schedule AFTER the
        #: sites are armed; ``on_disarm`` callbacks get the plane
        #: BEFORE the schedule is cleared, so they can read the seed
        #: and the fired log of the window that is ending
        self.on_arm: list = []
        self.on_disarm: list = []

    def site(self, name: str,
             kinds: tuple[str, ...] = ()) -> InjectionSite:
        """Register (or fetch) a site. Registration is idempotent;
        a re-registration may only widen the kind vocabulary."""
        existing = self._sites.get(name)
        if existing is not None:
            for kind in kinds:
                if kind not in existing.kinds:
                    existing.kinds = existing.kinds + (kind,)
            return existing
        site = InjectionSite(self, name, kinds)
        self._sites[name] = site
        _M_SITES.set(len(self._sites))
        if self.schedule is not None:
            # a seam first imported AFTER arm() (lazy imports mid-run)
            # must still get its decision stream, or the armed
            # schedule silently never fires there — the exact silent
            # hole the plane's loudness contract exists to close
            site._arm(self.schedule)
        return site

    def sites(self) -> dict[str, InjectionSite]:
        return dict(self._sites)

    @property
    def armed(self) -> bool:
        return self.schedule is not None

    def arm(self, schedule: FaultSchedule) -> None:
        """Arm a schedule: resets every site's event counter and
        decision stream so the injection sequence is a pure function
        of the seed, and records the seed in the flight recorder (a
        dump from any later fault carries it)."""
        self.schedule = schedule
        self.fired = []
        for site in self._sites.values():
            site._arm(schedule)
        _M_ARMED.set(1)
        self.flight.record("arm", seed=schedule.seed,
                           rates=str(sorted(schedule.rates)))
        for hook in list(self.on_arm):
            hook(schedule)

    def disarm(self) -> None:
        if self.schedule is not None:
            self.flight.record("disarm", seed=self.schedule.seed,
                               fired=len(self.fired))
            for hook in list(self.on_disarm):
                hook(self)
        self.schedule = None
        for site in self._sites.values():
            site._arm(None)
        _M_ARMED.set(0)

    class _Armed:
        def __init__(self, plane: "FaultPlane",
                     schedule: FaultSchedule):
            self.plane = plane
            self.schedule = schedule

        def __enter__(self) -> "FaultPlane":
            self.plane.arm(self.schedule)
            return self.plane

        def __exit__(self, *exc) -> None:
            self.plane.disarm()

    def while_armed(self, schedule: FaultSchedule) -> "_Armed":
        return self._Armed(self, schedule)


#: THE process-wide plane every seam registers against
PLANE = FaultPlane()


def standard_rates(sites: Optional[list[str]] = None
                   ) -> dict[str, dict[str, float]]:
    """The standard chaos mix (tools/stress --chaos, bench config11,
    the convergence differential): moderate rates at every seam,
    tuned so a ~100-event run fires a handful of faults per armed
    site. ``sites`` filters to a subset (--sites a,b)."""
    rates = {
        "socket.frame_in": {
            KIND_DROP: 0.08, KIND_DUPLICATE: 0.08,
            KIND_REORDER: 0.06, KIND_DELAY: 0.05,
        },
        "socket.frame_out": {
            KIND_DISCONNECT: 0.02, KIND_NACK: 0.03,
        },
        "broker.queue_append": {KIND_ERROR: 0.02},
        "broker.queue_consume": {KIND_DUPLICATE: 0.05},
        "storage.checkpoint_write": {
            KIND_ERROR: 0.02, KIND_ERROR_BURST: 0.01,
        },
        "sidecar.dispatch": {
            KIND_ERROR: 0.04, KIND_ERROR_BURST: 0.01,
        },
        "sidecar.pool_dispatch": {KIND_DEFER: 0.20},
        "sidecar.pool_admit": {KIND_ERROR: 0.25},
        "sidecar.pool_migrate": {KIND_DEFER: 0.25},
        "ingress.summary_upload": {KIND_ERROR: 0.30},
        # replicated sequencer seams (service/replication.py +
        # partitioning's queue counterpart): follower lag, lost/
        # erroring acks, lease renewal loss + spurious lapse (the
        # split-brain trigger), transient election failures
        "repl.lag": {KIND_DEFER: 0.15},
        "repl.append_ack": {KIND_DROP: 0.04, KIND_ERROR: 0.02},
        "repl.lease_expire": {KIND_DROP: 0.03, KIND_ERROR: 0.01},
        "repl.promote": {KIND_ERROR: 0.25},
    }
    if sites is not None:
        unknown = set(sites) - set(rates)
        if unknown:
            raise ValueError(
                f"unknown chaos sites {sorted(unknown)}; known: "
                f"{sorted(rates)}")
        rates = {k: v for k, v in rates.items() if k in sites}
    return rates
