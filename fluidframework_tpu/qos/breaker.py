"""Circuit breaker: closed / open / half-open with probe admission.

Wraps the two service dependencies that can fail independently of
load — TPU sidecar dispatch (device faults) and durable storage
writes (disk faults) — so a hard-down dependency degrades the service
instead of taking the serving loop down with it:

- CLOSED: calls pass through; ``failure_threshold`` CONSECUTIVE
  failures trip to OPEN (one success resets the streak — a flaky 1%
  failure rate must not open the breaker).
- OPEN: calls are refused instantly (``allow()`` is False /
  ``call()`` raises :class:`BreakerOpenError` with an honest
  ``retry_after_seconds``); after ``reset_timeout_s`` the next
  ``allow()`` transitions to HALF_OPEN.
- HALF_OPEN: ``probe_quota`` probe calls are admitted; any failure
  re-opens (fresh timeout), ``probe_successes`` consecutive
  successes close.

``on_open`` fires on every closed/half-open -> open transition — the
sidecar hooks its obs flight recorder there, so the postmortem of
WHAT tripped the breaker is captured at trip time, not reconstructed
later. State/transition series land in ``obs.metrics.REGISTRY``
(``qos_breaker_state{name}``, ``qos_breaker_transitions_total``).

Deterministic: the clock is injectable; nothing here sleeps.
Single-threaded by design (called from whatever loop drives the
wrapped dependency).
"""
from __future__ import annotations

import time
from typing import Callable, Optional

from ..obs import metrics as obs_metrics

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half_open"
_STATE_CODE = {STATE_CLOSED: 0, STATE_HALF_OPEN: 1, STATE_OPEN: 2}

_M_STATE = obs_metrics.REGISTRY.gauge(
    "qos_breaker_state",
    "circuit state (0=closed, 1=half-open, 2=open)",
    labelnames=("name",))
_M_TRANSITIONS = obs_metrics.REGISTRY.counter(
    "qos_breaker_transitions_total", "breaker state transitions",
    labelnames=("name", "to"))
_M_REFUSED = obs_metrics.REGISTRY.counter(
    "qos_breaker_refused_total",
    "calls refused while the breaker was open", labelnames=("name",))
_M_FAILURES = obs_metrics.REGISTRY.counter(
    "qos_breaker_failures_total",
    "failures reported to the breaker (every record_failure, "
    "including sub-threshold ones that do not open the circuit)",
    labelnames=("name",))


class BreakerOpenError(RuntimeError):
    """The wrapped dependency is circuit-broken; retry later."""

    def __init__(self, message: str,
                 retry_after_seconds: float = 0.0):
        super().__init__(message)
        self.retry_after_seconds = retry_after_seconds


class CircuitBreaker:
    def __init__(self, name: str = "breaker", *,
                 failure_threshold: int = 3,
                 reset_timeout_s: float = 5.0,
                 probe_quota: int = 1,
                 probe_successes: int = 1,
                 clock: Callable[[], float] = time.monotonic,
                 on_open: Optional[Callable[["CircuitBreaker"],
                                            None]] = None):
        if failure_threshold < 1 or probe_quota < 1 \
                or probe_successes < 1:
            raise ValueError("breaker thresholds must be >= 1")
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.probe_quota = probe_quota
        self.probe_successes = probe_successes
        self._clock = clock
        self.on_open = on_open
        self._state = STATE_CLOSED
        self._failures = 0          # consecutive, while closed
        self._opened_at = 0.0
        self._probes_left = 0       # while half-open
        self._probe_ok = 0          # consecutive, while half-open
        self.last_error: Optional[BaseException] = None
        _M_STATE.labels(name=name).set(0)

    # ------------------------------------------------------------------

    @property
    def state(self) -> str:
        """Current state, advancing OPEN -> HALF_OPEN on timeout."""
        self._maybe_half_open()
        return self._state

    def _transition(self, to: str) -> None:
        if to == self._state:
            return
        self._state = to
        _M_STATE.labels(name=self.name).set(_STATE_CODE[to])
        _M_TRANSITIONS.labels(name=self.name, to=to).inc()
        if to == STATE_OPEN:
            self._opened_at = self._clock()
            if self.on_open is not None:
                self.on_open(self)
        elif to == STATE_HALF_OPEN:
            self._probes_left = self.probe_quota
            self._probe_ok = 0
        else:  # closed
            self._failures = 0

    def _maybe_half_open(self) -> None:
        if (
            self._state == STATE_OPEN
            and self._clock() - self._opened_at >= self.reset_timeout_s
        ):
            self._transition(STATE_HALF_OPEN)

    def retry_after(self) -> float:
        """Honest wait until the next probe window (0 if admitting)."""
        if self.state == STATE_OPEN:
            return max(
                0.0,
                self._opened_at + self.reset_timeout_s - self._clock(),
            )
        return 0.0

    # ------------------------------------------------------------------

    def allow(self) -> bool:
        """May a call proceed right now? In HALF_OPEN this CONSUMES a
        probe slot — callers that get True must report the outcome
        via record_success/record_failure."""
        self._maybe_half_open()
        if self._state == STATE_CLOSED:
            return True
        if self._state == STATE_HALF_OPEN and self._probes_left > 0:
            self._probes_left -= 1
            return True
        _M_REFUSED.labels(name=self.name).inc()
        return False

    def record_success(self) -> None:
        if self._state == STATE_HALF_OPEN:
            self._probe_ok += 1
            if self._probe_ok >= self.probe_successes:
                self._transition(STATE_CLOSED)
            else:
                # serial probe admission: each success grants the
                # next probe slot, so probe_successes > probe_quota
                # converges instead of deadlocking out of probes
                self._probes_left += 1
        else:
            self._failures = 0

    def record_failure(self, error: Optional[BaseException] = None
                       ) -> None:
        self.last_error = error
        _M_FAILURES.labels(name=self.name).inc()
        if self._state == STATE_HALF_OPEN:
            self._transition(STATE_OPEN)  # probe failed: back off
            return
        if self._state == STATE_CLOSED:
            self._failures += 1
            if self._failures >= self.failure_threshold:
                self._transition(STATE_OPEN)

    def call(self, fn: Callable, *args, **kwargs):
        """Run ``fn`` under the breaker; refusals raise
        :class:`BreakerOpenError` with the honest retry hint."""
        if not self.allow():
            raise BreakerOpenError(
                f"{self.name} is open "
                f"(last error: {self.last_error!r})",
                retry_after_seconds=self.retry_after(),
            )
        try:
            out = fn(*args, **kwargs)
        except Exception as e:
            self.record_failure(e)
            raise
        self.record_success()
        return out
