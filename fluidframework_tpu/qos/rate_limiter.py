"""Token-bucket rate limiting — the per-tenant throttler analogue.

Reference: Routerlicious fronts alfred with per-tenant throttling
middleware (server/routerlicious/packages/services/src/throttler.ts,
utils/throttlerHelper.ts): every connect/submit consults a usage
counter and over-budget callers get a throttling response carrying
``retryAfterInMs``. The client half of that contract already exists
here (drivers/driver_utils.py honors ``retry_after_seconds``); this
module is the service half the stack was missing.

Design constraints:

- **Deterministic**: the clock is injectable (``clock=``), so tests
  and the overload harness drive refill explicitly — no wall-time
  races.
- **Honest waits**: a rejected take returns the exact seconds until
  the bucket can cover the request, which is what the throttle nack's
  ``retry_after_seconds`` must carry (a made-up constant teaches
  clients to ignore it).
- **Bounded memory**: per-scope bucket maps are LRU-capped — a scope
  churn attack (one op per fresh document id) cannot grow state
  without bound. Eviction forgets at most ``burst`` tokens of debt,
  which only ever errs toward admitting.

Single-threaded by design: limiters are consulted from the ingress
event loop (or a test/bench driver), never concurrently.
"""
from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Hashable


@dataclass(frozen=True)
class Budget:
    """One refill schedule: ``rate`` tokens/second, ``burst`` cap.

    ``burst`` defaults to one second of rate — enough to absorb a
    flush-sized spike without admitting a sustained overage."""

    rate: float
    burst: float = 0.0

    def __post_init__(self):
        if self.rate <= 0:
            raise ValueError(f"budget rate must be > 0, got {self.rate}")
        if self.burst <= 0:
            object.__setattr__(self, "burst", float(self.rate))


class TokenBucket:
    """Classic token bucket with peek/take split so a multi-bucket
    admission (connection AND document AND tenant) can check every
    budget before consuming from any — a partial take would charge
    callers for ops that were never admitted."""

    __slots__ = ("budget", "tokens", "_last", "_clock")

    def __init__(self, budget: Budget,
                 clock: Callable[[], float] = time.monotonic):
        self.budget = budget
        self.tokens = float(budget.burst)
        self._clock = clock
        self._last = clock()

    def _refill(self) -> None:
        now = self._clock()
        if now > self._last:
            self.tokens = min(
                self.budget.burst,
                self.tokens + (now - self._last) * self.budget.rate,
            )
        self._last = now

    def peek(self, n: float = 1.0) -> float:
        """Seconds until ``n`` tokens are available (0.0 = now)."""
        self._refill()
        if self.tokens >= n:
            return 0.0
        return (n - self.tokens) / self.budget.rate

    def take(self, n: float = 1.0) -> None:
        """Consume unconditionally (call after a 0.0 peek; going
        negative is allowed so a peek/take pair under one admission
        stays correct even if a sibling bucket took first)."""
        self._refill()
        self.tokens -= n

    def try_take(self, n: float = 1.0) -> float:
        """Atomic peek+take: 0.0 and consumed, or the honest wait."""
        wait = self.peek(n)
        if wait == 0.0:
            self.take(n)
        return wait


class ScopedBuckets:
    """``key -> TokenBucket`` under one shared Budget, LRU-capped.

    One instance per (scope, dimension) pair — e.g. per-document op
    budgets — where the key space is attacker-influenced and must not
    grow without bound."""

    def __init__(self, budget: Budget,
                 clock: Callable[[], float] = time.monotonic,
                 max_scopes: int = 4096):
        self.budget = budget
        self._clock = clock
        self.max_scopes = max_scopes
        self._buckets: "OrderedDict[Hashable, TokenBucket]" = \
            OrderedDict()

    def bucket(self, key: Hashable) -> TokenBucket:
        b = self._buckets.get(key)
        if b is None:
            b = self._buckets[key] = TokenBucket(
                self.budget, self._clock
            )
            while len(self._buckets) > self.max_scopes:
                self._buckets.popitem(last=False)
        else:
            self._buckets.move_to_end(key)
        return b

    def peek(self, key: Hashable, n: float = 1.0) -> float:
        return self.bucket(key).peek(n)

    def take(self, key: Hashable, n: float = 1.0) -> None:
        self.bucket(key).take(n)

    def __len__(self) -> int:
        return len(self._buckets)
