"""AdmissionController: the one gate ingress consults per frame.

Composes the three qos pieces — scoped token buckets
(qos/rate_limiter.py), the composite pressure signal
(qos/pressure.py) and the shed policy (qos/policy.py) — into a
single ``admit()`` call answering: may this (class, tenant, document,
connection, ops, bytes) proceed, and if not, when should the caller
retry?

Decision order:

1. PRESSURE first: if the current tier sheds this traffic class, the
   request never touches the buckets (an overloaded service must not
   spend per-scope bucket work on traffic it is about to refuse).
2. RATE LIMITS second: every applicable bucket is peeked BEFORE any
   is charged — a partial take would bill callers for refused work —
   and the worst bucket's exact refill wait becomes
   ``retry_after_seconds``.

Admitted work is charged to every bucket it consulted.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Optional

from ..obs import metrics as obs_metrics
from .policy import (
    CLASS_CATCHUP,
    CLASS_SUMMARY,
    CLASS_WRITE,
    REASON_PRESSURE,
    REASON_RATE_LIMIT,
    Admission,
    ShedPolicy,
)
from .pressure import PressureMonitor
from .rate_limiter import Budget, ScopedBuckets

_M_ADMITTED = obs_metrics.REGISTRY.counter(
    "qos_admitted_total", "requests the admission gate let through",
    labelnames=("klass",))
_M_SHED = obs_metrics.REGISTRY.counter(
    "qos_shed_total", "requests refused with a throttle response",
    labelnames=("klass", "reason"))


@dataclass(frozen=True)
class RateLimits:
    """Budget per (scope, dimension); ``None`` = that limit is off.

    Scopes: *connection* (one TCP session), *document*, *tenant*
    (anonymous deployments share the "" tenant, making tenant budgets
    effectively global). Dimensions: ops, bytes, summary uploads,
    catch-up reads."""

    connection_ops: Optional[Budget] = None
    document_ops: Optional[Budget] = None
    tenant_ops: Optional[Budget] = None
    connection_bytes: Optional[Budget] = None
    tenant_bytes: Optional[Budget] = None
    summary_uploads: Optional[Budget] = None   # per tenant, count
    summary_bytes: Optional[Budget] = None     # per tenant
    catchup_reads: Optional[Budget] = None     # per connection, count


def default_limits(ops_per_sec: float = 2000.0) -> RateLimits:
    """The ``--qos`` flag's defaults: per-connection op/byte budgets
    sized for one busy interactive client, per-document and
    per-tenant budgets an order above (many clients share them), and
    modest summary/catch-up budgets — summaries are bulk work."""
    return RateLimits(
        connection_ops=Budget(ops_per_sec),
        document_ops=Budget(ops_per_sec * 4),
        tenant_ops=Budget(ops_per_sec * 16),
        connection_bytes=Budget(ops_per_sec * 1024),
        tenant_bytes=Budget(ops_per_sec * 16 * 1024),
        summary_uploads=Budget(4.0, burst=8.0),
        summary_bytes=Budget(8 << 20),
        catchup_reads=Budget(50.0, burst=100.0),
    )


class AdmissionController:
    def __init__(self, limits: Optional[RateLimits] = None,
                 pressure: Optional[PressureMonitor] = None,
                 policy: Optional[ShedPolicy] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.limits = limits or RateLimits()
        self.pressure = pressure
        self.policy = policy or ShedPolicy()
        self._clock = clock
        self._buckets: dict[str, ScopedBuckets] = {
            dim: ScopedBuckets(budget, clock)
            for dim, budget in vars(self.limits).items()
            if budget is not None
        }

    # ------------------------------------------------------------------

    def _demands(self, klass: str, tenant: str, document: str,
                 connection: str, ops: float, nbytes: float
                 ) -> list[tuple[ScopedBuckets, str, float]]:
        """(bucket-set, scope key, amount) triples this request must
        clear. Zero amounts are skipped (a 0-byte op must not charge
        the byte buckets a refill wait of 0/rate)."""
        spec = {
            CLASS_WRITE: (
                ("connection_ops", connection, ops),
                ("document_ops", document, ops),
                ("tenant_ops", tenant, ops),
                ("connection_bytes", connection, nbytes),
                ("tenant_bytes", tenant, nbytes),
            ),
            CLASS_SUMMARY: (
                ("summary_uploads", tenant, ops),
                ("summary_bytes", tenant, nbytes),
            ),
            CLASS_CATCHUP: (
                ("catchup_reads", connection, ops),
            ),
        }[klass]
        return [
            (self._buckets[dim], key, amount)
            for dim, key, amount in spec
            if amount > 0 and dim in self._buckets
        ]

    def admit(self, klass: str, *, tenant: str = "",
              document: str = "", connection: str = "",
              ops: float = 1.0, nbytes: float = 0.0) -> Admission:
        tier = 0
        if self.pressure is not None:
            tier = self.pressure.tier()
            if self.policy.sheds(klass, tier):
                _M_SHED.labels(klass=klass, reason=REASON_PRESSURE
                               ).inc()
                return Admission(
                    admitted=False,
                    retry_after_seconds=self.policy.retry_after(tier),
                    reason=REASON_PRESSURE, tier=tier,
                    shed_class=klass,
                )
        demands = self._demands(
            klass, tenant, document, connection, ops, nbytes
        )
        wait = max(
            (b.peek(key, n) for b, key, n in demands), default=0.0
        )
        if wait > 0.0:
            _M_SHED.labels(klass=klass, reason=REASON_RATE_LIMIT).inc()
            return Admission(
                admitted=False, retry_after_seconds=wait,
                reason=REASON_RATE_LIMIT, tier=tier, shed_class=klass,
            )
        for b, key, n in demands:
            b.take(key, n)
        _M_ADMITTED.labels(klass=klass).inc()
        return Admission(admitted=True, tier=tier)
