"""Shed policy: pressure tier x traffic class -> admit or shed.

The shed ORDER is the subsystem's core judgment call (docs/QOS.md):

1. **summary uploads** go first — they are bulk, deferrable, and a
   missed summary only costs catch-up time (the op log retains
   everything until the NEXT ack truncates it);
2. **read-only catch-up** goes second — readers tolerate staleness,
   and every shed read frees fanout + outbound-queue budget for
   writers;
3. **admitted writers** go last — a writer's op stream is the product;
   shedding it is service-survival mode only (CRITICAL).

Every shed answer carries an honest ``retry_after_seconds``: for
rate-limit sheds the limiter computes the exact bucket-refill wait;
for pressure sheds the policy scales a base backoff by tier, so
clients naturally sort themselves by how overloaded the service is.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from .pressure import TIER_CRITICAL, TIER_ELEVATED, TIER_SEVERE

# traffic classes, in shed order (first shed first)
CLASS_SUMMARY = "summary"
CLASS_CATCHUP = "catchup"
CLASS_WRITE = "write"
SHED_ORDER = (CLASS_SUMMARY, CLASS_CATCHUP, CLASS_WRITE)

# shed reasons (bounded metric label values)
REASON_RATE_LIMIT = "rate_limit"
REASON_PRESSURE = "pressure"
# quorum-loss degraded mode (service/replication.py): not a pressure
# tier — the service refuses the write because it cannot PROVE it
# durable (quorum unreachable) or cannot prove its own leadership
# (lease service unreachable past the TTL). Rides throttle nacks in
# the same OPTIONAL shed_class wire field as the pressure reasons
# (1.0/1.1 peers that ignore it interop — test_wire_compat), and the
# nack is retriable by construction: the op stays with its submitter
# and the PR9 reconnect/resubmit path replays it after the heal.
REASON_UNAVAILABLE = "unavailable"

DEFAULT_SHED_AT = {
    CLASS_SUMMARY: TIER_ELEVATED,
    CLASS_CATCHUP: TIER_SEVERE,
    CLASS_WRITE: TIER_CRITICAL,
}


@dataclass(frozen=True)
class Admission:
    """One admission decision. ``admitted=False`` always carries a
    nonzero ``retry_after_seconds`` and a reason; ``tier`` and
    ``shed_class`` ride throttle nacks as OPTIONAL wire fields
    (1.0/1.1 peers that ignore them interop — test_wire_compat)."""

    admitted: bool
    retry_after_seconds: float = 0.0
    reason: str = ""
    tier: int = 0
    shed_class: Optional[str] = None


class ShedPolicy:
    """tier -> which classes shed, and with what backoff hint."""

    def __init__(self, shed_at: Optional[dict] = None,
                 base_retry_s: float = 0.25,
                 max_retry_s: float = 8.0):
        self.shed_at = dict(DEFAULT_SHED_AT)
        if shed_at:
            unknown = set(shed_at) - set(SHED_ORDER)
            if unknown:
                raise ValueError(
                    f"unknown traffic classes {sorted(unknown)}; "
                    f"pick from {SHED_ORDER}"
                )
            self.shed_at.update(shed_at)
        self.base_retry_s = base_retry_s
        self.max_retry_s = max_retry_s

    def sheds(self, klass: str, tier: int) -> bool:
        return tier >= self.shed_at.get(klass, TIER_CRITICAL)

    def shed_classes(self, tier: int) -> tuple[str, ...]:
        return tuple(
            k for k in SHED_ORDER if self.sheds(k, tier)
        )

    def retry_after(self, tier: int) -> float:
        """Pressure-shed backoff hint: base * 2^(tier-1), capped —
        the deeper the overload, the longer clients stay away."""
        if tier <= 0:
            return self.base_retry_s
        return min(self.max_retry_s,
                   self.base_retry_s * (2 ** (tier - 1)))
