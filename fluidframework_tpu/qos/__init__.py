"""Quality of service: admission control, backpressure, circuit
breaking — the layer that keeps the ordering service live at 10x
offered load.

Reference: Routerlicious's per-tenant throttling middleware (alfred
consults a Throttler before deli sees an op; throttle responses carry
retryAfter, which drivers/driver_utils.py already honors client-side)
plus the standard overload-control trio:

- **Token-bucket rate limiters** (:mod:`.rate_limiter`) — per-tenant
  / per-document / per-connection budgets for ops, bytes, summary
  uploads and catch-up reads;
- **Composite pressure signal** (:mod:`.pressure`) — queue depths
  from across the pipeline (sequencer inbox, sidecar dispatch
  backlog, broker fanout lag, session outbound queues) normalized
  into one tier;
- **Shed policy + admission gate** (:mod:`.policy`,
  :mod:`.admission`) — pressure tier x traffic class -> admit or
  shed with an HONEST ``retry_after_seconds``;
- **Circuit breaker** (:mod:`.breaker`) — closed/open/half-open with
  probe admission around sidecar dispatch and storage writes;
- **Fault plane** (:mod:`.faults`, "fluidchaos") — named injection
  sites at every recovery seam + seeded replayable fault schedules,
  the substrate of the crash-recovery convergence differential
  (docs/ROBUSTNESS.md).

Layering: qos sits beside obs (above protocol); the service plane
imports it, it imports nothing it protects. Everything is clock-
injectable so overload behavior pins down in deterministic tests
(tests/test_qos.py) instead of timing races.
"""
from __future__ import annotations

from .admission import AdmissionController, RateLimits, default_limits
from .faults import (
    PLANE,
    FaultPlane,
    FaultSchedule,
    InjectionSite,
    TransientFault,
    TransientIOFault,
    standard_rates,
)
from .breaker import (
    STATE_CLOSED,
    STATE_HALF_OPEN,
    STATE_OPEN,
    BreakerOpenError,
    CircuitBreaker,
)
from .policy import (
    CLASS_CATCHUP,
    CLASS_SUMMARY,
    CLASS_WRITE,
    REASON_PRESSURE,
    REASON_RATE_LIMIT,
    SHED_ORDER,
    Admission,
    ShedPolicy,
)
from .pressure import (
    TIER_CRITICAL,
    TIER_ELEVATED,
    TIER_NAMES,
    TIER_NOMINAL,
    TIER_SEVERE,
    PressureMonitor,
    PressureReading,
)
from .rate_limiter import Budget, ScopedBuckets, TokenBucket

__all__ = [
    "Admission",
    "AdmissionController",
    "BreakerOpenError",
    "Budget",
    "CircuitBreaker",
    "FaultPlane",
    "FaultSchedule",
    "InjectionSite",
    "PLANE",
    "standard_rates",
    "TransientFault",
    "TransientIOFault",
    "CLASS_CATCHUP",
    "CLASS_SUMMARY",
    "CLASS_WRITE",
    "PressureMonitor",
    "PressureReading",
    "RateLimits",
    "REASON_PRESSURE",
    "REASON_RATE_LIMIT",
    "ScopedBuckets",
    "SHED_ORDER",
    "ShedPolicy",
    "STATE_CLOSED",
    "STATE_HALF_OPEN",
    "STATE_OPEN",
    "TIER_CRITICAL",
    "TIER_ELEVATED",
    "TIER_NAMES",
    "TIER_NOMINAL",
    "TIER_SEVERE",
    "TokenBucket",
    "default_limits",
]
