"""Composite backpressure signal: sampled queue depths -> one tier.

Sequencing is a serial bottleneck (one ordering authority per
document), so the honest load signal is not request rate but DEPTH:
how far behind the pipeline's queues are. This module aggregates any
number of registered depth sources — sequencer inbox, sidecar
``queued_ops``/dispatch backlog, broker fanout lag, per-session
outbound queues — into one normalized pressure value and a discrete
tier the policy engine (qos/policy.py) maps to actions.

Tiers (docs/QOS.md):

    0 NOMINAL    everything admitted (rate limits still apply)
    1 ELEVATED   shed summary uploads
    2 SEVERE     also shed read-only catch-up traffic
    3 CRITICAL   also shed admitted writers (service survival mode)

Each source normalizes as ``depth / capacity``; the composite value
is the MAX over sources (one saturated stage stalls the pipeline no
matter how idle the others are). Gauges land in
``obs.metrics.REGISTRY`` under bounded label sets — source names are
code-chosen, never derived from tenant/document input.
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..obs import metrics as obs_metrics

TIER_NOMINAL = 0
TIER_ELEVATED = 1
TIER_SEVERE = 2
TIER_CRITICAL = 3

TIER_NAMES = ("nominal", "elevated", "severe", "critical")

_M_PRESSURE = obs_metrics.REGISTRY.gauge(
    "qos_pressure", "composite pressure (max normalized source depth)")
_M_TIER = obs_metrics.REGISTRY.gauge(
    "qos_pressure_tier", "pressure tier (0=nominal..3=critical)")
_M_SOURCE = obs_metrics.REGISTRY.gauge(
    "qos_pressure_source",
    "per-source normalized depth", labelnames=("source",))
_M_TRANSITIONS = obs_metrics.REGISTRY.counter(
    "qos_pressure_transitions_total",
    "tier changes observed by the monitor", labelnames=("to",))
_M_SOURCE_ERRORS = obs_metrics.REGISTRY.counter(
    "qos_pressure_source_errors_total",
    "pressure-source sampling callbacks that raised (source read 0)",
    labelnames=("source",))


@dataclass(frozen=True)
class PressureReading:
    """One sample: the composite value, its tier, per-source detail."""

    value: float
    tier: int
    by_source: dict = field(default_factory=dict)

    @property
    def tier_name(self) -> str:
        return TIER_NAMES[self.tier]


class PressureMonitor:
    """Registered depth sources -> PressureReading.

    ``min_interval_s`` rate-limits the sampling itself: at 10x
    offered load the admission gate runs per frame, and walking every
    source per frame would make the shed path cost what it sheds.
    0.0 (the default) samples every call — what deterministic tests
    want."""

    def __init__(self, *, elevated: float = 0.5, severe: float = 0.8,
                 critical: float = 1.0, min_interval_s: float = 0.0,
                 clock: Callable[[], float] = time.monotonic):
        if not (0 < elevated <= severe <= critical):
            raise ValueError(
                f"tier thresholds must be ordered: "
                f"{elevated}/{severe}/{critical}"
            )
        self.thresholds = (elevated, severe, critical)
        self.min_interval_s = min_interval_s
        self._clock = clock
        self._sources: dict[str, tuple[Callable[[], float], float]] = {}
        self._cached: Optional[PressureReading] = None
        self._cached_at = float("-inf")
        # tier-transition log (bounded): what the SLO report cites as
        # overload context — "submit→ack burned through its budget
        # WHILE pressure sat at severe" is the sentence an operator
        # needs, and it requires the WHEN of each tier change
        self._last_tier = TIER_NOMINAL
        self.transitions: deque = deque(maxlen=64)
        self.transition_counts = [0, 0, 0, 0]

    # ------------------------------------------------------------------

    def add_source(self, name: str, sample: Callable[[], float],
                   capacity: float) -> None:
        """Register (or replace) a depth source. ``capacity`` is the
        depth that counts as saturated (ratio 1.0 = CRITICAL)."""
        if capacity <= 0:
            raise ValueError(f"capacity must be > 0, got {capacity}")
        self._sources[name] = (sample, float(capacity))
        self._cached = None

    def ensure_source(self, name: str, sample: Callable[[], float],
                      capacity: float) -> None:
        """add_source unless ``name`` is already registered — default
        wiring must not clobber an operator-supplied source."""
        if name not in self._sources:
            self.add_source(name, sample, capacity)

    def remove_source(self, name: str) -> None:
        self._sources.pop(name, None)
        self._cached = None

    @property
    def sources(self) -> tuple[str, ...]:
        return tuple(self._sources)

    # ------------------------------------------------------------------

    def tier_of(self, value: float) -> int:
        elevated, severe, critical = self.thresholds
        if value >= critical:
            return TIER_CRITICAL
        if value >= severe:
            return TIER_SEVERE
        if value >= elevated:
            return TIER_ELEVATED
        return TIER_NOMINAL

    def sample(self) -> PressureReading:
        now = self._clock()
        if (
            self._cached is not None
            and now - self._cached_at < self.min_interval_s
        ):
            return self._cached
        by_source: dict[str, float] = {}
        worst = 0.0
        for name, (fn, capacity) in self._sources.items():
            try:
                ratio = max(0.0, float(fn())) / capacity
            except Exception:  # noqa: BLE001 - a dead source reads 0
                # a sampling fault must not take the admission gate
                # down with it; the source simply stops contributing —
                # but a silently-dead source under-reports pressure
                # forever, so count every faulted sample
                _M_SOURCE_ERRORS.labels(source=name).inc()
                ratio = 0.0
            by_source[name] = ratio
            _M_SOURCE.labels(source=name).set(ratio)
            if ratio > worst:
                worst = ratio
        reading = PressureReading(
            value=worst, tier=self.tier_of(worst), by_source=by_source,
        )
        _M_PRESSURE.set(worst)
        _M_TIER.set(reading.tier)
        if reading.tier != self._last_tier:
            self.transitions.append(
                (now, self._last_tier, reading.tier)
            )
            self.transition_counts[reading.tier] += 1
            _M_TRANSITIONS.labels(
                to=TIER_NAMES[reading.tier]).inc()
            self._last_tier = reading.tier
        self._cached = reading
        self._cached_at = now
        return reading

    def tier(self) -> int:
        return self.sample().tier

    def context(self) -> dict:
        """Overload context for SLO reports (SloEngine.add_context):
        current tier + the recent transition trail."""
        reading = self.sample()
        return {
            "tier": reading.tier,
            "tier_name": reading.tier_name,
            "value": round(reading.value, 4),
            "by_source": {
                k: round(v, 4) for k, v in reading.by_source.items()
            },
            "transition_counts": {
                TIER_NAMES[i]: c
                for i, c in enumerate(self.transition_counts) if c
            },
            "recent_transitions": [
                {"t": t, "from": TIER_NAMES[a], "to": TIER_NAMES[b]}
                for t, a, b in list(self.transitions)[-8:]
            ],
        }
