"""Flowed-document binding over SharedString — the webflow-class
integration layer.

Reference: examples/data-objects/webflow/src/document/index.ts — the
reference's richest editor sample: a FLOWED document where block
structure (paragraphs, line breaks) and inline structure (nested
begin/end tag ranges) are all merge-tree MARKERS riding the same
sequenced string as the text, formatting is css-class token lists
applied as annotates, and removal keeps begin/end tag PAIRS consistent
(removing a begin tag removes its paired end tag and vice versa —
index.ts:248-270's remove walk). Next to ``richtext.py`` (the
prosemirror-class binding) this adds the marker-pair machinery and a
much annotate/marker-heavier op mix, which is exactly what VERDICT r4
next #9 wants as a second kernel workload generator.

Model:

- text: flat SharedString characters;
- blocks: ``MARKER_PARAGRAPH`` / ``MARKER_LINEBREAK`` markers
  (tileLabels paragraph/lineBreak, index.ts:154-156);
- inline tag ranges: ``MARKER_TAG_BEGIN``/``MARKER_TAG_END`` marker
  PAIRS sharing a ``pairId`` prop, begin carrying ``tag`` (em/strong/
  span/h1...); ranges nest (index.ts:158 rangeLabels beginTags);
- css classes: the ``class`` annotate prop holds a space-joined token
  list; add/remove reads each covered span's current tokens and
  annotates the updated list (util/tokenlist.ts semantics over
  annotate LWW);
- comments: an interval collection, endpoints slide with the text.

``remove()`` preserves pair consistency the way the reference does:
after removing the range, begin tags whose partner survived outside
the range (and vice versa) get their orphaned partner removed too —
each as its own sequenced op, so replicas converge by merge-tree
semantics alone.
"""
from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Optional

# paragraph markers are a WIRE contract both bindings read off the
# same SharedString — one definition (richtext owns it)
from .richtext import MARKER_PARAGRAPH  # noqa: F401 (re-exported)

MARKER_LINEBREAK = 101
MARKER_TAG_BEGIN = 102
MARKER_TAG_END = 103

# the four kernel property channels this binding owns (DocStream
# intern_prop raises past PROP_CHANNELS=4: tag, pairId, class, heading)
PROP_TAG = "tag"
PROP_PAIR = "pairId"
PROP_CLASS = "class"
PROP_HEADING = "heading"

TAGS = ("em", "strong", "code", "span", "h1", "h2")


# ----------------------------------------------------------------------
# pair machinery shared by the binding and the bench-corpus stream
# generator (testing.record_flow_stream) — ONE copy of the
# index.ts:248 orphan-cleanup walk


def marker_positions(span_content, length: int, ref_type: int,
                     pair_id) -> list[int]:
    """Positions of pair markers with ``pair_id`` in the visible doc.
    ``span_content(a, b)`` is mergetree.span_content."""
    out, acc = [], 0
    for item in span_content(0, length):
        if item[0] == "text":
            acc += len(item[1])
            continue
        _, rt, props = item
        if rt == ref_type and (props or {}).get(PROP_PAIR) == pair_id:
            out.append(acc)
        acc += 1
    return out


def pair_consistent_remove(span_content, remove_fn,
                           start: int, end: int) -> None:
    """Remove [start, end), then remove tag partners the removal
    orphaned (index.ts:248-270): a begin whose end died keeps no
    range open; an end whose begin died closes nothing. Each removal
    is its own sequenced op, so replicas converge by merge-tree
    semantics alone. ``length`` is re-derived per pass by walking the
    visible content (positions shift after every removal)."""
    removed_begins: list = []
    removed_ends: list = []
    for item in span_content(start, end):
        if item[0] != "marker":
            continue
        _, rt, props = item
        pid = (props or {}).get(PROP_PAIR)
        if pid is None:
            continue
        if rt == MARKER_TAG_BEGIN:
            removed_begins.append(pid)
        elif rt == MARKER_TAG_END:
            removed_ends.append(pid)
    remove_fn(start, end)
    # span_content clamps its end bound itself, so the whole-doc scans
    # just pass a sentinel instead of recomputing the length per pass
    for pid in removed_begins:
        for pos in marker_positions(
                span_content, 1 << 30, MARKER_TAG_END, pid):
            remove_fn(pos, pos + 1)
    for pid in removed_ends:
        for pos in marker_positions(
                span_content, 1 << 30, MARKER_TAG_BEGIN, pid):
            remove_fn(pos, pos + 1)


@dataclass
class FlowBlock:
    """One rendered block: paragraph/lineBreak boundary + runs of
    (text, open-tag tuple, css-class frozenset)."""

    kind: str                      # "p" | "br"
    heading: Optional[int] = None
    runs: list = field(default_factory=list)

    @property
    def text(self) -> str:
        return "".join(t for t, _, _ in self.runs)


class FlowDocument:
    """One user's flowed-document session over a shared string."""

    def __init__(self, string, user: str = "user"):
        self.string = string
        self.user = user

    # ------------------------------------------------------------------

    @property
    def length(self) -> int:
        return self.string.get_length()

    def _items(self, start=0, end=None):
        if end is None:
            end = self.length
        return self.string.client.mergetree.span_content(start, end)

    def insert_text(self, pos: int, text: str,
                    classes: Optional[set] = None) -> None:
        props = {PROP_CLASS: " ".join(sorted(classes))} \
            if classes else None
        self.string.insert_text(pos, text, props)

    def insert_paragraph(self, pos: int,
                         heading: Optional[int] = None) -> None:
        props = {PROP_HEADING: heading} if heading else None
        self.string.insert_marker(pos, MARKER_PARAGRAPH, props)

    def insert_line_break(self, pos: int) -> None:
        self.string.insert_marker(pos, MARKER_LINEBREAK)

    def insert_tags(self, start: int, end: int, tag: str) -> str:
        """Wrap [start, end) in a begin/end tag pair (index.ts:309
        insertTags): two markers sharing a pairId; the end marker goes
        in first so the begin insert doesn't shift its position."""
        assert tag in TAGS, tag
        # uuid, not a process-local counter: two processes editing the
        # same doc as the same user must never mint colliding pairIds
        # (partner matching is by pairId alone — intervals.py uses the
        # same scheme for interval ids)
        pair_id = uuid.uuid4().hex
        self.string.insert_marker(
            end, MARKER_TAG_END, {PROP_PAIR: pair_id})
        self.string.insert_marker(
            start, MARKER_TAG_BEGIN,
            {PROP_TAG: tag, PROP_PAIR: pair_id})
        return pair_id

    # ------------------------------------------------------------------
    # pair-consistent removal (index.ts:248-270)

    def remove(self, start: int, end: int) -> None:
        """Remove [start, end); then remove tag partners orphaned by
        it — a begin whose end died keeps no range open, an end whose
        begin died closes nothing. (Shared walk: the bench corpus
        generator drives the SAME algorithm at the merge level —
        ``pair_consistent_remove``.)"""
        pair_consistent_remove(
            self.string.client.mergetree.span_content,
            self.string.remove_text, start, end,
        )

    # ------------------------------------------------------------------
    # css class token lists (util/tokenlist.ts over annotate LWW)

    def add_css_class(self, start: int, end: int, *tokens: str) -> None:
        self._update_classes(start, end, set(tokens), set())

    def remove_css_class(self, start: int, end: int,
                         *tokens: str) -> None:
        self._update_classes(start, end, set(), set(tokens))

    def _update_classes(self, start: int, end: int,
                        add: set, drop: set) -> None:
        spans = self.string.client.mergetree.span_props(
            start, end, [PROP_CLASS]
        )
        for lo, hi, old in spans:
            have = set((old[PROP_CLASS] or "").split()) \
                if old[PROP_CLASS] else set()
            new = (have | add) - drop
            if new == have:
                continue
            self.string.annotate_range(
                lo, hi,
                {PROP_CLASS: " ".join(sorted(new)) or None},
            )

    # ------------------------------------------------------------------
    # comments (interval collection)

    def add_comment(self, start: int, end: int, text: str):
        """Anchor a comment to DOC positions [start, end) — end
        EXCLUSIVE like every range op here. Interval anchors attach to
        characters, so the END anchor is the LAST covered position
        (end-1); ``comments()`` therefore reports inclusive endpoints
        and callers quote with ``text_span(start, end + 1)``."""
        comments = self.string.get_interval_collection("comments")
        end_anchor = max(start, min(end - 1, max(self.length - 1, 0)))
        return comments.add(start, end_anchor, props={
            "author": self.user, "text": text,
        })

    def comments(self) -> list[dict]:
        comments = self.string.get_interval_collection("comments")
        out = []
        for iv in comments:
            lo, hi = comments.endpoints(iv)
            if lo < 0:
                continue
            out.append({"id": iv.interval_id, "start": lo,
                        "end": hi, **dict(iv.props or {})})
        return sorted(out, key=lambda c: (c["start"], c["id"]))

    # ------------------------------------------------------------------
    # view model

    def render(self) -> list[FlowBlock]:
        """Blocks with (text, open tags, classes) runs; unmatched tag
        markers (concurrent-removal orphans) are skipped exactly like
        the reference's renderer ignores unpaired tags."""
        # pass 1: which pairIds have BOTH markers visible
        begins, ends = set(), set()
        for item in self._items():
            if item[0] != "marker":
                continue
            _, rt, props = item
            pid = (props or {}).get(PROP_PAIR)
            if rt == MARKER_TAG_BEGIN:
                begins.add(pid)
            elif rt == MARKER_TAG_END:
                ends.add(pid)
        paired = begins & ends
        # per-POSITION class sets (text and markers both occupy one
        # position, so span_props offsets line up with the walk)
        classes_at: list[frozenset] = []
        for lo, hi, old in self.string.client.mergetree.span_props(
                0, self.length, [PROP_CLASS]):
            tok = frozenset((old[PROP_CLASS] or "").split())
            classes_at.extend([tok] * (hi - lo))
        blocks = [FlowBlock(kind="p")]
        open_tags: list[tuple] = []  # (pairId, tag)
        acc = 0
        for item in self._items():
            if item[0] == "text":
                text = item[1]
                tags = tuple(t for _, t in open_tags)
                # split the run wherever the class set changes
                j = 0
                while j < len(text):
                    tok = classes_at[acc + j]
                    k = j + 1
                    while k < len(text) \
                            and classes_at[acc + k] == tok:
                        k += 1
                    blocks[-1].runs.append((text[j:k], tags, tok))
                    j = k
                acc += len(text)
                continue
            _, rt, props = item
            props = props or {}
            if rt == MARKER_PARAGRAPH:
                blocks.append(FlowBlock(
                    kind="p", heading=props.get(PROP_HEADING)))
            elif rt == MARKER_LINEBREAK:
                blocks.append(FlowBlock(kind="br"))
            elif rt == MARKER_TAG_BEGIN:
                if props.get(PROP_PAIR) in paired:
                    open_tags.append(
                        (props.get(PROP_PAIR), props.get(PROP_TAG)))
            elif rt == MARKER_TAG_END:
                pid = props.get(PROP_PAIR)
                open_tags = [t for t in open_tags if t[0] != pid]
            acc += 1
        return blocks

    def plain_text(self) -> str:
        return "".join(
            item[1] for item in self._items() if item[0] == "text"
        )

    def doc_pos(self, text_index: int) -> int:
        """Map a plain-text index to a DOC position (markers occupy
        positions; richtext.py:304 has the same mapping)."""
        acc = 0
        for item in self._items():
            if item[0] == "text":
                if text_index < len(item[1]):
                    return acc + text_index
                text_index -= len(item[1])
                acc += len(item[1])
            else:
                acc += 1
        return acc

    def text_span(self, start: int, end: int) -> str:
        """Text characters within DOC positions [start, end)."""
        return "".join(
            item[1] for item in self._items(start, end)
            if item[0] == "text"
        )

    def signature(self):
        return self.string.signature()


# ----------------------------------------------------------------------
# workload generator (the second kernel stress source)


def flow_workload(doc: FlowDocument, rng, steps: int) -> None:
    """Webflow-mix driver: typing plus MUCH heavier marker and
    annotate pressure than the prosemirror mix — tag-pair inserts,
    removes that cross pair boundaries, css token-list churn, comment
    intervals, block splits."""
    words = ("flow", "tensor", "lattice", "quorum", "spline", "glyph")
    for _ in range(steps):
        roll = rng.random()
        n = doc.length
        if roll < 0.30 or n < 4:
            pos = rng.randint(0, n)
            classes = {rng.choice(("hero", "note"))} \
                if rng.random() < 0.3 else None
            doc.insert_text(pos, rng.choice(words) + " ", classes)
        elif roll < 0.45:
            a = rng.randrange(n - 2)
            b = rng.randint(a + 1, min(n, a + 9))
            doc.insert_tags(a, b, rng.choice(TAGS))
        elif roll < 0.60:
            a = rng.randrange(n - 2)
            b = rng.randint(a + 1, min(n, a + 7))
            doc.remove(a, b)  # may cross tag pairs: partner cleanup
        elif roll < 0.80:
            a = rng.randrange(n - 2)
            b = rng.randint(a + 1, min(n, a + 10))
            if rng.random() < 0.6:
                doc.add_css_class(a, b, rng.choice(
                    ("hot", "cold", "muted", "alert")))
            else:
                doc.remove_css_class(a, b, rng.choice(
                    ("hot", "cold", "muted", "alert")))
        elif roll < 0.90:
            pos = rng.randint(0, n)
            if rng.random() < 0.5:
                doc.insert_paragraph(
                    pos, heading=rng.choice((None, 1, 2)))
            else:
                doc.insert_line_break(pos)
        else:
            a = rng.randrange(n - 2)
            doc.add_comment(a, rng.randint(a + 1, min(n, a + 6)),
                            f"c{rng.randrange(99)}")
