"""DataObject: the "write a Fluid object" authoring API.

Reference: packages/framework/aqueduct/src/data-objects —
``PureDataObject`` (pureDataObject.ts:33) and ``DataObject``
(dataObject.ts:25): a user subclass over a datastore with a private
root SharedMap, lifecycle hooks, and a factory
(``DataObjectFactory``) that registers it like any channel type.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Optional

from ..models.map import SharedMap

if TYPE_CHECKING:  # pragma: no cover
    from ..runtime.container_runtime import ContainerRuntime
    from ..runtime.datastore import DataStoreRuntime

ROOT_MAP_ID = "root"


class PureDataObject:
    """pureDataObject.ts:33 — lifecycle base. Subclasses override the
    ``initializing_*`` hooks; ``has_initialized`` runs on every load."""

    def __init__(self, datastore: "DataStoreRuntime"):
        self.datastore = datastore

    # ---- lifecycle hooks (subclass surface)

    def initializing_first_time(self) -> None:
        """Called exactly once, on the client that creates the object."""

    def initializing_from_existing(self) -> None:
        """Called when loading an object someone else created."""

    def has_initialized(self) -> None:
        """Called after either initialize path, every load."""


class DataObject(PureDataObject):
    """dataObject.ts:25 — PureDataObject + a root SharedMap."""

    @property
    def root(self) -> SharedMap:
        return self.datastore.get_channel(ROOT_MAP_ID)


class DataObjectFactory:
    """aqueduct's DataObjectFactory: creates/loads the datastore and
    runs the lifecycle. ``object_type`` names the datastore id prefix
    the same way the reference uses registry types."""

    def __init__(self, object_type: str, object_class=DataObject):
        self.object_type = object_type
        self.object_class = object_class

    def create(self, runtime: "ContainerRuntime",
               datastore_id: Optional[str] = None,
               root: bool = True) -> DataObject:
        ds = runtime.create_datastore(
            datastore_id or self.object_type, root=root
        )
        if issubclass(self.object_class, DataObject):
            ds.create_channel("sharedmap", ROOT_MAP_ID)
        obj = self.object_class(ds)
        obj.initializing_first_time()
        obj.has_initialized()
        return obj

    def load(self, runtime: "ContainerRuntime",
             datastore_id: Optional[str] = None) -> DataObject:
        ds = runtime.get_datastore(datastore_id or self.object_type)
        obj = self.object_class(ds)
        obj.initializing_from_existing()
        obj.has_initialized()
        return obj
