"""Framework/API layer: the app-facing surface.

Reference analogue: packages/framework/* (aqueduct, fluid-static,
undo-redo) + the service clients (tinylicious-client/azure-client).
"""
from .clients import ContainerServices, LocalServiceClient
from .data_object import DataObject, DataObjectFactory, PureDataObject
from .fluid_static import FluidContainer
from .helpers import (
    AgentScheduler,
    OldestClientObserver,
    RequestHandlerError,
    RequestParser,
    build_request_handler,
    create_shared_map_with_interception,
    create_shared_string_with_interception,
    datastore_channel_handler,
)
from .undo_redo import (
    SharedMapUndoRedoHandler,
    SharedStringUndoRedoHandler,
    UndoRedoStackManager,
)

__all__ = [
    "AgentScheduler",
    "ContainerServices",
    "DataObject",
    "DataObjectFactory",
    "FluidContainer",
    "LocalServiceClient",
    "OldestClientObserver",
    "RequestHandlerError",
    "RequestParser",
    "build_request_handler",
    "create_shared_map_with_interception",
    "create_shared_string_with_interception",
    "datastore_channel_handler",
    "PureDataObject",
    "SharedMapUndoRedoHandler",
    "SharedStringUndoRedoHandler",
    "UndoRedoStackManager",
]
