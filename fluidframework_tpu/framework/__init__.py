"""Framework/API layer: the app-facing surface.

Reference analogue: packages/framework/* (aqueduct, fluid-static,
undo-redo) + the service clients (tinylicious-client/azure-client).
"""
from .clients import ContainerServices, LocalServiceClient
from .data_object import DataObject, DataObjectFactory, PureDataObject
from .fluid_static import FluidContainer
from .undo_redo import (
    SharedMapUndoRedoHandler,
    SharedStringUndoRedoHandler,
    UndoRedoStackManager,
)

__all__ = [
    "ContainerServices",
    "DataObject",
    "DataObjectFactory",
    "FluidContainer",
    "LocalServiceClient",
    "PureDataObject",
    "SharedMapUndoRedoHandler",
    "SharedStringUndoRedoHandler",
    "UndoRedoStackManager",
]
