"""Undo/redo over DDS deltas.

Reference: packages/framework/undo-redo/src —
``UndoRedoStackManager`` (undoRedoStackManager.ts): operations are
groups of revertibles; reverting replays through the DDS as ordinary
local edits, which the handlers capture onto the *other* stack (undo
while undoing lands on redo, and vice versa).
``SharedMapUndoRedoHandler`` (mapHandler.ts) and
``SharedSegmentSequenceUndoRedoHandler`` (sequenceHandler.ts) — the
sequence handler anchors ranges with sliding local references so
concurrent remote edits move the undo target instead of corrupting it.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Optional, Protocol

from ..models.map import SharedMap
from ..models.mergetree.localref import DETACHED_POSITION
from ..models.mergetree.ops import ReferenceType

if TYPE_CHECKING:  # pragma: no cover
    from ..models.sharedstring import SharedString


class Revertible(Protocol):
    def revert(self) -> None: ...


class MapValueRevertible:
    """Undo of one map set/delete: restore the previous value."""

    def __init__(self, shared_map: SharedMap, key: str, previous):
        self.map = shared_map
        self.key = key
        self.previous = previous

    def revert(self) -> None:
        if self.previous is SharedMap._MISSING:
            self.map.delete(self.key)
        else:
            self.map.set(self.key, self.previous)


class MapClearRevertible:
    def __init__(self, shared_map: SharedMap, previous: dict):
        self.map = shared_map
        self.previous = previous

    def revert(self) -> None:
        for key, value in self.previous.items():
            self.map.set(key, value)


class _TrackingGroup:
    """Follows tracked segments through splits: merge-tree appends
    split tails to every entry in ``segment.groups`` (the reference's
    TrackingGroup mechanism, used by its sequence undo handler)."""

    __slots__ = ("segments",)

    def __init__(self) -> None:
        self.segments: list = []


class StringInsertRevertible:
    """Undo of a text/marker insert: remove exactly the inserted
    segments (tracked through splits), never remote content that
    landed inside the range afterwards."""

    def __init__(self, string: "SharedString", pos: int, length: int):
        self.string = string
        self.track = _TrackingGroup()
        tree = string.client.mergetree
        cur = tree.collab.current_seq
        viewer = tree.collab.client_id
        acc = 0
        end = pos + length
        for seg in tree.segments:
            if acc >= end:
                break
            seg_len = tree._length_at(seg, cur, viewer) or 0
            if seg_len and acc + seg_len > pos:
                self.track.segments.append(seg)
                seg.groups.append(self.track)
            acc += seg_len

    def revert(self) -> None:
        tree = self.string.client.mergetree
        cur = tree.collab.current_seq
        viewer = tree.collab.client_id
        for seg in list(self.track.segments):
            seg.groups = [g for g in seg.groups if g is not self.track]
            if seg.removed:
                continue  # someone else already removed it
            length = tree._length_at(seg, cur, viewer)
            if not length:
                continue
            start = tree.get_offset(seg, cur, viewer)
            self.string.remove_text(start, start + length)
        self.track.segments.clear()


class StringRemoveRevertible:
    """Undo of a removal: re-insert the captured span (text runs AND
    markers, position-accurate) where the removal point slid to."""

    def __init__(self, string: "SharedString", pos: int,
                 removed: list[tuple]):
        # constructed AFTER the removal applied: anchor the surviving
        # character just before the removal point and re-insert after
        # it (a start-of-document removal re-inserts at 0)
        self.string = string
        self.removed = removed
        self.ref = (
            string.client.create_reference(
                pos - 1, ReferenceType.SLIDE_ON_REMOVE
            ) if pos > 0 and string.get_length() >= pos else None
        )

    def revert(self) -> None:
        if self.ref is None:
            pos = 0
        else:
            anchor = self.string.client.reference_position(self.ref)
            pos = (
                self.string.get_length() if anchor == DETACHED_POSITION
                else anchor + 1
            )
        for item in self.removed:
            if item[0] == "text":
                self.string.insert_text(pos, item[1])
                pos += len(item[1])
            else:  # ("marker", ref_type, props)
                self.string.insert_marker(pos, item[1], item[2])
                pos += 1


class StringAnnotateRevertible:
    """Undo of an annotate: restore each subrange's prior values
    (None restores 'key absent')."""

    def __init__(self, string: "SharedString",
                 prior: list[tuple[int, int, dict]]):
        self.string = string
        client = string.client
        self.spans = [
            (client.create_reference(lo, ReferenceType.SLIDE_ON_REMOVE),
             hi - lo, dict(old))
            for lo, hi, old in prior
        ]

    def revert(self) -> None:
        for ref, length, old in self.spans:
            start = self.string.client.reference_position(ref)
            if start == DETACHED_POSITION:
                continue
            self.string.annotate_range(start, start + length, old)


class UndoRedoStackManager:
    """undoRedoStackManager.ts — operation-grouped undo/redo."""

    NORMAL, UNDOING, REDOING = range(3)

    def __init__(self) -> None:
        self._undo: list[list[Revertible]] = []
        self._redo: list[list[Revertible]] = []
        self._current: Optional[list[Revertible]] = None
        self._mode = self.NORMAL

    # ---- capture

    def push_revertible(self, revertible: Revertible) -> None:
        if self._mode == self.UNDOING:
            self._redo.append([revertible])
            return
        if self._mode == self.REDOING:
            self._undo.append([revertible])
            return
        if self._current is None:
            self._current = []
            self._undo.append(self._current)
        self._current.append(revertible)
        self._redo.clear()  # a fresh edit invalidates the redo branch

    def close_current_operation(self) -> None:
        """Group boundary: edits after this land in a new operation."""
        self._current = None

    # ---- stacks

    @property
    def undo_count(self) -> int:
        return len(self._undo)

    @property
    def redo_count(self) -> int:
        return len(self._redo)

    def undo_operation(self) -> bool:
        self.close_current_operation()
        if not self._undo:
            return False
        operation = self._undo.pop()
        self._mode = self.UNDOING
        try:
            # captured inverse edits of this op merge into ONE redo op
            marker = len(self._redo)
            for revertible in reversed(operation):
                revertible.revert()
            merged = [r for group in self._redo[marker:] for r in group]
            del self._redo[marker:]
            if merged:
                self._redo.append(merged)
        finally:
            self._mode = self.NORMAL
        return True

    def redo_operation(self) -> bool:
        self.close_current_operation()
        if not self._redo:
            return False
        operation = self._redo.pop()
        self._mode = self.REDOING
        try:
            marker = len(self._undo)
            for revertible in reversed(operation):
                revertible.revert()
            merged = [r for group in self._undo[marker:] for r in group]
            del self._undo[marker:]
            if merged:
                self._undo.append(merged)
        finally:
            self._mode = self.NORMAL
        return True


class SharedMapUndoRedoHandler:
    """mapHandler.ts — captures local map edits as revertibles."""

    def __init__(self, stack: UndoRedoStackManager,
                 shared_map: SharedMap):
        self.stack = stack
        self.map = shared_map
        self._offs = [
            shared_map.on("valueChanged", self._on_value_changed),
            shared_map.on("cleared", self._on_cleared),
        ]

    def dispose(self) -> None:
        for off in self._offs:
            off()

    def _on_value_changed(self, key, local, previous=None) -> None:
        if local:
            self.stack.push_revertible(
                MapValueRevertible(self.map, key, previous)
            )

    def _on_cleared(self, local, previous=None) -> None:
        if local:
            self.stack.push_revertible(
                MapClearRevertible(self.map, previous or {})
            )


class SharedStringUndoRedoHandler:
    """sequenceHandler.ts — captures local string edits."""

    def __init__(self, stack: UndoRedoStackManager,
                 string: "SharedString"):
        self.stack = stack
        self.string = string
        self._off = string.on("localEdit", self._on_local_edit)

    def dispose(self) -> None:
        self._off()

    def _on_local_edit(self, kind: str, pos: int, payload) -> None:
        if kind == "insert":
            self.stack.push_revertible(
                StringInsertRevertible(self.string, pos, payload)
            )
        elif kind == "remove":
            self.stack.push_revertible(
                StringRemoveRevertible(self.string, pos, payload)
            )
        elif kind == "annotate":
            self.stack.push_revertible(
                StringAnnotateRevertible(self.string, payload)
            )
