"""Rich-text editor binding over SharedString — the prosemirror-class
integration layer.

Reference: examples/data-objects/prosemirror (and webflow/monaco) —
the reference's editor samples prove the DDS surface carries a real
editor: a document model richer than a flat string (paragraphs,
styled runs), LOCAL editor state that survives remote edits (cursor /
selection mapped through concurrent inserts and removes), formatting
as annotations, comments as interval collections, and reconnect
without losing anything. This module is that binding rebuilt for the
TPU repo's SharedString, plus a deterministic workload generator so
the same surface doubles as a merge-kernel stress source (VERDICT r3
next-round #10).

Model (what a view layer consumes):

- the document is a flat SharedString; PARAGRAPH boundaries are
  markers (``MARKER_PARAGRAPH``) carrying block props (heading level);
- character formatting (bold/italic/comment-highlight) is annotate
  props on ranges — LWW per key, concurrency-safe by sequencing;
- the CURSOR and SELECTION are local reference positions
  (slide-on-remove), so remote edits move them exactly the way a
  prosemirror position mapping would;
- comments are interval-collection entries whose endpoints slide with
  the text (intervalCollection.ts semantics).

``render()`` produces ``[Paragraph(style, runs=[(text, marks)])]`` —
position-faithful, so a real view could diff it.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ..models.mergetree import ReferenceType

MARKER_PARAGRAPH = 100

# annotate keys the binding owns
MARK_KEYS = ("bold", "italic", "code")
HEADING_KEY = "heading"


@dataclass
class Paragraph:
    style: dict
    runs: list = field(default_factory=list)  # [(text, frozenset marks)]

    @property
    def text(self) -> str:
        return "".join(t for t, _ in self.runs)


class RichTextEditor:
    """One user's editor session over a shared string channel."""

    def __init__(self, string, user: Optional[str] = None):
        self.string = string
        self.user = user or "user"
        self._cursor_ref = None
        self._anchor_ref = None  # selection anchor (None = caret)
        self.marks: set[str] = set()  # active toggle marks for typing
        self.set_cursor(self.length)

    # ------------------------------------------------------------------
    # cursor / selection (local refs: stable through remote edits)

    @property
    def length(self) -> int:
        return self.string.get_length()

    def _make_ref(self, pos: int):
        """A position anchor. References attach to characters, so the
        end-of-document caret anchors AFTER the last character
        ((ref, bias=1)); an empty document has no anchor (None =
        document end)."""
        if self.length == 0:
            return None
        if pos >= self.length:
            return (self.string.create_position_reference(
                self.length - 1, ReferenceType.SLIDE_ON_REMOVE), 1)
        return (self.string.create_position_reference(
            pos, ReferenceType.SLIDE_ON_REMOVE), 0)

    def _ref_pos(self, ref) -> int:
        if ref is None:
            return self.length
        anchor, bias = ref
        pos = self.string.local_reference_position(anchor)
        if pos < 0:
            return self.length
        return min(pos + bias, self.length)

    @property
    def cursor(self) -> int:
        return self._ref_pos(self._cursor_ref)

    def set_cursor(self, pos: int, extend: bool = False) -> None:
        pos = max(0, min(pos, self.length))
        if extend and self._anchor_ref is None:
            self._anchor_ref = self._cursor_ref
        elif not extend:
            self._anchor_ref = None
        self._cursor_ref = self._make_ref(pos)

    @property
    def selection(self) -> tuple[int, int]:
        """(start, end) of the selection; collapsed => (cursor, cursor)."""
        c = self.cursor
        if self._anchor_ref is None:
            return c, c
        a = self._ref_pos(self._anchor_ref)
        return (min(a, c), max(a, c))

    # ------------------------------------------------------------------
    # editing commands

    def type_text(self, text: str) -> None:
        """Insert at the cursor (replacing any selection), applying
        the active toggle marks — prosemirror's storedMarks."""
        start, end = self.selection
        if end > start:
            self.string.remove_text(start, end)
        props = {k: True for k in self.marks} or None
        self.string.insert_text(start, text, props)
        self.set_cursor(start + len(text))

    def backspace(self) -> None:
        start, end = self.selection
        if end > start:
            self.string.remove_text(start, end)
            self.set_cursor(start)
        elif start > 0:
            self.string.remove_text(start - 1, start)
            self.set_cursor(start - 1)

    def split_paragraph(self, heading: Optional[int] = None) -> None:
        """Insert a paragraph boundary at the cursor (Enter)."""
        start, end = self.selection
        if end > start:
            self.string.remove_text(start, end)
        props = {HEADING_KEY: heading} if heading else None
        self.string.insert_marker(start, MARKER_PARAGRAPH, props)
        self.set_cursor(start + 1)

    def toggle_mark(self, mark: str) -> None:
        """Bold/italic/code over the selection; with a caret, toggles
        the stored mark for subsequent typing."""
        assert mark in MARK_KEYS, mark
        start, end = self.selection
        if end == start:
            if mark in self.marks:
                self.marks.discard(mark)
            else:
                self.marks.add(mark)
            return
        # turning_on considers TEXT positions only: a selection
        # spanning a paragraph marker must still clear a fully-marked
        # range (prosemirror's toggleMark ignores non-inline nodes)
        spans = self.string.client.mergetree.span_props(
            start, end, [mark]
        )
        texty = self._text_positions()
        turning_on = any(
            not old[mark] and any(texty[lo:hi])
            for lo, hi, old in spans
        )
        self.string.annotate_range(
            start, end, {mark: True if turning_on else None}
        )

    def set_heading(self, level: Optional[int]) -> None:
        """Set the heading level of the paragraph containing the
        cursor (annotates its leading marker; the document's first
        paragraph has no marker and stays body text)."""
        pos = self._paragraph_marker_before(self.cursor)
        if pos is None:
            return
        self.string.annotate_range(
            pos, pos + 1, {HEADING_KEY: level}
        )

    def add_comment(self, start: int, end: int, text: str):
        """Anchor a comment to [start, end): endpoints slide with
        concurrent edits (the interval collection). Endpoint anchors
        attach to characters; a comment reaching the document end
        anchors its end ON the last character with a +1 bias (same
        trick as the end-of-document caret), so the final character is
        never silently dropped from the range."""
        end_bias = 0
        if end >= self.length:
            end = max(self.length - 1, 0)
            end_bias = 1
        start = min(start, end)
        comments = self.string.get_interval_collection("comments")
        return comments.add(start, end, props={
            "author": self.user, "text": text,
            "endBias": end_bias,
        })

    def comments(self) -> list[dict]:
        out = []
        comments = self.string.get_interval_collection("comments")
        for iv in comments:
            lo, hi = comments.endpoints(iv)
            if lo < 0:
                continue  # both endpoints collapsed away
            props = dict(iv.props or {})
            hi += props.pop("endBias", 0)
            out.append({
                "id": iv.interval_id, "start": lo, "end": hi,
                **props,
            })
        return sorted(out, key=lambda c: (c["start"], c["id"]))

    # ------------------------------------------------------------------
    # view model

    def _paragraph_marker_before(self, pos: int) -> Optional[int]:
        items = self.string.client.mergetree.span_content(0, pos)
        acc = 0
        last = None
        for item in items:
            if item[0] == "text":
                acc += len(item[1])
            else:
                if item[1] == MARKER_PARAGRAPH:
                    last = acc
                acc += 1
        return last

    def render(self) -> list[Paragraph]:
        """Paragraph list with styled runs — the editor view model."""
        items = self.string.client.mergetree.span_content(
            0, self.length
        )
        paras = [Paragraph(style={})]
        for item in items:
            if item[0] == "marker":
                _, ref_type, props = item
                if ref_type == MARKER_PARAGRAPH:
                    style = {}
                    if props and props.get(HEADING_KEY):
                        style["heading"] = props[HEADING_KEY]
                    paras.append(Paragraph(style=style))
                continue
            # text runs carry uniform props per segment; re-read the
            # marks from span_props at run granularity
            paras[-1].runs.append((item[1], frozenset()))
        # second pass: stamp marks by position
        flat_marks = self._marks_by_position()
        pos = 0
        for p in paras:
            if p is not paras[0]:
                pos += 1  # the paragraph marker occupies one position
            new_runs: list = []
            for text, _ in p.runs:
                for ch in text:
                    m = flat_marks[pos]
                    if new_runs and new_runs[-1][1] == m:
                        new_runs[-1][0] += ch
                    else:
                        new_runs.append([ch, m])
                    pos += 1
            p.runs = [(t, m) for t, m in new_runs]
        return paras

    def _text_positions(self) -> list[bool]:
        """True at document positions holding text (False = marker)."""
        out: list[bool] = []
        for item in self.string.client.mergetree.span_content(
                0, self.length):
            if item[0] == "text":
                out.extend([True] * len(item[1]))
            else:
                out.append(False)
        return out

    def _marks_by_position(self) -> list[frozenset]:
        spans = self.string.client.mergetree.span_props(
            0, self.length, list(MARK_KEYS)
        )
        out = [frozenset()] * self.length
        for lo, hi, props in spans:
            m = frozenset(k for k in MARK_KEYS if props.get(k))
            for i in range(lo, hi):
                out[i] = m
        return out

    def plain_text(self) -> str:
        return self.string.get_text()

    def text_span(self, start: int, end: int) -> str:
        """Text content of a document-position range (markers occupy
        a position but contribute no text) — e.g. the quoted text of
        a comment's interval."""
        return "".join(
            item[1]
            for item in self.string.client.mergetree.span_content(
                start, end)
            if item[0] == "text"
        )

    def doc_pos(self, text_index: int) -> int:
        """Map an index into ``plain_text()`` (which excludes markers)
        to a document position (which counts each marker as one) —
        what ``set_cursor``/``add_comment`` expect. The editor-binding
        equivalent of prosemirror's position mapping between the DOM
        text and the document."""
        items = self.string.client.mergetree.span_content(
            0, self.length
        )
        doc = 0
        text = 0
        for item in items:
            if item[0] == "marker":
                doc += 1
                continue
            if text + len(item[1]) > text_index:
                return doc + (text_index - text)
            text += len(item[1])
            doc += len(item[1])
        return doc


# ----------------------------------------------------------------------
# deterministic workload generator (doubles as merge-kernel stress)


def editor_workload(editor: RichTextEditor, rng, steps: int) -> None:
    """Drive one editor with a realistic mix: typing bursts, bursty
    backspacing, formatting, paragraph splits, comments — the op
    pattern the merge kernel's config2 wants more of (same-client
    chains, concurrent storms, annotate ranges)."""
    words = ("collab", "merge", "tensor", "ink", "quorum", "ledger")
    for _ in range(steps):
        roll = rng.random()
        n = editor.length
        if roll < 0.45 or n == 0:
            editor.set_cursor(rng.randint(0, n))
            burst = rng.randint(1, 3)
            for _ in range(burst):
                editor.type_text(rng.choice(words) + " ")
        elif roll < 0.6:
            editor.set_cursor(rng.randint(0, n))
            for _ in range(rng.randint(1, 4)):
                editor.backspace()
        elif roll < 0.75 and n > 2:
            a = rng.randint(0, n - 2)
            editor.set_cursor(a)
            editor.set_cursor(
                rng.randint(a + 1, min(n, a + 12)), extend=True
            )
            editor.toggle_mark(rng.choice(MARK_KEYS))
            editor.set_cursor(editor.selection[1])
        elif roll < 0.85:
            editor.set_cursor(rng.randint(0, n))
            editor.split_paragraph(
                heading=rng.choice((None, 1, 2)))
        elif roll < 0.95 and n > 2:
            a = rng.randint(0, n - 2)
            editor.add_comment(
                a, rng.randint(a + 1, min(n, a + 8)),
                f"note-{rng.randint(0, 99)}",
            )
        else:
            editor.set_heading(rng.choice((None, 1, 2, 3)))
