"""Service clients: the app-facing entry points.

Reference: packages/framework/tinylicious-client
(``TinyliciousClient`` TinyliciousClient.ts:42) and
azure/packages/azure-client (``AzureClient`` AzureClient.ts:51) —
``create_container(schema)`` / ``get_container(id, schema)`` returning
a FluidContainer plus service audience.

``LocalServiceClient`` targets the in-proc LocalServer (the
tinylicious analogue); a production client would swap the driver
factory and keep this surface.
"""
from __future__ import annotations

import itertools
import uuid
from dataclasses import dataclass

from ..drivers.local_driver import LocalDocumentServiceFactory
from ..loader.container import Container
from ..service.local_server import LocalServer
from .fluid_static import FluidContainer


@dataclass
class ContainerServices:
    """Service-side facilities handed back with the container (the
    audience: who else is connected)."""

    audience: object


class _Audience:
    def __init__(self, container: Container):
        self._container = container

    def get_members(self) -> dict:
        return self._container.protocol.quorum.members

    @property
    def size(self) -> int:
        return len(self._container.protocol.quorum.members)


class LocalServiceClient:
    """TinyliciousClient.ts:42 shape over LocalServer."""

    def __init__(self, server: LocalServer | None = None,
                 user_id: str = "user"):
        self.server = server or LocalServer()
        self._factory = LocalDocumentServiceFactory(self.server)
        self._user_id = user_id
        self._counter = itertools.count()

    def _client_id(self) -> str:
        # uuid suffix: ids must be unique across client instances
        # sharing one server, or peers' ops read as local acks
        return (
            f"{self._user_id}-{next(self._counter)}-"
            f"{uuid.uuid4().hex[:8]}"
        )

    def create_container(self, schema: dict[str, str]
                         ) -> tuple[FluidContainer, ContainerServices, str]:
        """Create a new document; returns (container, services, id)."""
        document_id = uuid.uuid4().hex[:12]
        service = self._factory.create_document_service(document_id)
        container = Container.load(service, client_id=self._client_id())
        fluid = FluidContainer(container, schema, create=True)
        return fluid, ContainerServices(_Audience(container)), document_id

    def get_container(self, document_id: str, schema: dict[str, str]
                      ) -> tuple[FluidContainer, ContainerServices]:
        service = self._factory.create_document_service(document_id)
        container = Container.load(service, client_id=self._client_id())
        fluid = FluidContainer(container, schema, create=False)
        return fluid, ContainerServices(_Audience(container))
