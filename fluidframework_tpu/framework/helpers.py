"""Framework app-layer helpers: oldest-client observer, DDS
interceptions, request routing.

Reference packages (SURVEY §2.8):
- ``oldest-client-observer``: elects the longest-connected interactive
  client (join order over the quorum) and emits becameOldest /
  lostOldest — apps use it to run singleton work client-side without a
  server lease.
- ``dds-interceptions`` (packages/framework/dds-interceptions): wrap a
  SharedString/SharedMap so every LOCAL edit passes through an
  interception callback (the canonical use: stamping attribution /
  style props onto text as it is typed) while remote ops flow
  untouched.
- ``request-handler``: composable routers over container request
  paths (`/datastore/channel`), the RequestParser utilities.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

from ..utils.events import EventEmitter


# ----------------------------------------------------------------------
# oldest-client observer


class OldestClientObserver(EventEmitter):
    """Tracks whether THIS client is the oldest in the quorum
    (oldestClientObserver.ts). Oldest = earliest joined, which is the
    quorum's member insertion order; falls to the next client when the
    current oldest leaves."""

    def __init__(self, quorum, my_client_id: str):
        super().__init__()
        self._quorum = quorum
        self._my_id = my_client_id
        self._was_oldest = self.is_oldest()
        quorum.on("addMember", self._recheck)
        quorum.on("removeMember", self._recheck)

    def oldest_client_id(self) -> Optional[str]:
        members = self._quorum.members
        return next(iter(members), None)

    def is_oldest(self) -> bool:
        return self.oldest_client_id() == self._my_id

    def _recheck(self, *_args) -> None:
        now = self.is_oldest()
        if now and not self._was_oldest:
            self._was_oldest = True
            self.emit("becameOldest")
        elif not now and self._was_oldest:
            self._was_oldest = False
            self.emit("lostOldest")


# ----------------------------------------------------------------------
# DDS interceptions


class InterceptedSharedString:
    """SharedString wrapper applying a props interception to every
    LOCAL edit (createSharedStringWithInterception): e.g. stamp the
    current user/timestamp/style onto typed text. Reads and remote
    processing hit the underlying channel directly."""

    def __init__(self, string,
                 props_interceptor: Callable[[int, Optional[dict]],
                                             Optional[dict]]):
        self._string = string
        self._interceptor = props_interceptor

    def insert_text(self, pos: int, text: str,
                    props: Optional[dict] = None) -> None:
        self._string.insert_text(
            pos, text, self._interceptor(pos, props))

    def annotate_range(self, start: int, end: int,
                       props: dict) -> None:
        merged = self._interceptor(start, props)
        # an interceptor returning {} means "strip the props", not
        # "fall back to the originals" — only None defers
        self._string.annotate_range(
            start, end, merged if merged is not None else props)

    def __getattr__(self, name: str):  # reads + everything else
        return getattr(self._string, name)


class InterceptedSharedMap:
    """SharedMap wrapper passing every local set through the
    interceptor (createDirectoryWithInterception pattern): return a
    replacement value, or raise to veto the write."""

    def __init__(self, map_,
                 set_interceptor: Callable[[str, Any], Any]):
        self._map = map_
        self._interceptor = set_interceptor

    def set(self, key: str, value: Any) -> None:
        self._map.set(key, self._interceptor(key, value))

    def __getattr__(self, name: str):
        return getattr(self._map, name)


def create_shared_string_with_interception(string, props_interceptor):
    return InterceptedSharedString(string, props_interceptor)


def create_shared_map_with_interception(map_, set_interceptor):
    return InterceptedSharedMap(map_, set_interceptor)


# ----------------------------------------------------------------------
# request routing


class RequestParser:
    """Path-segment parser over container request urls
    (runtime-utils RequestParser)."""

    def __init__(self, url: str):
        self.url = url
        self.path_parts = [p for p in url.split("/") if p]

    @staticmethod
    def create(url: str) -> "RequestParser":
        return RequestParser(url)

    def is_leaf(self, elements: int) -> bool:
        return len(self.path_parts) == elements


class RequestHandlerError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


def build_request_handler(*handlers: Callable):
    """Compose handlers first-match-wins
    (buildRuntimeRequestHandler). Each handler takes
    (RequestParser, runtime) and returns a result or None."""

    def route(url: str, runtime) -> Any:
        parser = RequestParser(url)
        for handler in handlers:
            result = handler(parser, runtime)
            if result is not None:
                return result
        raise RequestHandlerError(404, f"no handler for {url!r}")

    return route


def datastore_channel_handler(parser: RequestParser, runtime) -> Any:
    """Default `/datastore[/channel]` resolution — the shape
    FluidHandle routes use (runtime/handles.py handle_to)."""
    if not parser.path_parts or len(parser.path_parts) > 2:
        return None  # trailing segments are NOT a match (strict 404)
    try:
        ds = runtime.get_datastore(parser.path_parts[0])
    except KeyError:
        return None
    if parser.is_leaf(1):
        return ds
    try:
        return ds.get_channel(parser.path_parts[1])
    except KeyError:
        return None


# ----------------------------------------------------------------------
# agent scheduler


class AgentScheduler(EventEmitter):
    """packages/framework/agent-scheduler: register named tasks with
    worker callbacks; exactly ONE connected client runs each task at a
    time (election rides the TaskManager DDS's sequenced volunteer
    queue), with automatic re-election when the assignee leaves.

    Events: ``picked(task_id)`` when this client wins a task,
    ``released(task_id)`` when it loses/abandons one.
    """

    def __init__(self, task_manager):
        super().__init__()
        self._tasks = task_manager
        self._workers: dict[str, Callable[[], None]] = {}
        self._running: set[str] = set()
        task_manager.on("assigned", self._on_change)
        task_manager.on("queueChanged", self._on_change)

    def register(self, task_id: str,
                 worker: Callable[[], None]) -> None:
        """Volunteer for ``task_id``; ``worker`` runs when (and only
        while) this client holds the assignment."""
        self._workers[task_id] = worker
        if not self._tasks.queued(task_id) \
                and not self._tasks.have_task(task_id):
            self._tasks.volunteer(task_id)
        self._maybe_start(task_id)

    def unregister(self, task_id: str) -> None:
        self._workers.pop(task_id, None)
        if task_id in self._running:
            self._running.discard(task_id)
            self.emit("released", task_id)
        self._tasks.abandon(task_id)

    def picked_tasks(self) -> list[str]:
        return sorted(self._running)

    def _maybe_start(self, task_id: str) -> None:
        if task_id in self._running:
            return
        if task_id in self._workers and self._tasks.have_task(task_id):
            self._running.add(task_id)
            self.emit("picked", task_id)
            self._workers[task_id]()

    def _on_change(self, task_id: str, *_):
        if task_id in self._running \
                and not self._tasks.have_task(task_id):
            self._running.discard(task_id)
            self.emit("released", task_id)
        else:
            self._maybe_start(task_id)
