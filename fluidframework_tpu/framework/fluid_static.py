"""FluidContainer: the simplified schema-first container API.

Reference: packages/framework/fluid-static/src —
``FluidContainer`` (fluidContainer.ts:201): apps declare
``initial_objects`` (name -> DDS type) and get them ready-made;
``create_dds`` makes additional dynamic channels referenced by handle.
"""
from __future__ import annotations

from typing import TYPE_CHECKING

from ..runtime.shared_object import SharedObject
from ..utils.events import EventEmitter

if TYPE_CHECKING:  # pragma: no cover
    from ..loader.container import Container

SCHEMA_DATASTORE = "initial-objects"


class FluidContainer(EventEmitter):
    """fluidContainer.ts:201 — schema-first facade over a loaded
    loader-layer Container."""

    def __init__(self, container: "Container", schema: dict[str, str],
                 create: bool):
        super().__init__()
        self._container = container
        self.schema = dict(schema)
        runtime = container.runtime
        if create:
            ds = runtime.create_datastore(SCHEMA_DATASTORE)
            for name, dds_type in schema.items():
                ds.create_channel(dds_type, name)
            container.flush()
        elif SCHEMA_DATASTORE not in runtime.datastores:
            # an empty schema produces no attach traffic, so the
            # store materializes lazily on loading clients
            runtime.create_datastore(SCHEMA_DATASTORE)
        self._datastore = runtime.get_datastore(SCHEMA_DATASTORE)
        container.on("connected", lambda: self.emit("connected"))
        container.on("disconnected", lambda: self.emit("disconnected"))

    @property
    def initial_objects(self) -> dict[str, SharedObject]:
        return {
            name: self._datastore.get_channel(name)
            for name in self.schema
        }

    @property
    def connected(self) -> bool:
        return self._container.connected

    @property
    def container(self) -> "Container":
        """The underlying loader container (advanced escape hatch)."""
        return self._container

    def create_dds(self, dds_type: str, channel_id: str) -> SharedObject:
        """Dynamically create an additional channel; store its handle
        in a reachable place or GC will collect it
        (fluid-static create flow)."""
        return self._datastore.create_channel(dds_type, channel_id)

    def disconnect(self) -> None:
        self._container.disconnect()

    def connect(self) -> None:
        self._container.connect()

    def dispose(self) -> None:
        self._container.close()
