"""Python face of the C++ scalar merge replayer (merge_replay.cpp).

Used by bench.py as the compiled-language baseline (the stand-in for
the reference's Node.js merge-tree — no Node runtime exists in this
image) and by tests as a third differential implementation next to the
Python oracle and the batched kernel.
"""
from __future__ import annotations

import ctypes
import time
from typing import Optional

import numpy as np

from ..ops.host_bridge import OP_FIELDS, DocStream
from ..ops.segment_table import NOT_REMOVED
from . import load_merge_replay

_MASK = (1 << 64) - 1


def encode_ops_array(stream: DocStream) -> np.ndarray:
    """[n_ops, 12] int32 row-major in OP_FIELDS order."""
    arr = np.zeros((len(stream.ops), len(OP_FIELDS)), np.int32)
    for i, op in enumerate(stream.ops):
        for j, f in enumerate(OP_FIELDS):
            arr[i, j] = op[f]
    return np.ascontiguousarray(arr)


def replay(ops_arr: np.ndarray, reps: int = 1
           ) -> Optional[tuple[int, int, float]]:
    """Replay one doc's stream ``reps`` times in C++; returns
    (checksum, live_chars, wall_seconds) or None if the native lib is
    unavailable."""
    lib = load_merge_replay()
    if lib is None:
        return None
    checksum = ctypes.c_uint64(0)
    live = ctypes.c_int64(0)
    ptr = ops_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    t0 = time.perf_counter()
    lib.merge_replay(ptr, ops_arr.shape[0], reps,
                     ctypes.byref(checksum), ctypes.byref(live))
    dt = time.perf_counter() - t0
    return checksum.value, live.value, dt


def table_checksum(table_np: dict[str, np.ndarray], doc: int) -> int:
    """FNV-1a per-character checksum of one doc's tip view from a
    fetched kernel table — bit-identical to merge_replay.cpp's
    Doc::checksum for parity assertions."""
    h = 1469598103934665603

    def mix(v: int, h: int) -> int:
        v &= _MASK  # two's-complement view of negatives
        for b in range(8):
            h ^= (v >> (8 * b)) & 0xFF
            h = (h * 1099511628211) & _MASK
        return h

    count = int(table_np["count"][doc])
    for i in range(count):
        if table_np["removed_seq"][doc, i] != NOT_REMOVED:
            continue
        op_id = int(table_np["op_id"][doc, i])
        op_off = int(table_np["op_off"][doc, i])
        is_marker = int(table_np["is_marker"][doc, i])
        props = [int(v) for v in table_np["prop"][doc, i]]
        for c in range(int(table_np["length"][doc, i])):
            h = mix(op_id, h)
            h = mix(op_off + c, h)
            h = mix(is_marker, h)
            for p in props:
                h = mix(p, h)
    return h
