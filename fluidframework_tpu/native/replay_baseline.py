"""Python face of the C++ scalar merge replayer (merge_replay.cpp).

Used by bench.py as the compiled-language baseline (the stand-in for
the reference's Node.js merge-tree — no Node runtime exists in this
image) and by tests as a third differential implementation next to the
Python oracle and the batched kernel.
"""
from __future__ import annotations

import ctypes
import time
from typing import Optional

import numpy as np

from ..ops.host_bridge import OP_FIELDS, DocStream
from ..ops.segment_table import NOT_REMOVED
from . import load_merge_replay

_MASK = (1 << 64) - 1


def encode_ops_array(stream: DocStream) -> np.ndarray:
    """[n_ops, 12] int32 row-major in OP_FIELDS order."""
    arr = np.zeros((len(stream.ops), len(OP_FIELDS)), np.int32)
    for i, op in enumerate(stream.ops):
        for j, f in enumerate(OP_FIELDS):
            arr[i, j] = op[f]
    return np.ascontiguousarray(arr)


def replay(ops_arr: np.ndarray, reps: int = 1
           ) -> Optional[tuple[int, int, float]]:
    """Replay one doc's stream ``reps`` times in C++; returns
    (checksum, live_chars, wall_seconds) or None if the native lib is
    unavailable."""
    lib = load_merge_replay()
    if lib is None:
        return None
    checksum = ctypes.c_uint64(0)
    live = ctypes.c_int64(0)
    ptr = ops_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    t0 = time.perf_counter()
    lib.merge_replay(ptr, ops_arr.shape[0], reps,
                     ctypes.byref(checksum), ctypes.byref(live))
    dt = time.perf_counter() - t0
    return checksum.value, live.value, dt


def table_checksum(table_np: dict[str, np.ndarray], doc: int) -> int:
    """FNV-1a per-character checksum of one doc's tip view from a
    fetched kernel table — bit-identical to merge_replay.cpp's
    Doc::checksum for parity assertions."""
    h = 1469598103934665603

    def mix(v: int, h: int) -> int:
        v &= _MASK  # two's-complement view of negatives
        for b in range(8):
            h ^= (v >> (8 * b)) & 0xFF
            h = (h * 1099511628211) & _MASK
        return h

    count = int(table_np["count"][doc])
    for i in range(count):
        if table_np["removed_seq"][doc, i] != NOT_REMOVED:
            continue
        op_id = int(table_np["op_id"][doc, i])
        op_off = int(table_np["op_off"][doc, i])
        is_marker = int(table_np["is_marker"][doc, i])
        props = [int(v) for v in table_np["prop"][doc, i]]
        for c in range(int(table_np["length"][doc, i])):
            h = mix(op_id, h)
            h = mix(op_off + c, h)
            h = mix(is_marker, h)
            for p in props:
                h = mix(p, h)
    return h


class MergeHostSession:
    """Incremental multi-document merge state in C++ — the host
    serving tier the full-service pipeline routes through on hosts
    without an accelerator (the device path is the XLA/TPU kernel;
    the sidecar evicts cold docs to these same engines).

    Rows must be fed in sequenced order per document; each round is
    one ``apply(rows, doc_of_row)`` call with row-major
    ``[n_rows, 12]`` int32 (OP_FIELDS order).
    """

    def __init__(self, n_docs: int):
        lib = load_merge_replay()
        if lib is None:
            raise RuntimeError("native merge tier unavailable")
        self._lib = lib
        self._h = lib.merge_session_create(n_docs)
        self.n_docs = n_docs

    def apply(self, rows: np.ndarray, doc_of_row: np.ndarray) -> None:
        assert rows.ndim == 2 and rows.shape[1] == len(OP_FIELDS)
        rows = np.ascontiguousarray(rows, np.int32)
        doc_of_row = np.ascontiguousarray(doc_of_row, np.int32)
        assert rows.shape[0] == doc_of_row.shape[0]
        if doc_of_row.size:
            # bounds-check HERE: C++ indexes s->docs[doc] unchecked,
            # so a bad index would be heap corruption, not an error
            lo, hi = int(doc_of_row.min()), int(doc_of_row.max())
            assert 0 <= lo and hi < self.n_docs, (
                f"doc_of_row out of range [{lo},{hi}] "
                f"for {self.n_docs} docs"
            )
        self._lib.merge_session_apply(
            self._h,
            rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            doc_of_row.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            rows.shape[0],
        )

    def stats(self, doc: int) -> tuple[int, int]:
        """(checksum, live_chars) of one doc's tip view."""
        checksum = ctypes.c_uint64(0)
        live = ctypes.c_int64(0)
        self._lib.merge_session_stats(
            self._h, doc, ctypes.byref(checksum), ctypes.byref(live)
        )
        return checksum.value, live.value

    def text(self, doc: int, stream: DocStream) -> str:
        """Tip-view text via (op_id, op_off, length) triples — same
        reconstruction as host_bridge.extract_text."""
        cap = 256
        while True:
            out = np.zeros((cap, 3), np.int32)
            n = self._lib.merge_session_segs(
                self._h, doc,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                cap,
            )
            if n <= cap:
                break
            cap = int(n)
        parts = []
        for op_id, off, length in out[:n]:
            parts.append(
                stream.payloads[int(op_id)][int(off):int(off) + int(length)]
            )
        return "".join(parts)

    def close(self) -> None:
        if self._h is not None:
            self._lib.merge_session_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - GC ordering
        try:
            self.close()
        except Exception:
            pass
