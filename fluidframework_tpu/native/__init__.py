"""Native runtime components (C++ via ctypes).

The service plane's hot loops live here; JAX/XLA owns the device
compute path, C++ owns the host sequencing path (deli ticket —
SURVEY §3.1 marks it one of the three hot loops). The shared library
builds on demand with g++ and caches beside the source; every native
component keeps a pure-Python twin as both fallback and differential
oracle.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

_HERE = Path(__file__).parent
_SRC = _HERE / "sequencer.cpp"
_LIB = _HERE / "_sequencer.so"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _compile(src: Path, out: Path) -> Optional[str]:
    """g++ -O2 build with mtime caching; returns an error string or
    None on success."""
    if out.exists() and out.stat().st_mtime >= src.stat().st_mtime:
        return None
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
        str(src), "-o", str(out),
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True)
    except FileNotFoundError:
        return "g++ not found"
    if proc.returncode != 0:
        return proc.stderr[-2000:]
    return None


def _build() -> Optional[Path]:
    global _build_error
    err = _compile(_SRC, _LIB)
    if err is not None:
        _build_error = err
        return None
    return _LIB


def load_native_sequencer() -> Optional[ctypes.CDLL]:
    """Build (if needed) + load the native core; None when the
    toolchain is unavailable (callers fall back to Python)."""
    global _lib, _build_error
    with _lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            return None  # failure is sticky: don't re-run g++ per call
        if os.environ.get("FFTPU_DISABLE_NATIVE") == "1":
            return None
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(str(path))
        except OSError as e:  # truncated/wrong-arch cached build
            _build_error = f"CDLL load failed: {e}"
            return None
        i64, p_i64 = ctypes.c_int64, ctypes.POINTER(ctypes.c_int64)
        p_i32 = ctypes.POINTER(ctypes.c_int32)
        lib.seq_create.restype = ctypes.c_void_p
        lib.seq_create.argtypes = [i64, i64]
        lib.seq_destroy.argtypes = [ctypes.c_void_p]
        lib.seq_client_join.restype = i64
        lib.seq_client_join.argtypes = [ctypes.c_void_p, i64]
        lib.seq_client_leave.restype = i64
        lib.seq_client_leave.argtypes = [ctypes.c_void_p, i64]
        for fn in ("seq_sequence_number", "seq_minimum_sequence_number",
                   "seq_client_count", "seq_bump"):
            getattr(lib, fn).restype = i64
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        lib.seq_ticket_batch.restype = i64
        lib.seq_ticket_batch.argtypes = [
            ctypes.c_void_p, i64, p_i64, p_i64, p_i64,
            p_i64, p_i64, p_i32,
        ]
        lib.seq_ticket_multi.restype = i64
        lib.seq_ticket_multi.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), i64, p_i64,
            p_i64, p_i64, p_i64, p_i64, p_i64, p_i32,
        ]
        lib.seq_export_clients.restype = i64
        lib.seq_export_clients.argtypes = [
            ctypes.c_void_p, i64, p_i64, p_i64, p_i64,
        ]
        lib.seq_restore_client.argtypes = [ctypes.c_void_p, i64, i64, i64]
        _lib = lib
        return _lib


def native_build_error() -> Optional[str]:
    return _build_error


_REPLAY_SRC = _HERE / "merge_replay.cpp"
_REPLAY_LIB = _HERE / "_merge_replay.so"
_replay_lib: Optional[ctypes.CDLL] = None
_replay_error: Optional[str] = None


def load_merge_replay() -> Optional[ctypes.CDLL]:
    """Build + load the C++ scalar merge replayer (the compiled
    baseline for bench.py); None when the toolchain is unavailable."""
    global _replay_lib, _replay_error
    with _lock:
        if _replay_lib is not None:
            return _replay_lib
        if _replay_error is not None:
            return None
        if os.environ.get("FFTPU_DISABLE_NATIVE") == "1":
            return None
        err = _compile(_REPLAY_SRC, _REPLAY_LIB)
        if err is not None:
            _replay_error = err
            return None
        try:
            lib = ctypes.CDLL(str(_REPLAY_LIB))
        except OSError as e:  # truncated/wrong-arch cached build
            _replay_error = f"CDLL load failed: {e}"
            return None
        i64 = ctypes.c_int64
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.merge_replay.restype = None
        lib.merge_replay.argtypes = [
            i32p, i64, i64,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(i64),
        ]
        lib.merge_session_create.restype = ctypes.c_void_p
        lib.merge_session_create.argtypes = [i64]
        lib.merge_session_destroy.restype = None
        lib.merge_session_destroy.argtypes = [ctypes.c_void_p]
        lib.merge_session_apply.restype = None
        lib.merge_session_apply.argtypes = [
            ctypes.c_void_p, i32p, i32p, i64,
        ]
        lib.merge_session_stats.restype = None
        lib.merge_session_stats.argtypes = [
            ctypes.c_void_p, i64,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(i64),
        ]
        lib.merge_session_segs.restype = i64
        lib.merge_session_segs.argtypes = [
            ctypes.c_void_p, i64, i32p, i64,
        ]
        _replay_lib = lib
        return _replay_lib


def merge_replay_error() -> Optional[str]:
    return _replay_error


from .sequencer_core import NativeSequencerCore  # noqa: E402

__all__ = [
    "NativeSequencerCore",
    "load_native_sequencer",
    "load_merge_replay",
    "merge_replay_error",
    "native_build_error",
]
