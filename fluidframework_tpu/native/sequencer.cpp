// Native sequencer core: the deli ticket() hot loop in C++.
//
// Reference semantics: server/routerlicious/packages/lambdas/src/deli/
// lambda.ts — ticket() (:741) assigns sequenceNumber, validates
// clientSequenceNumber continuity, tracks per-client refSeq, and stamps
// minimumSequenceNumber = min over connected clients' refSeqs (:308,
// clientSeqManager.ts). This is the service plane's hottest loop: one
// call per op per document. The Python DocumentSequencer
// (service/sequencer.py) is the spec oracle; differential tests pin
// this implementation to it op-for-op.
//
// Interface is C (ctypes-friendly): integer client ids (the Python
// wrapper interns strings), batch ticketing for throughput.

#include <cstdint>
#include <map>
#include <set>
#include <vector>

namespace {

struct ClientState {
    int64_t ref_seq;
    int64_t csn;
};

struct Sequencer {
    int64_t seq;
    int64_t msn;
    std::map<int64_t, ClientState> clients;
    // multiset of live refSeqs for O(log n) min maintenance
    std::multiset<int64_t> ref_seqs;

    int64_t compute_msn() {
        int64_t m = ref_seqs.empty() ? seq : *ref_seqs.begin();
        if (m > msn) msn = m;  // msn never regresses
        return msn;
    }
};

}  // namespace

// Ticket status codes (mirror TicketResult/Nack reasons)
enum TicketStatus : int32_t {
    TICKET_OK = 0,
    TICKET_UNKNOWN_CLIENT = 1,   // nack: join first
    TICKET_DUPLICATE = 2,        // dropped silently (idempotence)
    TICKET_CSN_GAP = 3,          // nack: clientSequenceNumber gap
    TICKET_REFSEQ_BELOW_MSN = 4, // nack: refSeq below msn
    TICKET_REFSEQ_AHEAD = 5,     // nack: refSeq ahead of doc seq
};

extern "C" {

void* seq_create(int64_t sequence_number, int64_t minimum_sequence_number) {
    auto* s = new Sequencer();
    s->seq = sequence_number;
    s->msn = minimum_sequence_number;
    return s;
}

void seq_destroy(void* handle) {
    delete static_cast<Sequencer*>(handle);
}

// Join: new client's refSeq starts at the join op's seq. Returns the
// join's sequence number. Redundant joins keep existing state.
int64_t seq_client_join(void* handle, int64_t client_id) {
    auto* s = static_cast<Sequencer*>(handle);
    int64_t join_seq = ++s->seq;
    auto it = s->clients.find(client_id);
    if (it == s->clients.end()) {
        // refSeq starts at the seq BEFORE the join: the client has
        // not seen its own join yet (matches service/sequencer.py)
        s->clients[client_id] = ClientState{join_seq - 1, 0};
        s->ref_seqs.insert(join_seq - 1);
    }
    s->compute_msn();
    return join_seq;
}

// Leave: returns the leave's sequence number, or -1 if unknown.
int64_t seq_client_leave(void* handle, int64_t client_id) {
    auto* s = static_cast<Sequencer*>(handle);
    auto it = s->clients.find(client_id);
    if (it == s->clients.end()) return -1;
    s->ref_seqs.erase(s->ref_seqs.find(it->second.ref_seq));
    s->clients.erase(it);
    int64_t leave_seq = ++s->seq;
    s->compute_msn();
    return leave_seq;
}

int64_t seq_sequence_number(void* handle) {
    return static_cast<Sequencer*>(handle)->seq;
}

// Allocate a seq for a service-generated system op (scribe's
// summaryAck/Nack loop back through the sequencer).
int64_t seq_bump(void* handle) {
    auto* s = static_cast<Sequencer*>(handle);
    int64_t v = ++s->seq;
    s->compute_msn();
    return v;
}

int64_t seq_minimum_sequence_number(void* handle) {
    return static_cast<Sequencer*>(handle)->msn;
}

int64_t seq_client_count(void* handle) {
    return static_cast<int64_t>(
        static_cast<Sequencer*>(handle)->clients.size());
}

// The hot loop: ticket n ops. Inputs are parallel arrays; outputs:
// out_seq/out_msn (valid when out_status==TICKET_OK) and out_status.
// Returns the count of TICKET_OK ops.
int64_t seq_ticket_batch(
    void* handle, int64_t n,
    const int64_t* client_ids, const int64_t* csns,
    const int64_t* ref_seqs,
    int64_t* out_seq, int64_t* out_msn, int32_t* out_status) {
    auto* s = static_cast<Sequencer*>(handle);
    int64_t ok = 0;
    for (int64_t i = 0; i < n; ++i) {
        auto it = s->clients.find(client_ids[i]);
        if (it == s->clients.end()) {
            out_status[i] = TICKET_UNKNOWN_CLIENT;
            continue;
        }
        ClientState& c = it->second;
        const int64_t expected = c.csn + 1;
        if (csns[i] < expected) {
            out_status[i] = TICKET_DUPLICATE;
            continue;
        }
        if (csns[i] > expected) {
            out_status[i] = TICKET_CSN_GAP;
            continue;
        }
        if (ref_seqs[i] < s->msn) {
            out_status[i] = TICKET_REFSEQ_BELOW_MSN;
            continue;
        }
        if (ref_seqs[i] > s->seq) {
            out_status[i] = TICKET_REFSEQ_AHEAD;
            continue;
        }
        c.csn = csns[i];
        if (ref_seqs[i] != c.ref_seq) {
            s->ref_seqs.erase(s->ref_seqs.find(c.ref_seq));
            c.ref_seq = ref_seqs[i];
            s->ref_seqs.insert(c.ref_seq);
        }
        out_seq[i] = ++s->seq;
        out_msn[i] = s->compute_msn();
        out_status[i] = TICKET_OK;
        ++ok;
    }
    return ok;
}

// Multi-document boxcar: ticket every document's op slice in ONE
// call — the Kafka boxcar shape (the deli lambda consumes message
// boxes grouped by document; lambdas/src/deli/lambda.ts rebatches the
// same way). Op arrays are flattened with doc_start[d]..doc_start[d+1]
// delimiting document d's slice. Returns total TICKET_OK count.
int64_t seq_ticket_multi(
    void** handles, int64_t n_docs, const int64_t* doc_start,
    const int64_t* client_ids, const int64_t* csns,
    const int64_t* ref_seqs,
    int64_t* out_seq, int64_t* out_msn, int32_t* out_status) {
    int64_t ok = 0;
    for (int64_t d = 0; d < n_docs; ++d) {
        const int64_t a = doc_start[d], b = doc_start[d + 1];
        if (b <= a) continue;
        ok += seq_ticket_batch(
            handles[d], b - a, client_ids + a, csns + a, ref_seqs + a,
            out_seq + a, out_msn + a, out_status + a);
    }
    return ok;
}

// Checkpoint export: fill parallel arrays (capacity must be
// >= seq_client_count). Returns the client count written.
int64_t seq_export_clients(
    void* handle, int64_t capacity,
    int64_t* client_ids, int64_t* ref_seqs_out, int64_t* csns) {
    auto* s = static_cast<Sequencer*>(handle);
    int64_t i = 0;
    for (const auto& [cid, state] : s->clients) {
        if (i >= capacity) break;
        client_ids[i] = cid;
        ref_seqs_out[i] = state.ref_seq;
        csns[i] = state.csn;
        ++i;
    }
    return i;
}

// Checkpoint restore: register a client with explicit state.
void seq_restore_client(void* handle, int64_t client_id,
                        int64_t ref_seq, int64_t csn) {
    auto* s = static_cast<Sequencer*>(handle);
    auto it = s->clients.find(client_id);
    if (it != s->clients.end()) {
        s->ref_seqs.erase(s->ref_seqs.find(it->second.ref_seq));
    }
    s->clients[client_id] = ClientState{ref_seq, csn};
    s->ref_seqs.insert(ref_seq);
}

}  // extern "C"
