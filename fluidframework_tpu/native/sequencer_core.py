"""Python wrapper over the native sequencer core.

``NativeSequencerCore`` exposes the DocumentSequencer surface (ticket/
join/leave/checkpoint — service/sequencer.py) backed by the C++ hot
loop, plus a batch API the service plane uses for throughput. String
client ids are interned to ints here; nack construction stays in
Python (cold path).
"""
from __future__ import annotations

import ctypes
import time
from typing import Any, Callable, Optional

from ..protocol.messages import (
    ClientDetail,
    DocumentMessage,
    MessageType,
    Nack,
    NackErrorType,
    SequencedMessage,
    Trace,
)
from ..service.sequencer import TicketResult

_STATUS_MESSAGES = {
    1: "client not in quorum (join first)",
    3: "clientSequenceNumber gap",
    4: "refSeq below msn",
    5: "refSeq ahead of document sequence number",
}


class NativeSequencerCore:
    """Drop-in DocumentSequencer with the C++ ticket loop."""

    def __init__(self, document_id: str = "",
                 sequence_number: int = 0,
                 minimum_sequence_number: int = 0,
                 clock: Optional[Callable[[], float]] = None):
        from . import load_native_sequencer
        lib = load_native_sequencer()
        if lib is None:
            from . import native_build_error
            raise RuntimeError(
                f"native sequencer unavailable: {native_build_error()}"
            )
        self._lib = lib
        # same injectable wall clock as DocumentSequencer: wire
        # timestamps stay byte-stable under a manual clock
        self._clock = clock or time.time
        self.document_id = document_id
        self._handle = lib.seq_create(
            sequence_number, minimum_sequence_number
        )
        self._intern: dict[str, int] = {}
        self._unintern: list[str] = []

    def __del__(self):  # pragma: no cover - interpreter teardown
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.seq_destroy(handle)
            self._handle = None

    # ------------------------------------------------------------------

    @property
    def sequence_number(self) -> int:
        return self._lib.seq_sequence_number(self._handle)

    @property
    def minimum_sequence_number(self) -> int:
        return self._lib.seq_minimum_sequence_number(self._handle)

    @property
    def clients(self) -> tuple[str, ...]:
        n = self._lib.seq_client_count(self._handle)
        ids = (ctypes.c_int64 * n)()
        refs = (ctypes.c_int64 * n)()
        csns = (ctypes.c_int64 * n)()
        count = self._lib.seq_export_clients(
            self._handle, n, ids, refs, csns
        )
        return tuple(self._unintern[ids[i]] for i in range(count))

    def _intern_id(self, client_id: str) -> int:
        idx = self._intern.get(client_id)
        if idx is None:
            idx = len(self._unintern)
            self._intern[client_id] = idx
            self._unintern.append(client_id)
        return idx

    def _system_msg(self, msg_type: MessageType, contents: Any,
                    seq: int) -> SequencedMessage:
        return SequencedMessage(
            client_id=None,
            sequence_number=seq,
            minimum_sequence_number=self.minimum_sequence_number,
            client_sequence_number=-1,
            reference_sequence_number=-1,
            type=msg_type,
            contents=contents,
            timestamp=self._clock(),
        )

    # ------------------------------------------------------------------
    # DocumentSequencer surface

    def client_join(self, detail: ClientDetail) -> SequencedMessage:
        seq = self._lib.seq_client_join(
            self._handle, self._intern_id(detail.client_id)
        )
        return self._system_msg(MessageType.CLIENT_JOIN, detail, seq)

    def client_leave(self, client_id: str) -> Optional[SequencedMessage]:
        idx = self._intern.get(client_id)
        if idx is None:
            return None
        seq = self._lib.seq_client_leave(self._handle, idx)
        if seq < 0:
            return None
        return self._system_msg(MessageType.CLIENT_LEAVE, client_id, seq)

    def ticket(self, client_id: str,
               op: DocumentMessage) -> TicketResult:
        results = self.ticket_batch([(client_id, op)])
        return results[0]

    def ticket_batch(
        self, ops: list[tuple[str, DocumentMessage]]
    ) -> list[TicketResult]:
        """The throughput API: one native call tickets a whole window
        of raw ops (the deli lambda processes Kafka message boxcars
        the same way)."""
        n = len(ops)
        intern = self._intern
        cids = (ctypes.c_int64 * n)(
            *(intern.get(cid, -1) for cid, _ in ops)
        )
        csns = (ctypes.c_int64 * n)(
            *(op.client_sequence_number for _, op in ops)
        )
        refs = (ctypes.c_int64 * n)(
            *(op.reference_sequence_number for _, op in ops)
        )
        out_seq = (ctypes.c_int64 * n)()
        out_msn = (ctypes.c_int64 * n)()
        out_status = (ctypes.c_int32 * n)()
        self._lib.seq_ticket_batch(
            self._handle, n, cids, csns, refs,
            out_seq, out_msn, out_status,
        )
        results: list[TicketResult] = []
        now = self._clock()
        # nacks report the doc seq AT rejection time, matching the
        # sequential oracle: track it through the batch
        running_seq = self.sequence_number - sum(
            1 for i in range(n) if out_status[i] == 0
        )
        for i, (client_id, op) in enumerate(ops):
            status = out_status[i]
            if status == 0:
                running_seq = out_seq[i]
                traces = list(op.traces)
                traces.append(Trace("sequencer", "ticket"))
                results.append(TicketResult(message=SequencedMessage(
                    client_id=client_id,
                    sequence_number=out_seq[i],
                    minimum_sequence_number=out_msn[i],
                    client_sequence_number=op.client_sequence_number,
                    reference_sequence_number=(
                        op.reference_sequence_number
                    ),
                    type=op.type,
                    contents=op.contents,
                    metadata=op.metadata,
                    timestamp=now,
                    traces=traces,
                )))
            elif status == 2:
                results.append(TicketResult())  # duplicate: dropped
            else:
                results.append(TicketResult(nack=Nack(
                    operation=op,
                    sequence_number=running_seq,
                    error_type=NackErrorType.BAD_REQUEST,
                    message=_STATUS_MESSAGES.get(status, "rejected"),
                )))
        return results

    def ticket_batch_arrays(self, cids, csns, refs):
        """The true throughput lane: ticket a whole window with zero
        per-op Python objects. Inputs are int64 arrays (client ids
        already interned via ``intern_id``); returns (seq, msn, status)
        numpy arrays — exactly the numeric form the TPU sidecar's
        OpBatch wants, so sequencing feeds the device path without ever
        materializing SequencedMessage objects. Status 0 = sequenced,
        2 = duplicate (dropped), else nack (resolve via the scalar
        ``ticket`` path for the message/nack details — cold path)."""
        import numpy as np

        cids = np.ascontiguousarray(cids, dtype=np.int64)
        csns = np.ascontiguousarray(csns, dtype=np.int64)
        refs = np.ascontiguousarray(refs, dtype=np.int64)
        n = len(cids)
        out_seq = np.empty(n, np.int64)
        out_msn = np.empty(n, np.int64)
        out_status = np.empty(n, np.int32)
        p64 = ctypes.POINTER(ctypes.c_int64)
        p32 = ctypes.POINTER(ctypes.c_int32)
        self._lib.seq_ticket_batch(
            self._handle, n,
            cids.ctypes.data_as(p64),
            csns.ctypes.data_as(p64),
            refs.ctypes.data_as(p64),
            out_seq.ctypes.data_as(p64),
            out_msn.ctypes.data_as(p64),
            out_status.ctypes.data_as(p32),
        )
        return out_seq, out_msn, out_status

    def intern_id(self, client_id: str) -> int:
        """Public interning hook for the array lane (intern once per
        client, not per op)."""
        return self._intern_id(client_id)

    def system_message(self, msg_type: MessageType,
                       contents: Any) -> SequencedMessage:
        """Allocate a seq for a service-generated op (summaryAck/Nack
        loop back through the sequencer; they carry no client state,
        so the core just bumps its counter)."""
        seq = self._lib.seq_bump(self._handle)
        return self._system_msg(msg_type, contents, seq)

    # ------------------------------------------------------------------
    # checkpoint/restore (deli/checkpointContext.ts parity)

    def checkpoint(self) -> dict[str, Any]:
        n = self._lib.seq_client_count(self._handle)
        ids = (ctypes.c_int64 * n)()
        refs = (ctypes.c_int64 * n)()
        csns = (ctypes.c_int64 * n)()
        count = self._lib.seq_export_clients(
            self._handle, n, ids, refs, csns
        )
        return {
            "document_id": self.document_id,
            "sequence_number": self.sequence_number,
            "minimum_sequence_number": self.minimum_sequence_number,
            "clients": [
                {
                    "client_id": self._unintern[ids[i]],
                    "reference_sequence_number": refs[i],
                    "client_sequence_number": csns[i],
                }
                for i in range(count)
            ],
        }

    @classmethod
    def restore(cls, state: dict[str, Any],
                clock: Optional[Callable[[], float]] = None,
                ) -> "NativeSequencerCore":
        core = cls(
            document_id=state["document_id"],
            sequence_number=state["sequence_number"],
            minimum_sequence_number=state["minimum_sequence_number"],
            clock=clock,
        )
        for c in state["clients"]:
            core._lib.seq_restore_client(
                core._handle,
                core._intern_id(c["client_id"]),
                c["reference_sequence_number"],
                c["client_sequence_number"],
            )
        return core


class MultiDocSequencer:
    """A fleet of native sequencers ticketed in ONE FFI call per
    boxcar — the deli lambda's Kafka message-box shape (ops grouped by
    document, lambdas/src/deli/lambda.ts): the service plane's
    full-corpus replay path crosses the FFI boundary once per round,
    not once per document."""

    def __init__(self, n_docs: int):
        from . import load_native_sequencer

        lib = load_native_sequencer()
        if lib is None:
            from . import native_build_error

            raise RuntimeError(
                f"native sequencer unavailable: {native_build_error()}"
            )
        self._lib = lib
        self.n_docs = n_docs
        self._handles = (ctypes.c_void_p * n_docs)(
            *(lib.seq_create(0, 0) for _ in range(n_docs))
        )

    def __del__(self):  # pragma: no cover - interpreter teardown
        lib = getattr(self, "_lib", None)
        handles = getattr(self, "_handles", None)
        if lib is not None and handles is not None:
            for h in handles:
                if h:
                    lib.seq_destroy(h)
            self._handles = None

    def join(self, doc: int, client_idx: int) -> int:
        """Join an (interned) client to one document's quorum."""
        return self._lib.seq_client_join(self._handles[doc], client_idx)

    def doc_sequence_number(self, doc: int) -> int:
        return self._lib.seq_sequence_number(self._handles[doc])

    def ticket_boxcar(self, doc_start, cids, csns, refs):
        """Ticket one boxcar: flattened per-doc op slices delimited by
        ``doc_start`` (len n_docs+1). Returns (seq, msn, status) int64
        /int32 arrays aligned with the inputs — the numeric form the
        device OpBatch consumes directly (zero per-op Python)."""
        import numpy as np

        doc_start = np.ascontiguousarray(doc_start, np.int64)
        cids = np.ascontiguousarray(cids, np.int64)
        csns = np.ascontiguousarray(csns, np.int64)
        refs = np.ascontiguousarray(refs, np.int64)
        n = len(cids)
        out_seq = np.empty(n, np.int64)
        out_msn = np.empty(n, np.int64)
        out_status = np.empty(n, np.int32)
        p64 = ctypes.POINTER(ctypes.c_int64)
        p32 = ctypes.POINTER(ctypes.c_int32)
        self._lib.seq_ticket_multi(
            self._handles, self.n_docs,
            doc_start.ctypes.data_as(p64),
            cids.ctypes.data_as(p64),
            csns.ctypes.data_as(p64),
            refs.ctypes.data_as(p64),
            out_seq.ctypes.data_as(p64),
            out_msn.ctypes.data_as(p64),
            out_status.ctypes.data_as(p32),
        )
        return out_seq, out_msn, out_status
