// Scalar merge-tree replayer — the compiled-language baseline for the
// batched TPU kernel (BASELINE.md: "Node.js baselines ... must be
// measured"; no Node runtime exists in this image, so the baseline
// proxy is this C++ -O2 replay of the same sequenced-path semantics,
// which bounds what a V8-JITted merge-tree could do on this host).
//
// Semantics mirror ops/merge_kernel.py (_views/_apply_one) and the
// scalar Python oracle (models/mergetree/mergetree.py), which encode
// the reference's refSeq-view resolution (mergeTree.ts insertingWalk
// :1723, markRangeRemoved :1908, annotateRange :1864) reduced to the
// server-side sequenced path (every seq acked).
//
// Input: row-major int32 ops [n_ops][12] in host_bridge.OP_FIELDS
// order: kind,pos1,pos2,seq,refseq,client,op_id,length,is_marker,
// prop_key,prop_val,min_seq.  Output: FNV-1a checksum over the
// per-character tip view — comparable with the kernel's fetched table.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int32_t kNotRemoved = INT32_MAX;
constexpr int kPropChannels = 4;
constexpr int kF_kind = 0, kF_pos1 = 1, kF_pos2 = 2, kF_seq = 3,
              kF_refseq = 4, kF_client = 5, kF_op_id = 6, kF_length = 7,
              kF_is_marker = 8, kF_prop_key = 9, kF_prop_val = 10,
              kF_min_seq = 11;
constexpr int kFields = 12;
constexpr int kKindInsert = 0, kKindRemove = 1, kKindAnnotate = 2,
              kKindNoop = 3;

struct Seg {
  int32_t length;
  int32_t seq;
  int32_t client;
  int32_t removed_seq;
  uint32_t removers;
  int32_t op_id;
  int32_t op_off;
  int32_t is_marker;
  int32_t prop[kPropChannels];
};

struct Doc {
  std::vector<Seg> segs;
  int32_t min_seq = 0;
  int32_t ops_since_compact = 0;

  bool below_window(const Seg& s) const {
    return s.removed_seq != kNotRemoved && s.removed_seq <= min_seq;
  }
  bool removal_visible(const Seg& s, int32_t refseq, int32_t client) const {
    return s.removed_seq != kNotRemoved &&
           (s.removed_seq <= refseq ||
            ((s.removers >> (static_cast<uint32_t>(client) & 31u)) & 1u));
  }
  bool insert_visible(const Seg& s, int32_t refseq, int32_t client) const {
    return s.seq <= refseq || s.client == client;
  }
  bool visible(const Seg& s, int32_t refseq, int32_t client) const {
    return !below_window(s) && insert_visible(s, refseq, client) &&
           !removal_visible(s, refseq, client);
  }

  // Split segs[i] at interior offset off; tail inherits provenance
  // (splitLeafSegment, mergeTree.ts:1681).
  void split(size_t i, int32_t off) {
    Seg tail = segs[i];
    tail.length = segs[i].length - off;
    tail.op_off = segs[i].op_off + off;
    segs[i].length = off;
    segs.insert(segs.begin() + i + 1, tail);
  }

  void insert(const int32_t* op) {
    int32_t p1 = op[kF_pos1], refseq = op[kF_refseq],
            client = op[kF_client];
    int64_t E = 0;
    size_t idx = segs.size();
    int32_t off = 0;
    for (size_t i = 0; i < segs.size(); ++i) {
      const Seg& s = segs[i];
      if (below_window(s)) continue;  // not stop-eligible
      int32_t vlen = visible(s, refseq, client) ? s.length : 0;
      if (E == p1 || (E <= p1 && p1 < E + vlen)) {
        idx = i;
        off = static_cast<int32_t>(p1 - E);
        break;
      }
      E += vlen;
    }
    if (idx == segs.size() && p1 > E) return;  // beyond total: invalid
    if (off > 0) {
      split(idx, off);
      ++idx;
    }
    Seg n{};
    n.length = op[kF_length];
    n.seq = op[kF_seq];
    n.client = client;
    n.removed_seq = kNotRemoved;
    n.op_id = op[kF_op_id];
    n.is_marker = op[kF_is_marker];
    segs.insert(segs.begin() + idx, n);
  }

  // Split at visible-position boundary p (for range ops): slot
  // strictly containing p splits so stamps align to op boundaries.
  void boundary(int32_t p, int32_t refseq, int32_t client) {
    int64_t E = 0;
    for (size_t i = 0; i < segs.size(); ++i) {
      const Seg& s = segs[i];
      if (below_window(s)) continue;
      int32_t vlen = visible(s, refseq, client) ? s.length : 0;
      if (E < p && p < E + vlen) {
        split(i, static_cast<int32_t>(p - E));
        return;
      }
      E += vlen;
      if (E >= p) return;  // E is monotone: no later slot contains p
    }
  }

  void range_stamp(const int32_t* op) {
    int32_t p1 = op[kF_pos1], p2 = op[kF_pos2], refseq = op[kF_refseq],
            client = op[kF_client], kind = op[kF_kind];
    boundary(p1, refseq, client);
    boundary(p2, refseq, client);
    // encode enforces client < 32 (DocStream.intern_client); the
    // clamp guards against UB if a hand-built stream violates it.
    uint32_t bit = 1u << (static_cast<uint32_t>(client) & 31u);
    int64_t E = 0;
    for (size_t i = 0; i < segs.size(); ++i) {
      Seg& s = segs[i];
      if (below_window(s)) continue;
      int32_t vlen = visible(s, refseq, client) ? s.length : 0;
      if (vlen > 0 && E >= p1 && E + vlen <= p2) {
        if (kind == kKindRemove) {
          if (s.removed_seq == kNotRemoved) s.removed_seq = op[kF_seq];
          s.removers |= bit;
        } else {  // annotate: sequenced-order LWW on one channel
          s.prop[op[kF_prop_key]] = op[kF_prop_val];
        }
      }
      E += vlen;
      if (E >= p2) break;
    }
  }

  // Zamboni analogue (mergeTree.ts:800): drop below-window tombstones
  // periodically so long sessions stay bounded, like the real client.
  void maybe_compact() {
    if (++ops_since_compact < 64) return;
    ops_since_compact = 0;
    size_t w = 0;
    for (size_t i = 0; i < segs.size(); ++i) {
      if (segs[i].removed_seq != kNotRemoved &&
          segs[i].removed_seq <= min_seq)
        continue;
      if (w != i) segs[w] = segs[i];
      ++w;
    }
    segs.resize(w);
  }

  void apply(const int32_t* op) {
    switch (op[kF_kind]) {
      case kKindInsert:
        insert(op);
        break;
      case kKindRemove:
      case kKindAnnotate:
        range_stamp(op);
        break;
      case kKindNoop:
      default:
        break;
    }
    if (op[kF_min_seq] > min_seq) min_seq = op[kF_min_seq];
    maybe_compact();
  }

  uint64_t checksum() const {
    uint64_t h = 1469598103934665603ull;  // FNV-1a
    auto mix = [&h](int64_t v) {
      for (int b = 0; b < 8; ++b) {
        h ^= static_cast<uint64_t>(v >> (8 * b)) & 0xffu;
        h *= 1099511628211ull;
      }
    };
    for (const Seg& s : segs) {
      if (s.removed_seq != kNotRemoved) continue;  // tip view
      for (int32_t c = 0; c < s.length; ++c) {
        mix(s.op_id);
        mix(s.op_off + c);
        mix(s.is_marker);
        for (int k = 0; k < kPropChannels; ++k) mix(s.prop[k]);
      }
    }
    return h;
  }
};

// Incremental multi-document session — the HOST SERVING TIER the
// full-service pipeline routes merges through on hosts without an
// accelerator (config5's CPU path; the sidecar's host tier uses the
// same engines). Unlike merge_replay (from-scratch, one doc), a
// session holds per-doc state across rounds and applies flat
// round batches of (row, doc) pairs in sequenced order.
struct Session {
  std::vector<Doc> docs;
};

}  // namespace

extern "C" {

void* merge_session_create(int64_t n_docs) {
  auto* s = new Session();
  s->docs.resize(static_cast<size_t>(n_docs));
  for (auto& d : s->docs) d.segs.reserve(64);
  return s;
}

void merge_session_destroy(void* h) {
  delete static_cast<Session*>(h);
}

// rows: [n_rows][12] int32 (OP_FIELDS order), doc_of_row: [n_rows].
// Rows must arrive in sequenced order per document (the round batch).
void merge_session_apply(void* h, const int32_t* rows,
                         const int32_t* doc_of_row, int64_t n_rows) {
  auto* s = static_cast<Session*>(h);
  for (int64_t i = 0; i < n_rows; ++i)
    s->docs[static_cast<size_t>(doc_of_row[i])]
        .apply(rows + i * kFields);
}

void merge_session_stats(void* h, int64_t doc,
                         uint64_t* out_checksum, int64_t* out_live) {
  auto* s = static_cast<Session*>(h);
  const Doc& d = s->docs[static_cast<size_t>(doc)];
  if (out_checksum) *out_checksum = d.checksum();
  int64_t live = 0;
  for (const Seg& seg : d.segs)
    if (seg.removed_seq == kNotRemoved) live += seg.length;
  if (out_live) *out_live = live;
}

// Live non-marker segments as (op_id, op_off, length) triples for
// host-side text reconstruction (host_bridge.extract_text shape).
// Returns the number of triples; writes at most `cap`.
int64_t merge_session_segs(void* h, int64_t doc, int32_t* out,
                           int64_t cap) {
  auto* s = static_cast<Session*>(h);
  const Doc& d = s->docs[static_cast<size_t>(doc)];
  int64_t n = 0;
  for (const Seg& seg : d.segs) {
    if (seg.removed_seq != kNotRemoved || seg.is_marker) continue;
    if (n < cap) {
      out[n * 3 + 0] = seg.op_id;
      out[n * 3 + 1] = seg.op_off;
      out[n * 3 + 2] = seg.length;
    }
    ++n;
  }
  return n;
}

// Replay one document's op stream `reps` times from scratch; returns
// nanoseconds-free op count actually applied (reps * n_ops) and the
// final checksum of the last replay via out params. Timing is done by
// the caller around this call.
void merge_replay(const int32_t* ops, int64_t n_ops, int64_t reps,
                  uint64_t* out_checksum, int64_t* out_live_chars) {
  uint64_t checksum = 0;
  int64_t live = 0;
  for (int64_t r = 0; r < reps; ++r) {
    Doc doc;
    doc.segs.reserve(256);
    for (int64_t i = 0; i < n_ops; ++i) doc.apply(ops + i * kFields);
    checksum = doc.checksum();
    live = 0;
    for (const Seg& s : doc.segs)
      if (s.removed_seq == kNotRemoved) live += s.length;
  }
  if (out_checksum) *out_checksum = checksum;
  if (out_live_chars) *out_live_chars = live;
}

}  // extern "C"
