"""Replay driver: serves a recorded op stream as a read-only document.

Reference: packages/drivers/replay-driver/src
(``ReplayDocumentService`` replayDocumentService.ts:18,
``ReplayController``) — replays persisted op streams against snapshots
for validation and benchmarking (BASELINE configs are replay-driven).
"""
from __future__ import annotations

from typing import Callable, Optional

from ..protocol.messages import (
    DocumentMessage,
    Nack,
    SequencedMessage,
)


class _ReplayConnection:
    client_id = "replay-reader"
    open = True

    def submit(self, op: DocumentMessage) -> None:
        raise RuntimeError("replay documents are read-only")

    def disconnect(self) -> None:
        self.open = False


class ReplayDocumentService:
    """Replays ``messages`` (an already-sequenced stream) up to
    ``replay_to`` through the normal delta-stream interface."""

    def __init__(self, document_id: str,
                 messages: list[SequencedMessage],
                 summary: Optional[tuple[int, dict]] = None):
        self.document_id = document_id
        self._messages = sorted(messages,
                                key=lambda m: m.sequence_number)
        self._summary = summary

    def connect_to_delta_stream(
        self,
        client_id: str,
        on_message: Callable[[SequencedMessage], None],
        on_nack: Optional[Callable[[Nack], None]] = None,
    ) -> _ReplayConnection:
        conn = _ReplayConnection()
        base = self._summary[0] if self._summary else 0
        for msg in self._messages:
            if msg.sequence_number > base:
                on_message(msg)
        return conn

    def read_ops(self, from_seq: int, to_seq: Optional[int] = None
                 ) -> list[SequencedMessage]:
        return [
            m for m in self._messages
            if m.sequence_number > from_seq
            and (to_seq is None or m.sequence_number <= to_seq)
        ]

    def get_latest_summary(self) -> Optional[tuple[int, dict]]:
        return self._summary
