"""Socket driver: DocumentService over a TCP connection to the
networked ingress (service/ingress.py).

Reference: the production networked driver pair —
packages/drivers/driver-base/src/documentDeltaConnection.ts (:41, the
connect_document handshake + op/nack events) and
packages/drivers/routerlicious-driver/src/documentService.ts (:37, the
three planes over the wire). One TCP connection per DocumentService; a
daemon receive-pump thread dispatches broadcast ops to the container's
callback and pairs request/response frames by ``rid``.

The client surface is synchronous (the loader's Container is
synchronous and single-threaded). Two daemon threads serve it:

- the RECV PUMP parses frames and only ever sets rid events or
  enqueues broadcasts — it never calls back into user code, so a
  request issued from any thread can always complete;
- the DISPATCH thread delivers op/nack broadcasts to the container's
  callbacks while holding ``self.lock``. The container's inbound path
  may itself issue blocking requests (gap refetch calls read_ops —
  deltaManager.ts:883), which is safe because the recv pump stays
  free.

Application code MUST hold the same ``service.lock`` around container
calls (flush/process/reads) — the container is not thread-safe and the
dispatch thread mutates it; `with svc.lock: container.flush()`.
"""
from __future__ import annotations

import itertools
import json
import queue
import select
import socket
import struct
import sys
import threading
from typing import Callable, Optional

from ..obs import metrics as obs_metrics
from ..obs.flight_recorder import FlightRecorder
from ..obs.trace import stamp as trace_stamp
from ..protocol.messages import DocumentMessage, Nack, NackErrorType, SequencedMessage
from ..protocol.constants import wire_version_lt
from ..protocol.serialization import decode_contents, message_from_json
from ..qos.faults import (
    KIND_DELAY,
    KIND_DISCONNECT,
    KIND_DROP,
    KIND_DUPLICATE,
    KIND_NACK,
    KIND_REORDER,
    PLANE as _CHAOS,
)
from ..protocol.columnar import encode_columns
from ..service.ingress import document_message_to_json, pack_frame

_LEN = struct.Struct(">I")

_FRAMES_SENT = obs_metrics.REGISTRY.counter(
    "driver_frames_sent_total", "frames the socket driver sent")
_FRAMES_RECV = obs_metrics.REGISTRY.counter(
    "driver_frames_received_total", "frames the socket driver parsed")
_DISPATCH_FAULTS = obs_metrics.REGISTRY.counter(
    "driver_dispatch_faults_total",
    "delivery callbacks that raised (transport torn down loudly)")
_REQUEST_TIMEOUTS = obs_metrics.REGISTRY.counter(
    "driver_request_timeouts_total",
    "request/response deadlines missed (flight dump emitted)")

# chaos seams (docs/ROBUSTNESS.md): the SAME site names the in-proc
# chaos transport (testing/chaos.py) registers, so one schedule
# drives either harness. Outbound faults are the ones a real TCP
# stream can actually exhibit at this layer — transport death and an
# injected throttle nack (the faultInjectionDriver vocabulary);
# inbound faults apply to broadcast "op" frames only, where
# drop/duplicate/reorder are REAL phenomena with real recovery paths
# (slow-consumer fanout drops -> gap refetch; catch-up overlapping
# live fanout -> the container's seq dedupe). rid-paired
# request/response frames ride the reliable stream untouched.
_SITE_FRAME_OUT = _CHAOS.site(
    "socket.frame_out", (KIND_DISCONNECT, KIND_NACK))
_SITE_FRAME_IN = _CHAOS.site(
    "socket.frame_in",
    (KIND_DROP, KIND_DUPLICATE, KIND_REORDER, KIND_DELAY))


# wire versions this driver speaks, newest first (the server echoes
# the agreed one in "connected"; see ingress.WIRE_VERSIONS for what
# each version adds — 1.1 is the chunked summary-upload plane, 1.2 the
# boxcarred batch submit, 1.3 the columnar SoA batch submit, 1.4 the
# heat cost-attribution frame, 1.5 the registered sharedtree payload
# vocabulary)
WIRE_VERSIONS = ("1.5", "1.4", "1.3", "1.2", "1.1", "1.0")


def build_connect_frame(document_id: str, client_id: str, mode: str,
                        tenant_id=None, token=None,
                        versions=None) -> dict:
    """The connect_document handshake frame — ONE definition so the
    single-socket and multiplexed drivers cannot diverge on auth/mode
    fields. ``versions`` overrides the offer (compat tests pin an
    old client against a new server)."""
    frame = {
        "type": "connect_document",
        "document_id": document_id,
        "client_id": client_id,
        "mode": mode,
        "versions": list(versions or WIRE_VERSIONS),
    }
    if token is not None:
        frame["tenant_id"] = tenant_id
        frame["token"] = token
    return frame


class SocketDocumentService:
    """IDocumentService over the wire; create via the factory."""

    def __init__(self, host: str, port: int, document_id: str,
                 timeout: float = 30.0,
                 tenant_id: Optional[str] = None,
                 token: Optional[str] = None,
                 mode: str = "write",
                 wire_versions=None):
        self.document_id = document_id
        # riddler-analogue auth (service/tenancy.py): sent with the
        # connect_document handshake when the server gates on tokens
        self.tenant_id = tenant_id
        self.token = token
        self.mode = mode
        # offered wire versions (override pins an old client for the
        # compat matrix); the server's pick lands in agreed_version
        self.wire_versions = tuple(wire_versions or WIRE_VERSIONS)
        self.agreed_version: Optional[str] = None
        self.auth_error: Optional[str] = None
        self.lock = threading.RLock()
        self._timeout = timeout
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout)
        self._send_lock = threading.Lock()
        self._rid = itertools.count(1)
        self._pending: dict[int, tuple[threading.Event, list]] = {}
        self._pending_lock = threading.Lock()
        self._on_message: Optional[Callable] = None
        self._on_nack: Optional[Callable] = None
        self._connected = threading.Event()
        self._closed = False
        self.last_error: Optional[str] = None
        # transport flight recorder: the last N frames in/out, dumped
        # automatically on a dispatch fault or a missed deadline (the
        # postmortem the PR-2 ack stall lacked)
        self.flight = FlightRecorder(
            128, name=f"socket-{document_id}")
        self.last_flight_dump: Optional[str] = None
        self._inbox: queue.Queue[Optional[dict]] = queue.Queue()
        # broadcast frames a chaos reorder/delay fault is holding
        # (recv-pump thread only; released after the next delivery)
        self._held: list[dict] = []
        self._pump = threading.Thread(
            target=self._recv_loop, daemon=True,
            name=f"socket-recv-{document_id}",
        )
        self._pump.start()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name=f"socket-dispatch-{document_id}",
        )
        self._dispatcher.start()

    # -- framing -------------------------------------------------------

    def _send(self, data: dict) -> None:
        if data.get("type") == "submitOp":
            fault = _SITE_FRAME_OUT.fire(doc=self.document_id)
            if fault == KIND_NACK:
                # refused as a throttling service would: the frame is
                # dropped and an injected nack delivers on the normal
                # dispatch path — reconnect + pending-resubmit is the
                # recovery (faultInjectionDriver.ts:62 semantics)
                self.flight.record("chaos-nack", type="submitOp")
                self._inbox.put({
                    "type": "nack",
                    "document_id": self.document_id,
                    "operation": None,
                    "sequence_number": 0,
                    "error_type": int(NackErrorType.THROTTLING),
                    "message": "chaos: injected nack",
                    "retry_after_seconds": 0.0,
                })
                return
            if fault == KIND_DISCONNECT:
                # transport death, no goodbye: the frame is lost to
                # the dying socket; the recv pump's teardown protocol
                # runs and the app-level reconnect path recovers
                self.flight.record("chaos-disconnect")
                self.close()
                return
        frame = pack_frame(data)
        self.flight.record("send", type=data.get("type"),
                           rid=data.get("rid"), bytes=len(frame))
        _FRAMES_SENT.inc()
        with self._send_lock:
            self._sock.sendall(frame)

    def _recv_exact(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            try:
                chunk = self._sock.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf += chunk
        return buf

    # how long a chaos-held (reordered/delayed) frame may wait for a
    # NEXT frame before it releases anyway: a held frame on an IDLE
    # connection would otherwise stall the replica until the socket
    # timeout (gap detection needs follow-on traffic to notice)
    HELD_FLUSH_S = 0.05

    def _recv_header(self) -> Optional[bytes]:
        """Read the next frame header. While chaos-held frames exist,
        poll READABILITY with ``select`` and flush the holds if the
        wire stays idle — never by toggling the socket timeout, which
        is shared with concurrent ``sendall`` on the submit path (a
        50ms send timeout could tear an outbound frame mid-write and
        desync the whole length-prefixed stream)."""
        while self._held:
            try:
                readable, _, _ = select.select(
                    [self._sock], [], [], self.HELD_FLUSH_S)
            except (OSError, ValueError):
                return None  # socket died under us
            if readable:
                break  # real traffic follows: the reorder resolves
            for held in self._held:
                self._inbox.put(held)
            self._held = []
        return self._recv_exact(_LEN.size)

    def _recv_loop(self) -> None:
        try:
            while not self._closed:
                header = self._recv_header()
                if header is None:
                    break
                (length,) = _LEN.unpack(header)
                body = self._recv_exact(length)
                if body is None:
                    break
                frame = json.loads(body.decode("utf-8"))
                self.flight.record(
                    "recv", type=frame.get("type"),
                    rid=frame.get("rid"),
                    seq=(frame.get("msg") or {}).get("sequenceNumber"),
                )
                _FRAMES_RECV.inc()
                rid = frame.get("rid")
                if rid is not None:
                    with self._pending_lock:
                        pending = self._pending.pop(rid, None)
                    if pending is not None:
                        event, slot = pending
                        slot.append(frame)
                        event.set()
                    continue
                kind = frame.get("type")
                if kind == "connected":
                    self._on_connected(frame)
                elif kind == "connect_document_error":
                    # deliver directly from the pump: the dispatcher
                    # takes self.lock before delivering, but callers
                    # hold that lock around Container.load while
                    # waiting on _connected — routing the rejection
                    # through the dispatcher would deadlock into a
                    # TimeoutError instead of a prompt PermissionError
                    self._on_connect_error(frame)
                else:
                    if kind == "op":
                        fault = _SITE_FRAME_IN.fire(
                            doc=self.document_id)
                        if fault == KIND_DROP:
                            # the slow-consumer shape: the fanout
                            # frame vanishes; the container's gap
                            # detection refetches it from delta
                            # storage
                            continue
                        if fault == KIND_DUPLICATE:
                            # at-least-once shape: the container's
                            # inbound seq check drops the copy
                            self._inbox.put(frame)
                        elif fault in (KIND_REORDER, KIND_DELAY):
                            # held past the next delivered frame:
                            # out-of-order arrival — gap refetch +
                            # seq dedupe absorb it
                            self._held.append(frame)
                            continue
                    self._inbox.put(frame)
                    if self._held:
                        for held in self._held:
                            self._inbox.put(held)
                        self._held = []
        finally:
            # even on a parse error the shutdown protocol must run, or
            # the dispatcher and every pending request hang
            self.flight.record("transport-closed")
            self._closed = True
            for held in self._held:
                # chaos-held frames still deliver (late, like the
                # reordered arrivals they model) — held-forever would
                # be a silent drop without the drop accounting
                self._inbox.put(held)
            self._held = []
            self._inbox.put(None)
            with self._pending_lock:
                waiters = list(self._pending.values())
                self._pending.clear()
            for event, _slot in waiters:
                event.set()
            # a thread blocked in the connect_document handshake must
            # fail promptly too (socket death mid-handshake otherwise
            # waits out the full timeout)
            self._on_transport_closed()

    def _on_transport_closed(self) -> None:
        self._connected.set()

    def _dispatch_loop(self) -> None:
        while True:
            frame = self._inbox.get()
            if frame is None:
                break
            try:
                with self.lock:
                    self._deliver(frame)
            except Exception:  # noqa: BLE001 - must fail LOUDLY
                # A delivery callback raising used to kill this thread
                # SILENTLY: every later broadcast (including the acks
                # of ops already submitted) was dropped and the
                # container waited on pending ops forever — the exact
                # shape of the round-5 ~1-in-3 whiteboard stall (a
                # foreign op sequenced mid-batch tripped the
                # ScheduleManager assert here). Continuing to deliver
                # would be no better: the fault may have torn the
                # runtime mid-message, and feeding it further ops
                # serves silently-divergent state. Fail LOUDLY and
                # DETECTABLY instead: record the fault, print it, and
                # tear the transport down — the app layer reconnects
                # and the pending-state machinery resubmits exactly
                # (the same recovery path a dropped connection takes).
                import traceback

                err = (
                    f"dispatch fault on {frame.get('type')!r}: "
                    f"{traceback.format_exc()}"
                )
                _DISPATCH_FAULTS.inc()
                self.flight.record("dispatch-fault",
                                   type=frame.get("type"))
                with self.lock:
                    self.last_error = err
                print(
                    f"socket-driver[{self.document_id}]: {err}",
                    file=sys.stderr,
                )
                # postmortem: the last N transport events that led
                # here (what was delivered, what was in flight)
                self.last_flight_dump = self.flight.dump_to(
                    reason="dispatch fault teardown")
                self.close()
                break

    def _on_connected(self, frame: dict) -> None:
        """Handshake-ack hook (the multiplexing subclass routes by
        document_id)."""
        self.agreed_version = frame.get("version")
        self._connected.set()

    def _on_connect_error(self, frame: dict) -> None:
        # auth/handshake rejection: record the reason and release the
        # waiter so it can raise immediately with the cause
        self.auth_error = frame.get("message", "rejected")
        self._connected.set()

    def _deliver(self, frame: dict) -> None:
        kind = frame.get("type")
        if kind == "error":
            # a submit the server could neither sequence nor nack
            # (e.g. undecodable op contents): losing it silently would
            # stall the CSN stream with no diagnostic
            self.last_error = frame.get("message", "server error")
            print(
                f"socket-driver[{self.document_id}]: server error: "
                f"{self.last_error}",
                file=sys.stderr,
            )
            return
        if kind == "op" and self._on_message is not None:
            msg = message_from_json(frame["msg"])
            # per-session deserialized copy: the deliver hop is this
            # client's own (unlike the shared in-proc object)
            trace_stamp(msg.traces, "driver", "deliver")
            self._on_message(msg)
        elif kind == "nack" and self._on_nack is not None:
            from ..service.ingress import document_message_from_json

            op = frame.get("operation")
            self._on_nack(Nack(
                operation=document_message_from_json(op)
                if op else None,
                sequence_number=frame["sequence_number"],
                error_type=NackErrorType(frame["error_type"]),
                message=frame.get("message", ""),
                retry_after_seconds=frame.get("retry_after_seconds"),
                # qos shed attribution: OPTIONAL on the wire (absent
                # from pre-qos servers — test_wire_compat)
                pressure_tier=frame.get("pressure_tier"),
                shed_class=frame.get("shed_class"),
            ))

    def _request(self, data: dict) -> dict:
        rid = next(self._rid)
        event: threading.Event = threading.Event()
        slot: list = []
        with self._pending_lock:
            self._pending[rid] = (event, slot)
        self._send(dict(data, rid=rid))
        if not event.wait(self._timeout):
            with self._pending_lock:
                self._pending.pop(rid, None)
            # a missed deadline used to be a bare TimeoutError with
            # zero context; dump the recent transport events so the
            # postmortem ships with the exception
            _REQUEST_TIMEOUTS.inc()
            self.flight.record("request-timeout", type=data["type"],
                               rid=rid)
            self.last_flight_dump = self.flight.dump_to(
                reason=f"no response to {data['type']} "
                       f"(rid={rid}) within {self._timeout}s")
            raise TimeoutError(
                f"no response to {data['type']} (rid={rid}) within "
                f"{self._timeout}s; recent transport events:\n"
                f"{self.last_flight_dump}"
            )
        if not slot:
            raise ConnectionError("connection closed mid-request")
        frame = slot[0]
        if frame.get("type") == "error":
            msg = frame.get("message", "server error")
            if frame.get("error_kind") == "permission":
                raise PermissionError(msg)
            if frame.get("error_kind") == "throttle":
                # qos shed a storage-plane request: surface it as the
                # RETRIABLE shape run_with_retry honors, with the
                # server's honest retry hint as the backoff floor
                from .driver_utils import RetriableError

                raise RetriableError(
                    msg,
                    retry_after_seconds=frame.get(
                        "retry_after_seconds"),
                )
            raise RuntimeError(msg)
        return frame

    # -- DocumentService surface ---------------------------------------

    def connect_to_delta_stream(
        self,
        client_id: str,
        on_message: Callable[[SequencedMessage], None],
        on_nack: Optional[Callable[[Nack], None]] = None,
    ) -> "SocketDeltaConnection":
        self._on_message = on_message
        self._on_nack = on_nack
        # a retried handshake must not see the previous attempt's
        # rejection or completion state
        self.auth_error = None
        self._connected.clear()
        if self._closed:
            # transport already dead: clear() above just discarded the
            # shutdown wakeup — fail now, not after the full timeout
            raise ConnectionError("connection closed")
        self._send(build_connect_frame(
            self.document_id, client_id, self.mode,
            self.tenant_id, self.token,
            versions=self.wire_versions))
        if not self._connected.wait(self._timeout):
            self.last_flight_dump = self.flight.dump_to(
                reason="connect_document handshake deadline missed")
            raise TimeoutError(
                "connect_document handshake timed out; recent "
                f"transport events:\n{self.last_flight_dump}")
        if self.auth_error is not None:
            raise PermissionError(
                f"connect_document rejected: {self.auth_error}")
        if self._closed:
            raise ConnectionError("connection closed during handshake")
        return SocketDeltaConnection(self, client_id)

    def read_ops(self, from_seq: int,
                 to_seq: Optional[int] = None) -> list[SequencedMessage]:
        # storage-plane requests carry the token: the loader reads
        # snapshot + ops BEFORE connect_document
        return self._doc_read_ops(self.document_id, from_seq, to_seq,
                                  auth=(self.tenant_id, self.token))

    def get_latest_summary(self) -> Optional[tuple[int, dict]]:
        return self._doc_latest_summary(
            self.document_id, auth=(self.tenant_id, self.token))

    # single definitions of the request planes, parameterized by
    # document so the multiplexed facades reuse them verbatim; ``auth``
    # lets a facade supply ITS document's (tenant_id, token) over the
    # shared transport
    def _doc_read_ops(self, document_id: str, from_seq: int,
                      to_seq: Optional[int] = None, auth=None
                      ) -> list[SequencedMessage]:
        data = {
            "type": "read_ops", "document_id": document_id,
            "from_seq": from_seq, "to_seq": to_seq,
        }
        if auth is not None and auth[1] is not None:
            data["tenant_id"], data["token"] = auth
        frame = self._request(data)
        return [message_from_json(m) for m in frame["msgs"]]

    def upload_summary(self, summary: dict) -> str:
        """Upload a summary tree to service storage and return its
        root handle — the storage half of the reference's summarize
        flow (driver-definitions/src/storage.ts:119
        uploadSummaryWithContext): the summarize op then proposes the
        handle instead of carrying the tree on the op stream.

        Wire >= 1.1 only: on a 1.0-agreed connection raise the
        transient-shaped error the container's summarize fallback
        catches, so an old-server pairing degrades to inline
        summaries instead of sending frames the server rejects."""
        if self.agreed_version is not None and \
                wire_version_lt(self.agreed_version, "1.1"):
            raise RuntimeError(
                f"summary upload needs wire >= 1.1; connection "
                f"agreed {self.agreed_version}"
            )
        return self._doc_upload_summary(
            self.document_id, summary,
            auth=(self.tenant_id, self.token))

    _UPLOAD_CHUNK = 512 * 1024

    def _doc_upload_summary(self, document_id: str, summary: dict,
                            auth=None) -> str:
        """Chunks PIPELINE: intermediate frames are fire-and-forget
        (TCP ordering + backpressure carry them) and only the final
        chunk is a waited request — one round trip per upload, so a
        large summary does not hold the dispatch path hostage for
        total/chunk RTTs (matters most on the multiplexed socket,
        where every document shares one connection)."""
        from ..protocol.serialization import encode_contents

        payload = json.dumps(encode_contents(summary))
        parts = [
            payload[i:i + self._UPLOAD_CHUNK]
            for i in range(0, len(payload), self._UPLOAD_CHUNK)
        ] or [""]
        upload_id = f"u{next(self._rid)}"
        for i, part in enumerate(parts):
            data = {
                "type": "upload_summary_chunk",
                "document_id": document_id,
                "upload_id": upload_id,
                "chunk": i, "total": len(parts), "data": part,
            }
            if auth is not None and auth[1] is not None:
                data["tenant_id"], data["token"] = auth
            if i + 1 < len(parts):
                self._send(data)
            else:
                frame = self._request(data)
        return frame["handle"]

    def _doc_latest_summary(self, document_id: str, auth=None
                            ) -> Optional[tuple[int, dict]]:
        data = {
            "type": "fetch_summary", "document_id": document_id,
        }
        if auth is not None and auth[1] is not None:
            data["tenant_id"], data["token"] = auth
        frame = self._request(data)
        if frame.get("sequence_number") is None:
            return None
        return frame["sequence_number"], decode_contents(frame["summary"])

    def close(self) -> None:
        self._closed = True
        # shutdown BEFORE close: close() alone does not unblock a
        # thread currently inside recv() (it waits out the socket
        # timeout, deferring our FIN ~10s and stalling server-side
        # connection teardown); shutdown delivers EOF immediately
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class SocketDeltaConnection:
    """IDocumentDeltaConnection over the wire.

    BATCH BOXCARRING (wire >= 1.2): a runtime batch (ops between a
    ``{"batch": true}`` and ``{"batch": false}`` metadata mark) is
    buffered here and sent as ONE ``submitOp`` frame carrying the op
    array. This is the liveness fix for the round-5 ~1-in-3
    submit->ack stall: per-op frames from two TCP sessions interleave
    on the server's event loop, so another client's op could be
    SEQUENCED in the middle of this client's batch — receivers'
    ScheduleManager treats a foreign op mid-batch as a service
    ordering violation (it is one) and the replica stops acking. The
    reference never has this problem because a socket.io submitOp
    carries the whole batch array and alfred tickets it atomically;
    this restores that contract. Against a pre-1.2 server the driver
    degrades to per-op frames (the legacy racy behavior, for the
    compat matrix).

    COLUMNAR BATCHES (wire >= 1.3): at the batch flush point, a batch
    inside the columnar subset (plain text INSERT/REMOVEs, untraced —
    protocol/columnar.py) is sent as ONE ``submitOp`` frame whose
    payload IS the column layout ("cols"), which the service validates
    once and slices instead of re-interpreting per op. Anything the
    columns cannot express — and any batch against a pre-1.3 server —
    rides the wire-1.2 row boxcar unchanged (the compatibility
    fallback the compat matrix pins)."""

    def __init__(self, service: SocketDocumentService, client_id: str):
        self._service = service
        self.client_id = client_id
        self.open = True
        self._batch: list[DocumentMessage] = []
        self._batching = False

    def _boxcar_capable(self) -> bool:
        agreed = self._service.agreed_version
        return agreed is not None and not wire_version_lt(agreed, "1.2")

    def _columnar_capable(self) -> bool:
        agreed = self._service.agreed_version
        return agreed is not None and not wire_version_lt(agreed, "1.3")

    def submit(self, op: DocumentMessage) -> None:
        assert self.open, "submit on closed connection"
        from ..protocol.constants import batch_flag

        flag = batch_flag(op.metadata)
        if self._boxcar_capable() and (self._batching or flag is True):
            # buffered as the MESSAGE, not its wire form: the flush
            # point decides the encoding (columnar vs row boxcar) for
            # the batch as a unit, and the driver:send hop stamps at
            # the actual wire write below
            self._batch.append(op)
            self._batching = flag is not False
            if self._batching:
                return
            ops, self._batch = self._batch, []
            cols = (encode_columns(ops)
                    if self._columnar_capable() else None)
            if cols is not None:
                # traceless by design: the column layout carries no
                # traces column, and encode_columns routed any traced
                # (or otherwise inexpressible) batch to the row path
                # below — trace_ops traffic keeps its full hop chain
                self._service._send({
                    "type": "submitOp",
                    "document_id": self._service.document_id,
                    "cols": cols,
                })
                return
            wires = []
            for o in ops:
                trace_stamp(o.traces, "driver", "send")
                wires.append(document_message_to_json(o))
            self._service._send({
                "type": "submitOp",
                "document_id": self._service.document_id,
                "ops": wires,
            })
            return
        # stamped BEFORE serialization so the hop rides the wire
        trace_stamp(op.traces, "driver", "send")
        self._service._send({
            "type": "submitOp",
            "document_id": self._service.document_id,
            "op": document_message_to_json(op),
        })

    def disconnect(self) -> None:
        if not self.open:
            return
        self.open = False
        # an unterminated batch dies with the connection: its ops stay
        # in the runtime's pending state and resubmit on reconnect
        self._batch = []
        self._batching = False
        try:
            self._service._send({
                "type": "disconnect_document",
                "document_id": self._service.document_id,
            })
        except OSError:
            pass  # server already gone; the session cleans up


class SocketDocumentServiceFactory:
    """IDocumentServiceFactory against a running dev service
    (`python -m fluidframework_tpu.service`)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 7070):
        self.host = host
        self.port = port

    def create_document_service(self, document_id: str
                                ) -> SocketDocumentService:
        return SocketDocumentService(self.host, self.port, document_id)
