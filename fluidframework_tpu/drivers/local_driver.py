"""Local driver: DocumentService over the in-proc LocalServer.

Reference: packages/drivers/local-driver/src/localDocumentService.ts
(:23) — pairs with LocalDeltaConnectionServer for integration tests.
"""
from __future__ import annotations

from typing import Callable, Optional

from ..obs.trace import stamp as trace_stamp
from ..protocol.messages import DocumentMessage, Nack, SequencedMessage
from ..service.local_server import DeltaConnection, LocalServer


class _TracingDeltaConnection:
    """Stamps the ``driver:send`` hop on outbound ops, so in-proc
    traces line up with the socket driver's (no ``driver:deliver``
    stamp in-proc: the broadcast message OBJECT is shared by every
    subscriber, and per-client delivery stamps on a shared list would
    pollute each other's view)."""

    def __init__(self, inner: DeltaConnection):
        self._inner = inner

    def submit(self, op: DocumentMessage) -> None:
        trace_stamp(op.traces, "driver", "send")
        self._inner.submit(op)

    def disconnect(self) -> None:
        self._inner.disconnect()

    @property
    def open(self) -> bool:
        return self._inner.open

    @property
    def client_id(self) -> str:
        return self._inner.client_id


class LocalDocumentService:
    def __init__(self, server: LocalServer, document_id: str):
        self._server = server
        self.document_id = document_id

    def connect_to_delta_stream(
        self,
        client_id: str,
        on_message: Callable[[SequencedMessage], None],
        on_nack: Optional[Callable[[Nack], None]] = None,
    ) -> _TracingDeltaConnection:
        return _TracingDeltaConnection(self._server.connect(
            self.document_id, client_id, on_message, on_nack
        ))

    def read_ops(self, from_seq: int, to_seq: Optional[int] = None
                 ) -> list[SequencedMessage]:
        return self._server.read_ops(self.document_id, from_seq, to_seq)

    def get_latest_summary(self) -> Optional[tuple[int, dict]]:
        latest = self._server.latest_summary(self.document_id)
        if latest is None:
            return None
        return latest.sequence_number, latest.summary


class LocalDocumentServiceFactory:
    """IDocumentServiceFactory: document id -> service."""

    def __init__(self, server: LocalServer):
        self.server = server

    def create_document_service(self, document_id: str
                                ) -> LocalDocumentService:
        return LocalDocumentService(self.server, document_id)
