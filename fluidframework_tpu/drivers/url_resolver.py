"""URL resolvers — the request-routing seam between a host app and
the drivers.

Reference: packages/drivers/routerlicious-urlResolver/src/urlResolver.ts
:25 (RouterliciousUrlResolver.resolve: request URL -> IFluidResolvedUrl
with fluid:// identity + service endpoints + token),
packages/drivers/local-driver/src/localResolver.ts:32 (LocalResolver
for the in-proc dev service), and the loader flow that consumes them
(container.ts Loader.resolve). The reference's host apps never build a
driver by hand — they hand a URL to a resolver and get back the
document identity + endpoints the driver factory needs; this module is
that seam for the TPU repo's drivers (closing the §2.6
aux-drivers row: the dev service + socket driver already play the
tinylicious role; this adds the url-resolver layer, and
``debug_driver`` the debugger layer).

URL shape (the fftpu scheme mirrors fluid://):

    fftpu://<host>:<port>/<tenant>/<document>
    fftpu-local:///<document>            (in-proc LocalServer)
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional, Protocol
from urllib.parse import quote, unquote, urlparse


@dataclass
class ResolvedUrl:
    """IFluidResolvedUrl equivalent (driver-definitions
    urlResolver.ts): canonical identity + endpoints + tokens."""

    url: str                     # canonical fftpu:// identity
    document_id: str
    tenant_id: Optional[str] = None
    endpoints: dict = field(default_factory=dict)  # {"ordering": ...}
    tokens: dict = field(default_factory=dict)     # {"jwt": ...}


class UrlResolver(Protocol):
    def resolve(self, request_url: str) -> Optional[ResolvedUrl]:
        """Request URL -> resolved identity/endpoints, or None if the
        request is not for this resolver (resolvers chain)."""
        ...

    def get_absolute_url(self, resolved: ResolvedUrl,
                         relative: str) -> str:
        """Canonical shareable URL for a path within the document."""
        ...


class SocketUrlResolver:
    """Resolves fftpu:// (and localhost http://) URLs to the framed-
    TCP service — routerlicious-urlResolver equivalence. A token
    provider (riddler-analogue JWT mint) is attached per resolve, the
    way the reference resolver awaits getToken()."""

    def __init__(self,
                 token_provider: Optional[
                     Callable[[str, str], str]] = None):
        self._token_provider = token_provider

    def resolve(self, request_url: str) -> Optional[ResolvedUrl]:
        u = urlparse(request_url)
        if u.scheme not in ("fftpu", "http"):
            return None
        if u.scheme == "http" and u.hostname not in (
                "localhost", "127.0.0.1"):
            return None  # not ours; let another resolver try
        parts = [p for p in (u.path or "").split("/") if p]
        if len(parts) >= 2:
            tenant_id, document_id = parts[0], parts[1]
        elif len(parts) == 1:
            tenant_id, document_id = None, parts[0]
        else:
            return None
        tenant_id = unquote(tenant_id) if tenant_id else None
        document_id = unquote(document_id)
        host = u.hostname or "127.0.0.1"
        port = u.port or 7070
        tokens = {}
        if self._token_provider is not None and tenant_id:
            tokens["jwt"] = self._token_provider(
                tenant_id, document_id)
        return ResolvedUrl(
            url=_canonical(host, port, tenant_id, document_id),
            document_id=document_id,
            tenant_id=tenant_id,
            endpoints={"ordering": {"host": host, "port": port}},
            tokens=tokens,
        )

    def get_absolute_url(self, resolved: ResolvedUrl,
                         relative: str) -> str:
        rel = relative.lstrip("/")
        return f"{resolved.url}/{rel}" if rel else resolved.url


class LocalUrlResolver:
    """LocalResolver equivalent: routes fftpu-local:// requests to an
    in-proc LocalServer (the dev loop's resolver)."""

    def __init__(self, server):
        self.server = server

    def resolve(self, request_url: str) -> Optional[ResolvedUrl]:
        u = urlparse(request_url)
        if u.scheme != "fftpu-local":
            return None
        parts = [p for p in (u.path or "").split("/") if p]
        if not parts:
            return None
        document_id = unquote(parts[-1])
        return ResolvedUrl(
            url=f"fftpu-local:///{quote(document_id, safe='')}",
            document_id=document_id,
            endpoints={"local_server": self.server},
        )

    def get_absolute_url(self, resolved: ResolvedUrl,
                         relative: str) -> str:
        rel = relative.lstrip("/")
        return f"{resolved.url}/{rel}" if rel else resolved.url


def _canonical(host, port, tenant_id, document_id) -> str:
    tid = quote(tenant_id, safe="") if tenant_id else None
    did = quote(document_id, safe="")
    path = f"{tid}/{did}" if tid else did
    return f"fftpu://{host}:{port}/{path}"


def resolve_request(resolvers, request_url: str) -> ResolvedUrl:
    """First-match resolver chain (the loader walks its resolvers the
    same way; container.ts resolveWithLocationRedirectionHandling)."""
    for r in resolvers:
        resolved = r.resolve(request_url)
        if resolved is not None:
            return resolved
    raise ValueError(f"no resolver for {request_url!r}")


def create_document_service(resolved: ResolvedUrl, **kwargs):
    """Resolved URL -> the right driver (the driver-factory half of
    the reference's IDocumentServiceFactory.createDocumentService)."""
    if "local_server" in resolved.endpoints:
        if kwargs:
            # the in-proc driver takes no connection options; silently
            # dropping what the socket branch honors would make the
            # same call behave differently per URL scheme
            raise TypeError(
                f"local driver takes no options: {sorted(kwargs)}"
            )
        from .local_driver import LocalDocumentServiceFactory

        return LocalDocumentServiceFactory(
            resolved.endpoints["local_server"]
        ).create_document_service(resolved.document_id)
    ordering = resolved.endpoints["ordering"]
    from .socket_driver import SocketDocumentService

    return SocketDocumentService(
        ordering["host"], ordering["port"], resolved.document_id,
        tenant_id=resolved.tenant_id,
        token=resolved.tokens.get("jwt"),
        **kwargs,
    )


def load_container_from_url(resolvers, request_url: str,
                            client_id: str, **kwargs):
    """The host-app one-liner: URL -> resolver chain -> driver ->
    attached Container. Returns (container, service)."""
    from ..loader import Container

    resolved = resolve_request(resolvers, request_url)
    svc = create_document_service(resolved)
    lock = getattr(svc, "lock", None)
    if lock is not None:
        with lock:
            container = Container.load(
                svc, client_id=client_id, **kwargs)
    else:
        container = Container.load(svc, client_id=client_id, **kwargs)
    return container, svc
