"""Driver contracts: how a client reaches a service.

Reference: packages/common/driver-definitions/src/storage.ts —
``IDocumentService`` (:288) with its three planes:
``IDocumentDeltaConnection`` (:193, live op stream),
``IDocumentDeltaStorageService`` (:76, op range reads) and
``IDocumentStorageService`` (:119, summaries/snapshots).
"""
from __future__ import annotations

from typing import Callable, Optional, Protocol

from ..protocol.messages import (
    DocumentMessage,
    Nack,
    SequencedMessage,
)


class DeltaStreamConnection(Protocol):
    """Live op stream (IDocumentDeltaConnection)."""

    client_id: str
    open: bool

    def submit(self, op: DocumentMessage) -> None: ...

    def disconnect(self) -> None: ...


class DocumentService(Protocol):
    """IDocumentService (storage.ts:288): one document, three planes."""

    document_id: str

    def connect_to_delta_stream(
        self,
        client_id: str,
        on_message: Callable[[SequencedMessage], None],
        on_nack: Optional[Callable[[Nack], None]] = None,
    ) -> DeltaStreamConnection: ...

    def read_ops(self, from_seq: int,
                 to_seq: Optional[int] = None) -> list[SequencedMessage]: ...

    def get_latest_summary(self) -> Optional[tuple[int, dict]]:
        """Returns (sequence_number, summary) or None."""
        ...
