"""Drivers: client <-> service adapters.

Reference analogue: packages/drivers/*.
"""
from .caching_driver import (
    CachingDocumentService,
    CachingMultiplexFactory,
    FileSnapshotCache,
    MultiplexedSocketClient,
    SnapshotCache,
)
from .debug_driver import DebugDocumentService
from .definitions import DeltaStreamConnection, DocumentService
from .driver_utils import (
    PrefetchingDocumentService,
    RetriableError,
    RetryDocumentService,
    run_with_retry,
)
from .file_driver import load_document, save_document
from .local_driver import LocalDocumentService, LocalDocumentServiceFactory
from .replay_driver import ReplayDocumentService
from .socket_driver import (
    SocketDocumentService,
    SocketDocumentServiceFactory,
)
from .url_resolver import (
    LocalUrlResolver,
    ResolvedUrl,
    SocketUrlResolver,
    UrlResolver,
    load_container_from_url,
    resolve_request,
)

__all__ = [
    "CachingDocumentService",
    "DebugDocumentService",
    "LocalUrlResolver",
    "ResolvedUrl",
    "SocketUrlResolver",
    "UrlResolver",
    "load_container_from_url",
    "resolve_request",
    "CachingMultiplexFactory",
    "DeltaStreamConnection",
    "DocumentService",
    "FileSnapshotCache",
    "MultiplexedSocketClient",
    "SnapshotCache",
    "PrefetchingDocumentService",
    "RetriableError",
    "RetryDocumentService",
    "run_with_retry",
    "LocalDocumentService",
    "LocalDocumentServiceFactory",
    "ReplayDocumentService",
    "SocketDocumentService",
    "SocketDocumentServiceFactory",
    "load_document",
    "save_document",
]
