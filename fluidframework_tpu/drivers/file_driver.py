"""File driver: persist/load op streams + summaries as JSON files.

Reference: packages/drivers/file-driver — reads snapshots/ops from
local files for tooling (replay tool, corpus benchmarks).
"""
from __future__ import annotations

import json
from pathlib import Path
from typing import Optional

from ..protocol.messages import SequencedMessage
from ..protocol.serialization import (
    decode_contents,
    encode_contents,
    message_from_json,
    message_to_json,
)
from .replay_driver import ReplayDocumentService


def save_document(path: str | Path, document_id: str,
                  messages: list[SequencedMessage],
                  summary: Optional[tuple[int, dict]] = None) -> None:
    blob = {
        "documentId": document_id,
        "messages": [message_to_json(m) for m in messages],
        # summaries can hold FluidHandles and op dataclasses: encode
        "summary": (
            {"sequenceNumber": summary[0],
             "tree": encode_contents(summary[1])}
            if summary else None
        ),
    }
    Path(path).write_text(json.dumps(blob))


def load_document(path: str | Path) -> ReplayDocumentService:
    blob = json.loads(Path(path).read_text())
    summary = None
    if blob.get("summary"):
        summary = (blob["summary"]["sequenceNumber"],
                   decode_contents(blob["summary"]["tree"]))
    return ReplayDocumentService(
        document_id=blob["documentId"],
        messages=[message_from_json(d) for d in blob["messages"]],
        summary=summary,
    )
