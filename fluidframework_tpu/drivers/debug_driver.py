"""Debugger driver — step-through op playback over any inner driver.

Reference: packages/drivers/debugger/src/fluidDebuggerController.ts:34
(DebugReplayController: user picks a starting point, then releases
sequenced ops in controlled steps while the container renders each
intermediate state) over replay-driver's ReplayController seam. The
TPU-repo construction wraps ANY DocumentService: the delta stream
connection it hands out buffers incoming sequenced messages and only
forwards them under controller commands — ``step(n)``,
``play_to(seq)``, ``resume_live()`` — so a host can inspect a
document's evolution message by message against a live service, not
just a file recording (tools/replay covers the offline case).
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Optional

from ..protocol.messages import DocumentMessage, SequencedMessage


class DebugDocumentService:
    """DocumentService wrapper with a playback gate on the delta
    stream. Storage/read paths pass through untouched.

    Delivery ordering: every release path (gated drain, live
    passthrough) appends to ONE fifo outbox under the state lock and
    drains it under a separate delivery lock, so a control-thread
    ``resume_live()`` can never race the network thread's next live
    message past still-buffered earlier sequence numbers."""

    def __init__(self, inner, start_paused: bool = True):
        self.inner = inner
        self.document_id = inner.document_id
        self._lock = threading.Lock()
        # RLock: a listener can synchronously trigger the next
        # _on_message on the same thread (in-proc LocalServer); the
        # nested pump drains the fifo and the outer loop finds it
        # empty — order still the fifo's
        self._deliver_lock = threading.RLock()
        self._buffer: list[SequencedMessage] = []
        self._outbox: deque[SequencedMessage] = deque()
        self._listener: Optional[Callable] = None
        self._paused = start_paused
        self._allowance = 0          # messages step() still owes
        self._play_to: Optional[int] = None
        self.delivered_seq = 0       # last seq released downstream
        # breakpoint: pause BEFORE delivering this seq. Guarded by
        # _lock (the drain gate reads it); mutate via set_breakpoint.
        self._break_at: Optional[int] = None

    # -- DocumentService surface --------------------------------------

    def connect_to_delta_stream(self, client_id: str,
                                listener: Callable, *args, **kwargs):
        self._listener = listener
        return self.inner.connect_to_delta_stream(
            client_id, self._on_message, *args, **kwargs)

    def read_ops(self, from_seq: int, to_seq: Optional[int] = None):
        return self.inner.read_ops(from_seq, to_seq)

    def get_latest_summary(self):
        return self.inner.get_latest_summary()

    def __getattr__(self, name):
        # everything else (lock, upload_summary, close, ...) passes
        # through to the wrapped driver
        return getattr(self.inner, name)

    # -- playback controller (fluidDebuggerController.ts) -------------

    @property
    def pending_count(self) -> int:
        with self._lock:
            return len(self._buffer)

    @property
    def break_at(self) -> Optional[int]:
        return self._break_at

    def set_breakpoint(self, seq: Optional[int]) -> None:
        """Pause BEFORE delivering ``seq`` (None clears). The gate
        reads the breakpoint under ``_lock`` on the network thread, so
        an unsynchronized ``break_at`` write from a control thread
        could be missed by an in-flight drain — this setter is the
        supported mutation path."""
        with self._lock:
            self._break_at = seq

    def pause(self) -> None:
        """Strict stop: beyond gating future releases, recall every
        released-but-undelivered message from the outbox back to the
        buffer head (outbox messages precede buffered ones in the
        fifo, so re-prepending preserves order). A message another
        thread already popped for delivery cannot be recalled; nothing
        further leaves after pause() returns."""
        with self._lock:
            self._paused = True
            self._allowance = 0
            self._play_to = None
            if self._outbox:
                self._buffer[:0] = self._outbox
                self._outbox.clear()

    def step(self, n: int = 1) -> int:
        """Release up to ``n`` buffered messages; returns how many
        were delivered now (more may flow as they arrive until the
        allowance is spent)."""
        with self._lock:
            self._allowance += n
            self._outbox.extend(self._drain_locked())
        return self._pump()

    def play_to(self, seq: int) -> int:
        """Release every buffered/incoming message with
        sequence_number <= seq."""
        with self._lock:
            self._play_to = max(self._play_to or 0, seq)
            self._outbox.extend(self._drain_locked())
        return self._pump()

    def resume_live(self) -> int:
        """Drop the gate entirely: drain the buffer and forward
        everything from now on (the debugger's 'go live')."""
        with self._lock:
            self._paused = False
            self._allowance = 0
            self._play_to = None
            self._outbox.extend(self._buffer)
            self._buffer = []
        return self._pump()

    # -- internals ----------------------------------------------------

    def _on_message(self, msg: SequencedMessage) -> None:
        with self._lock:
            if not self._paused:
                # live passthrough rides the SAME fifo so it cannot
                # overtake anything a concurrent resume just released
                self._outbox.append(msg)
            else:
                self._buffer.append(msg)
                self._outbox.extend(self._drain_locked())
        self._pump()

    def _drain_locked(self) -> list:
        out = []
        while self._buffer:
            head = self._buffer[0]
            if self._break_at is not None and \
                    head.sequence_number >= self._break_at:
                self._allowance = 0
                self._play_to = None
                break
            if self._play_to is not None and \
                    head.sequence_number <= self._play_to:
                out.append(self._buffer.pop(0))
                continue
            if self._allowance > 0:
                self._allowance -= 1
                out.append(self._buffer.pop(0))
                continue
            break
        return out

    def _pump(self) -> int:
        """Drain the outbox in fifo order under the delivery lock.
        A thread that appended while another was pumping either gets
        its messages delivered by that pump or delivers them itself
        right after acquiring the lock — order is the fifo's."""
        n = 0
        with self._deliver_lock:
            while True:
                with self._lock:
                    if not self._outbox:
                        break
                    m = self._outbox.popleft()
                self.delivered_seq = max(
                    self.delivered_seq, m.sequence_number)
                if self._listener is not None:
                    self._listener(m)
                n += 1
        return n
