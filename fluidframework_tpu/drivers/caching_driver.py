"""Caching + multiplexing driver — the odsp-driver class.

Reference: packages/drivers/odsp-driver — the production driver whose
two defining behaviors beyond the routerlicious driver are
(a) PERSISTENT SNAPSHOT CACHING (odsp-driver + driver-web-cache:
snapshots cached across sessions, served stale-while-offline, age
policy decides refresh) and (b) SOCKET MULTIPLEXING: many documents
share one physical websocket.

TPU-repo construction:

- ``SnapshotCache`` / ``FileSnapshotCache``: (document -> sequence
  number, summary, cached_at); the file variant survives the process
  (driver-web-cache's IndexedDB analogue).
- ``CachingDocumentService``: wraps any DocumentService. Fresh cache
  hits skip the network; misses fetch and populate; fetch FAILURES
  fall back to whatever the cache holds (offline load), and the
  trailing ops come from ``read_ops`` as usual so a stale snapshot is
  only a longer catch-up, never wrong.
- ``MultiplexedSocketClient``: ONE TCP connection to the ingress
  shared by every document's service (the server's per-session
  connection map already routes ops by document_id — ingress.py
  _ClientSession.connections); per-document facades expose the
  standard DocumentService surface.
"""
from __future__ import annotations

import hashlib
import json
import os
import sys
import threading
import time
from typing import Any, Callable, Optional

from ..protocol.constants import wire_version_lt
from ..protocol.serialization import decode_contents, encode_contents  # noqa: F401 - decode used by cache load
from .socket_driver import (
    SocketDeltaConnection,
    SocketDocumentService,
    build_connect_frame,
)


# ----------------------------------------------------------------------
# snapshot cache


class SnapshotCache:
    """In-memory snapshot cache (driver-web-cache interface)."""

    def __init__(self):
        self._entries: dict[str, dict] = {}

    def get(self, document_id: str) -> Optional[dict]:
        return self._entries.get(document_id)

    def put(self, document_id: str, sequence_number: int,
            summary: dict) -> None:
        entry = {
            "sequence_number": sequence_number,
            "summary": summary,
            "cached_at": time.time(),
        }
        self._entries[document_id] = entry
        self._persist(document_id, entry)

    def _persist(self, document_id: str, entry: dict) -> None:
        pass


class FileSnapshotCache(SnapshotCache):
    """On-disk snapshot cache surviving the process (the IndexedDB
    analogue)."""

    def __init__(self, root: str):
        super().__init__()
        self.root = root
        os.makedirs(root, exist_ok=True)
        for name in os.listdir(root):
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(root, name)) as f:
                    entry = json.load(f)
                entry["summary"] = decode_contents(entry["summary"])
                # the real id lives inside the entry; the filename is a
                # hash (a raw id containing '/' or '..' would escape
                # the cache root and never be rescanned)
                doc_id = entry.pop("document_id", name[:-5])
                # a legacy raw-named file may coexist with the hashed
                # rewrite of the same document: scan order is
                # arbitrary, so keep the newer entry
                prev = self._entries.get(doc_id)
                if prev is not None and \
                        prev.get("cached_at", 0) >= entry.get("cached_at", 0):
                    continue
                self._entries[doc_id] = entry
            except (ValueError, KeyError, OSError) as e:
                # corrupt cache entry: treat as miss — but say so; a
                # cache that silently sheds entries looks like a cold
                # cache and hides real on-disk corruption
                print(
                    f"snapshot-cache[{self.root}]: dropping corrupt "
                    f"entry {name!r} ({type(e).__name__}: {e})",
                    file=sys.stderr,
                )
                continue

    @staticmethod
    def _filename(document_id: str) -> str:
        return hashlib.sha256(
            document_id.encode("utf-8")).hexdigest() + ".json"

    def _persist(self, document_id: str, entry: dict) -> None:
        path = os.path.join(self.root, self._filename(document_id))
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(dict(entry, document_id=document_id,
                           summary=encode_contents(entry["summary"])), f)
        os.replace(tmp, path)


class CachingDocumentService:
    """Snapshot-caching wrapper over any DocumentService (odsp-driver
    load flow: cached snapshot first, network refresh by age policy,
    stale fallback when the fetch fails)."""

    def __init__(self, inner, cache: SnapshotCache,
                 max_age_s: float = 60.0):
        self._inner = inner
        self.cache = cache
        self.max_age_s = max_age_s
        self.last_load_source: Optional[str] = None

    @property
    def document_id(self) -> str:
        return self._inner.document_id

    @property
    def lock(self):
        return self._inner.lock

    def get_latest_summary(self) -> Optional[tuple[int, dict]]:
        entry = self.cache.get(self.document_id)
        if entry is not None and \
                time.time() - entry["cached_at"] <= self.max_age_s:
            self.last_load_source = "cache"
            return entry["sequence_number"], entry["summary"]
        try:
            latest = self._inner.get_latest_summary()
        except PermissionError:
            # auth rejection is NOT "offline": serving the stale cache
            # would keep a revoked client reading the document
            # (PermissionError subclasses OSError — it must be
            # excluded before the fallback clause)
            raise
        except (OSError, TimeoutError, ConnectionError, RuntimeError):
            if entry is not None:
                # offline: a stale snapshot + op catch-up is correct,
                # just a longer replay
                self.last_load_source = "stale-cache"
                return entry["sequence_number"], entry["summary"]
            raise
        self.last_load_source = "network"
        if latest is not None:
            self.cache.put(self.document_id, latest[0], latest[1])
        return latest

    def read_ops(self, from_seq: int, to_seq=None):
        return self._inner.read_ops(from_seq, to_seq)

    def upload_summary(self, summary: dict) -> str:
        return self._inner.upload_summary(summary)

    def connect_to_delta_stream(self, client_id, on_message,
                                on_nack=None):
        return self._inner.connect_to_delta_stream(
            client_id, on_message, on_nack)

    def close(self) -> None:
        self._inner.close()


# ----------------------------------------------------------------------
# socket multiplexing


class _DocumentFacade:
    """One document's DocumentService surface over the shared socket
    (odsp socket multiplexing: many documents, one connection)."""

    def __init__(self, client: "MultiplexedSocketClient",
                 document_id: str, tenant_id: Optional[str],
                 token: Optional[str], mode: str):
        self._client = client
        self.document_id = document_id
        self.tenant_id = tenant_id
        self.token = token
        self.mode = mode
        self.auth_error: Optional[str] = None
        self.agreed_version: Optional[str] = None
        self._connected = threading.Event()
        self._on_message: Optional[Callable] = None
        self._on_nack: Optional[Callable] = None

    @property
    def lock(self):
        # one dispatch thread serves every document on the socket: all
        # containers on this connection share its lock
        return self._client.lock

    def connect_to_delta_stream(self, client_id: str, on_message,
                                on_nack=None) -> SocketDeltaConnection:
        self._on_message = on_message
        self._on_nack = on_nack
        # a retried handshake (e.g. after a token refresh) must not
        # see the previous attempt's rejection or completion state
        self.auth_error = None
        self._connected.clear()
        if self._client._closed:
            raise ConnectionError("connection closed")
        self._client._send(build_connect_frame(
            self.document_id, client_id, self.mode,
            self.tenant_id, self.token))
        if not self._connected.wait(self._client._timeout):
            raise TimeoutError("connect_document handshake timed out")
        if self.auth_error is not None:
            raise PermissionError(
                f"connect_document rejected: {self.auth_error}")
        if self._client._closed:
            raise ConnectionError("connection closed during handshake")
        return SocketDeltaConnection(self, client_id)

    # SocketDeltaConnection needs _send + document_id
    def _send(self, data: dict) -> None:
        self._client._send(data)

    def read_ops(self, from_seq: int, to_seq=None):
        return self._client._doc_read_ops(
            self.document_id, from_seq, to_seq,
            auth=(self.tenant_id, self.token))

    def get_latest_summary(self):
        return self._client._doc_latest_summary(
            self.document_id, auth=(self.tenant_id, self.token))

    def upload_summary(self, summary: dict) -> str:
        # same wire >= 1.1 guard as the single-socket driver: on a
        # 1.0-agreed connection degrade to inline summaries instead
        # of sending frames the server will reject
        if self.agreed_version is not None and \
                wire_version_lt(self.agreed_version, "1.1"):
            raise RuntimeError(
                f"summary upload needs wire >= 1.1; connection "
                f"agreed {self.agreed_version}"
            )
        return self._client._doc_upload_summary(
            self.document_id, summary,
            auth=(self.tenant_id, self.token))

    def close(self) -> None:
        # tell the server to drop this document's connection (leave
        # the quorum — a silently departed client would pin the msn);
        # the shared socket stays up for the other documents
        try:
            self._client._send({
                "type": "disconnect_document",
                "document_id": self.document_id,
            })
        except OSError:
            pass
        self._client._facades.pop(self.document_id, None)


class MultiplexedSocketClient(SocketDocumentService):
    """One physical connection, many documents: frames route to
    per-document facades by document_id."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self._facades: dict[str, _DocumentFacade] = {}
        super().__init__(host, port, document_id="<multiplex>",
                         timeout=timeout)

    def document_service(self, document_id: str,
                         tenant_id: Optional[str] = None,
                         token: Optional[str] = None,
                         mode: str = "write") -> _DocumentFacade:
        facade = self._facades.get(document_id)
        if facade is None:
            facade = _DocumentFacade(
                self, document_id, tenant_id, token, mode)
            self._facades[document_id] = facade
        else:
            # refresh credentials: a caller retrying with a new token
            # must not be stuck with the facade's original (possibly
            # rejected) one
            if token is not None:
                facade.token = token
                facade.tenant_id = tenant_id
            facade.mode = mode
        return facade

    # -- routing hooks --------------------------------------------------

    def _on_connected(self, frame: dict) -> None:
        facade = self._facades.get(frame.get("document_id", ""))
        if facade is not None:
            facade.agreed_version = frame.get("version")
            facade._connected.set()

    def _on_connect_error(self, frame: dict) -> None:
        facade = self._facades.get(frame.get("document_id", ""))
        if facade is not None:
            facade.auth_error = frame.get("message", "rejected")
            facade._connected.set()

    def _on_transport_closed(self) -> None:
        super()._on_transport_closed()
        for facade in list(self._facades.values()):
            facade._connected.set()

    def _deliver(self, frame: dict) -> None:
        doc = frame.get("document_id")
        facade = self._facades.get(doc) if doc is not None else None
        if facade is not None and frame.get("type") in ("op", "nack"):
            # borrow the base parsing by impersonating the facade's
            # handlers for this frame
            self._on_message = facade._on_message
            self._on_nack = facade._on_nack
            try:
                super()._deliver(frame)
            finally:
                self._on_message = None
                self._on_nack = None
            return
        super()._deliver(frame)


class CachingMultiplexFactory:
    """IDocumentServiceFactory with odsp-class behavior: one shared
    socket per server endpoint + snapshot caching on every document
    service."""

    def __init__(self, host: str = "127.0.0.1", port: int = 7070,
                 cache: Optional[SnapshotCache] = None,
                 cache_dir: Optional[str] = None,
                 max_age_s: float = 60.0,
                 tenant_id: Optional[str] = None,
                 token_for: Optional[Callable[[str], str]] = None):
        self.host = host
        self.port = port
        self.max_age_s = max_age_s
        self.tenant_id = tenant_id
        self.token_for = token_for   # document_id -> signed token
        if cache is None:
            cache = FileSnapshotCache(cache_dir) \
                if cache_dir is not None else SnapshotCache()
        self.cache = cache
        self._client: Optional[MultiplexedSocketClient] = None

    def _shared_client(self) -> MultiplexedSocketClient:
        if self._client is None or self._client._closed:
            self._client = MultiplexedSocketClient(self.host, self.port)
        return self._client

    def create_document_service(self, document_id: str
                                ) -> CachingDocumentService:
        token = self.token_for(document_id) if self.token_for else None
        facade = self._shared_client().document_service(
            document_id, tenant_id=self.tenant_id, token=token)
        return CachingDocumentService(
            facade, self.cache, max_age_s=self.max_age_s)

    def close(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None
