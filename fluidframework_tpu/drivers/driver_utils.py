"""Shared driver plumbing: retry, throttling backoff, snapshot
prefetch.

Reference: packages/loader/driver-utils — ``runWithRetry`` (retriable
error loop with backoff + throttling respect), ``prefetchSnapshot``
(warm the snapshot/ops caches before Container.load), and the
compression utilities (op compression already lives in
runtime/op_lifecycle.py).
"""
from __future__ import annotations

import os
import random
import time
from typing import Any, Callable, Optional, TypeVar

T = TypeVar("T")


def default_seed() -> int:
    """The process's jitter seed: ``FFTPU_SEED`` when set (replaying a
    failure), otherwise fresh OS entropy — but always an EXPLICIT,
    recorded value, so a failing jittered-backoff schedule is
    reproducible by re-running with ``FFTPU_SEED=<printed seed>``."""
    env = os.environ.get("FFTPU_SEED")
    if env is not None:
        try:
            # base 0: accepts the decimal form JITTER_SEED prints and
            # pasted hex ("0x1f") alike
            return int(env, 0)
        except ValueError:
            raise ValueError(
                f"FFTPU_SEED must be an integer, got {env!r}"
            ) from None
    return int.from_bytes(os.urandom(4), "big")


#: the seed behind the module RNG; set FFTPU_SEED to pin it. Noted
#: ONCE on stderr at the first module-RNG jitter draw (the moment a
#: schedule starts mattering), so a flaky backoff failure always has
#: the seed in its captured output
JITTER_SEED = default_seed()

# module-level source for callers that don't inject their own
# (``run_with_retry(rng=...)`` overrides per call); seeded from
# JITTER_SEED so the backoff schedule is replayable from its seed
_RNG = random.Random(JITTER_SEED)

_SEED_NOTED = False


def _note_seed_once() -> None:
    global _SEED_NOTED
    if not _SEED_NOTED:
        _SEED_NOTED = True
        import sys

        print(
            f"driver_utils: jitter seed {JITTER_SEED} "
            f"(FFTPU_SEED={JITTER_SEED} replays this process's "
            "backoff schedules)",
            file=sys.stderr,
        )


def derived_seed(index: int) -> int:
    """A per-client seed derived from the recorded process seed:
    distinct streams per client (jitter must decorrelate clients)
    that all replay from the ONE surfaced ``FFTPU_SEED`` given the
    same construction order (the loader's backoff RNG uses this).
    Deriving a stream is the moment a schedule starts mattering, so
    the process seed is noted here too — a throttle-storm flake whose
    only jitter rode derived streams still carries its seed."""
    _note_seed_once()
    return (JITTER_SEED << 20) ^ index


class RetriableError(Exception):
    """An error the driver layer may retry (canRetry=true errors).
    ``retry_after_seconds`` mirrors service throttling responses."""

    def __init__(self, message: str = "",
                 retry_after_seconds: Optional[float] = None):
        super().__init__(message)
        self.retry_after_seconds = retry_after_seconds


def full_jitter_delay(attempt: int, *,
                      base_delay_s: float = 0.05,
                      max_delay_s: float = 5.0,
                      floor_s: float = 0.0,
                      rng: Optional[random.Random] = None) -> float:
    """AWS-style FULL-JITTER backoff: uniform in [0, min(cap,
    base*2^(attempt-1))], on TOP of ``floor_s``.

    ``floor_s`` carries a service throttle's ``retry_after_seconds``
    and is a FLOOR, never reduced: the service computed when capacity
    returns, and coming back earlier just re-sheds. The jitter rides
    ABOVE it because a deterministic schedule synchronizes every
    client the service throttled in the same window — they would all
    return at floor+base, floor+2*base, ... in lockstep, re-creating
    the spike the throttle shed (the thundering herd)."""
    span = min(max_delay_s, base_delay_s * (2 ** max(0, attempt - 1)))
    if rng is None:
        _note_seed_once()
    return max(0.0, floor_s) + (rng or _RNG).uniform(0.0, span)


def run_with_retry(fn: Callable[[], T], *,
                   max_retries: int = 5,
                   base_delay_s: float = 0.05,
                   max_delay_s: float = 5.0,
                   retriable=(RetriableError, ConnectionError,
                              TimeoutError),
                   sleep: Callable[[float], None] = time.sleep,
                   on_retry: Optional[Callable[[int, Exception], None]]
                   = None,
                   rng: Optional[random.Random] = None) -> T:
    """driver-utils runWithRetry: call ``fn`` until it succeeds or a
    non-retriable error/exhaustion; full-jitter exponential backoff
    (:func:`full_jitter_delay`) with a throttler's
    ``retry_after_seconds`` as the floor.

    ``rng=None`` (the default) draws jitter from the module RNG,
    which is seeded with :data:`JITTER_SEED` (``FFTPU_SEED`` when
    set): the whole process's backoff schedule replays from one
    recorded seed. Pass a dedicated seeded ``random.Random`` to pin
    one caller's schedule independently of everything else drawing
    from the shared stream."""
    attempt = 0
    while True:
        try:
            return fn()
        except retriable as e:  # noqa: PERF203 - retry loop
            attempt += 1
            if attempt > max_retries:
                raise
            hinted = getattr(e, "retry_after_seconds", None)
            delay = full_jitter_delay(
                attempt, base_delay_s=base_delay_s,
                max_delay_s=max_delay_s,
                floor_s=hinted if hinted is not None else 0.0,
                rng=rng,
            )
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(delay)


class PrefetchingDocumentService:
    """prefetchSnapshot: wraps any DocumentService, fetching the
    latest summary and trailing ops ONCE (optionally ahead of time)
    and serving Container.load's storage reads from the cache — the
    reference uses this to overlap snapshot fetch with boot."""

    def __init__(self, inner):
        self._inner = inner
        self.document_id = inner.document_id
        self._summary: Any = None
        self._ops: Optional[list] = None
        self._base = 0

    def prefetch(self) -> "PrefetchingDocumentService":
        self._summary = self._inner.get_latest_summary()
        if self._summary is not None:
            # the load path replays from the snapshot's PROTOCOL
            # position (the summarize op itself sequences after the
            # snapshotted state), so the cache must start there, not
            # at the summary version's seq
            seq, tree = self._summary
            base = (tree.get("protocol") or {}).get(
                "sequenceNumber", seq
            )
        else:
            base = 0
        self._base = base
        self._ops = self._inner.read_ops(base)
        return self

    # -- DocumentService surface ---------------------------------------

    def get_latest_summary(self):
        if self._ops is None:
            self.prefetch()
        return self._summary

    def read_ops(self, from_seq: int, to_seq=None):
        if self._ops is None:
            self.prefetch()
        base = self._base
        covered_to = (self._ops[-1].sequence_number
                      if self._ops else base)
        if from_seq < base:
            # below the prefetched window: the cache cannot answer
            # (it starts at base+1) — delegate to the live service
            return self._inner.read_ops(from_seq, to_seq)
        if from_seq < covered_to:
            # inside the prefetched view: serve the cached consistent
            # snapshot (a load against it sees exactly prefetch-time
            # state; newer ops arrive via connect()'s catch-up below)
            return [m for m in self._ops
                    if m.sequence_number > from_seq
                    and (to_seq is None
                         or m.sequence_number <= to_seq)]
        # past the prefetched range: live service
        return self._inner.read_ops(from_seq, to_seq)

    def connect_to_delta_stream(self, client_id, on_message,
                                on_nack=None):
        return self._inner.connect_to_delta_stream(
            client_id, on_message, on_nack
        )

    def close(self) -> None:
        close = getattr(self._inner, "close", None)
        if close is not None:
            close()


class RetryDocumentService:
    """Wraps a DocumentService so its storage reads run under
    runWithRetry (transient socket drops / throttling survive)."""

    def __init__(self, inner, **retry_kwargs):
        self._inner = inner
        self._kw = retry_kwargs
        self.document_id = inner.document_id

    def get_latest_summary(self):
        return run_with_retry(self._inner.get_latest_summary,
                              **self._kw)

    def read_ops(self, from_seq: int, to_seq=None):
        return run_with_retry(
            lambda: self._inner.read_ops(from_seq, to_seq), **self._kw
        )

    def connect_to_delta_stream(self, client_id, on_message,
                                on_nack=None):
        return run_with_retry(
            lambda: self._inner.connect_to_delta_stream(
                client_id, on_message, on_nack
            ),
            **self._kw,
        )

    def close(self) -> None:
        close = getattr(self._inner, "close", None)
        if close is not None:
            close()
