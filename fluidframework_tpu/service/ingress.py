"""Networked service ingress — the alfred-equivalent front door.

Reference: the alfred socket handler
(server/routerlicious/packages/lambdas/src/alfred/index.ts —
``connect_document`` :465, ``submitOp`` :500) fronting the per-document
orderer, and the client-side socket protocol
(packages/drivers/driver-base/src/documentDeltaConnection.ts:41).

Transport: length-prefixed JSON frames (4-byte big-endian length +
UTF-8 JSON body) over TCP via asyncio — the protocol EVENTS mirror the
reference's socket.io vocabulary; the framing is deliberately minimal
(no third-party websocket dependency in this image). Events:

  client -> server
    {"type": "connect_document", "document_id", "client_id",
     "details"?}                     -> "connected"
    {"type": "submitOp", "document_id", "op": {<DocumentMessage>}}
    {"type": "read_ops", "rid", "document_id", "from_seq", "to_seq"?}
                                     -> "ops"
    {"type": "fetch_summary", "rid", "document_id"} -> "summary"
    {"type": "disconnect_document", "document_id"}

  server -> client
    {"type": "connected", "document_id", "client_id"}
    {"type": "op", "document_id", "msg": {<SequencedMessage>}}
    {"type": "nack", "document_id", ...}
    {"type": "ops", "rid", "msgs": [...]}
    {"type": "summary", "rid", "sequence_number", "summary"} | null
    {"type": "error", "message"}

All orderer work runs on the event loop thread (the deli ticket path is
synchronous and fast — the C++ batch lane exists for bulk replay);
per-connection outbound frames go through a queue drained by a writer
task, so a slow client never blocks sequencing (broadcaster batching,
lambdas/src/broadcaster/lambda.ts:49).
"""
from __future__ import annotations

import asyncio
import dataclasses
import itertools
import json
import struct
import sys
import time
from typing import Any, Optional

from ..obs import metrics as obs_metrics
from ..obs.trace import stamp as trace_stamp
from ..protocol.columnar import decode_columns, validate_columns
from ..protocol.constants import wire_version_lt
from ..qos import CLASS_CATCHUP, CLASS_SUMMARY, CLASS_WRITE
from ..qos.faults import KIND_ERROR, PLANE as _CHAOS
from ..protocol.messages import (
    ClientDetail,
    DocumentMessage,
    MessageType,
    Nack,
    NackErrorType,
    SequencedMessage,
)
from ..protocol.serialization import (
    decode_contents,
    encode_contents,
    message_from_json,
    message_to_json,
)
from .local_server import DeltaConnection, LocalServer

_LEN = struct.Struct(">I")
MAX_FRAME = 64 * 1024 * 1024

# frame-kind label values are drawn from the FIXED protocol vocabulary
# below, never from client input (bounded cardinality by construction;
# anything else counts as "unknown")
_KNOWN_FRAME_KINDS = frozenset((
    "connect_document", "submitOp", "read_ops", "fetch_summary",
    "upload_summary_chunk", "disconnect_document", "metrics", "slo",
    "fleet-metrics", "heat",
))
_FRAMES = obs_metrics.REGISTRY.counter(
    "ingress_frames_total", "frames dispatched by the ingress",
    labelnames=("kind",))
_OPS_IN = obs_metrics.REGISTRY.counter(
    "ingress_ops_received_total", "raw client ops decoded (incl. "
    "boxcar members)")
_OPS_OFFERED = obs_metrics.REGISTRY.counter(
    "ingress_ops_offered_total",
    "client ops offered to the ingress, shed ones included — the "
    "denominator of the default goodput SLO")
_OPS_TICKETED = obs_metrics.REGISTRY.counter(
    "ingress_ops_ticketed_total",
    "offered ops that actually reached the sequencer — the goodput "
    "SLO's numerator (decoded-but-nacked ops must not count as "
    "served)")
_BOXCARS = obs_metrics.REGISTRY.counter(
    "ingress_boxcars_total", "wire-1.2 boxcarred batch submits")
_COLUMNAR = obs_metrics.REGISTRY.counter(
    "ingress_columnar_batches_total",
    "wire-1.3 columnar SoA batch submits (validated once, sliced)")
_NACKS_OUT = obs_metrics.REGISTRY.counter(
    "ingress_nacks_sent_total", "nack frames sent to clients")
_ERRORS_OUT = obs_metrics.REGISTRY.counter(
    "ingress_errors_sent_total", "error frames sent to clients")
_THROTTLE_NACKS = obs_metrics.REGISTRY.counter(
    "ingress_throttle_nacks_total",
    "frames refused by the qos admission gate", labelnames=("klass",))
_OUT_DROPPED = obs_metrics.REGISTRY.counter(
    "ingress_outbound_dropped_total",
    "sequenced-op fanout frames dropped to slow consumers")
_SLOW_DISCONNECTS = obs_metrics.REGISTRY.counter(
    "ingress_slow_consumer_disconnects_total",
    "sessions disconnected past the hard outbound limit")
_OUT_DEPTH = obs_metrics.REGISTRY.gauge(
    "ingress_outbound_depth_max",
    "deepest per-session outbound queue at last sample")
_DISPATCH_MS = obs_metrics.REGISTRY.histogram(
    "ingress_dispatch_ms",
    "event-loop occupancy per dispatched frame (decode + ticket + "
    "fanout enqueue)")

# per-tenant usage rollup (the cost-attribution plane, obs/heat.py).
# AGGREGATE families only — tenant ids are unbounded client input and
# never become label values (the obs cardinality discipline); exact
# per-tenant splits live on the usage HeatLedger, LRU-capped, served
# via the heat frame / --dump-heat.
_TENANT_OPS_OFFERED = obs_metrics.REGISTRY.counter(
    "tenant_ops_offered_total",
    "ops offered by sessions with a tenant identity (connect-token "
    "claims), shed ones included")
_TENANT_OPS_TICKETED = obs_metrics.REGISTRY.counter(
    "tenant_ops_ticketed_total",
    "tenant-attributed ops that reached the sequencer")
_TENANT_BYTES_IN = obs_metrics.REGISTRY.counter(
    "tenant_bytes_in_total",
    "wire bytes received on frames attributed to a tenant")
_TENANT_BYTES_OUT = obs_metrics.REGISTRY.counter(
    "tenant_bytes_out_total",
    "wire bytes enqueued outbound on tenant-attributed fanout")
_TENANT_SHEDS = obs_metrics.REGISTRY.counter(
    "tenant_sheds_total",
    "qos admission sheds charged to a tenant")
_TENANT_UPLOADS = obs_metrics.REGISTRY.counter(
    "tenant_summary_uploads_total",
    "completed summary uploads charged to a tenant")

# chaos seam (docs/ROBUSTNESS.md): a transient fault on the summary
# upload plane — fired on the FINAL (rid-waited) chunk so it always
# reaches the uploader synchronously; the container's summarize
# fallback degrades to the inline-summary path, which is the recovery
# this seam exists to keep exercised
_SITE_UPLOAD = _CHAOS.site("ingress.summary_upload", (KIND_ERROR,))

# Wire-protocol versions this server speaks (newest first). The
# reference negotiates `versions` on connect_document
# (documentDeltaConnection.ts protocolVersions / alfred's
# connect_document): the client offers what it speaks, the server
# picks the newest shared one and echoes it in "connected"; no overlap
# is a connect error, not a silent mismatch. Snapshot formats are
# versioned separately (testing/compat.py); this covers the FRAMES.
#
# 1.0 — base frames: connect/op/nack/read_ops/summary/summarize.
# 1.1 — adds the chunked summary-upload plane (upload_summary_chunk)
#       and structured error kinds. A connection that NEGOTIATED 1.0
#       must not use 1.1 frames (server rejects them; the driver
#       degrades to inline summaries — the old-client/new-service
#       pairing of the compat matrix, tests/test_wire_compat.py).
# 1.2 — adds the boxcarred batch submit: one submitOp frame may carry
#       "ops": [<DocumentMessage>...] and the whole array tickets
#       atomically on the event loop, so a runtime batch can never be
#       interleaved with another session's ops in the sequenced order
#       (the submit->ack liveness fix — see SocketDeltaConnection).
# 1.3 — adds the columnar SoA batch submit: one submitOp frame may
#       carry "cols": {parallel arrays + shared payload string}
#       (protocol/columnar.py) — validated once, sliced, never
#       re-interpreted per op. Same atomic-ticket semantics as the
#       1.2 boxcar; 1.0-1.2 peers keep the row paths unchanged.
# 1.4 — adds the heat frame (cost-attribution plane, obs/heat.py):
#       top-k hot documents and tenants off the heat/usage ledgers,
#       with an optional requested cut "k". A connection that
#       NEGOTIATED <= 1.3 must not send it (server rejects loudly,
#       same as the 1.1 upload gate); 1.0-1.3 peers see no heat
#       frames and no behavior change.
# 1.5 — registers the sharedtree channel-op payload ("msg:tree",
#       protocol/tree_payload.py, the tree serving plane). Pure
#       vocabulary: the payload rode opaque envelope contents
#       before, so no frame changes, no gate, and no byte changes
#       for any peer — 1.5 puts its fields under the wirecheck /
#       wiresan / golden-snapshot review regime.
WIRE_VERSIONS = ("1.5", "1.4", "1.3", "1.2", "1.1", "1.0")


def document_message_to_json(op: DocumentMessage) -> dict:
    return {
        "client_sequence_number": op.client_sequence_number,
        "reference_sequence_number": op.reference_sequence_number,
        "type": int(op.type),
        "contents": encode_contents(op.contents),
        "metadata": op.metadata,
        "traces": [dataclasses.asdict(t) for t in op.traces],
    }


def document_message_from_json(data: dict) -> DocumentMessage:
    from ..protocol.messages import Trace

    return DocumentMessage(
        client_sequence_number=data["client_sequence_number"],
        reference_sequence_number=data["reference_sequence_number"],
        type=MessageType(data["type"]),
        contents=decode_contents(data.get("contents")),
        metadata=data.get("metadata"),
        traces=[Trace(**t) for t in data.get("traces", [])],
    )


def nack_to_json(nack: Nack) -> dict:
    out = {
        "sequence_number": nack.sequence_number,
        "error_type": int(nack.error_type),
        "message": nack.message,
        "operation": document_message_to_json(nack.operation)
        if nack.operation is not None else None,
    }
    # retry_after_seconds and the qos shed attribution are OPTIONAL
    # on the wire: emitted only when set, so non-throttle nack frames
    # stay byte-identical to the 1.0 shape and older peers never see
    # keys they don't know (test_wire_compat)
    if nack.retry_after_seconds is not None:
        out["retry_after_seconds"] = nack.retry_after_seconds
    if nack.pressure_tier is not None:
        out["pressure_tier"] = nack.pressure_tier
    if nack.shed_class is not None:
        out["shed_class"] = nack.shed_class
    return out


async def read_frame_sized(reader: asyncio.StreamReader
                           ) -> tuple[Optional[dict], int]:
    """(frame, wire bytes) — the server's read path keeps the exact
    frame size so the qos byte budgets charge what the wire carried,
    not a re-serialization estimate."""
    try:
        header = await reader.readexactly(_LEN.size)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None, 0
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    try:
        body = await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None, 0
    return json.loads(body.decode("utf-8")), length


async def read_frame(reader: asyncio.StreamReader) -> Optional[dict]:
    frame, _ = await read_frame_sized(reader)
    return frame


def recv_frame_blocking(sock) -> dict:
    """Read one frame from a BLOCKING socket — the sync-side twin of
    ``read_frame`` (one definition of the wire framing for clients
    without an event loop, e.g. the broker's request/response
    client). Enforces the same MAX_FRAME bound: a corrupt/desynced
    length prefix must fail fast, not allocate gigabytes."""
    buf = b""
    while len(buf) < _LEN.size:
        chunk = sock.recv(_LEN.size - len(buf))
        if not chunk:
            raise ConnectionError("connection closed")
        buf += chunk
    (length,) = _LEN.unpack(buf)
    if length > MAX_FRAME:
        raise ValueError(f"frame length {length} exceeds {MAX_FRAME}")
    body = b""
    while len(body) < length:
        chunk = sock.recv(length - len(body))
        if not chunk:
            raise ConnectionError("connection closed mid-frame")
        body += chunk
    return json.loads(body.decode("utf-8"))


def pack_frame(data: dict) -> bytes:
    body = json.dumps(data).encode("utf-8")
    return _LEN.pack(len(body)) + body


class _ClientSession:
    """One TCP connection; may hold delta connections to several
    documents (the reference multiplexes the same way per socket)."""

    def __init__(self, server: "AlfredServer",
                 writer: Optional[asyncio.StreamWriter]):
        self.server = server
        self.writer = writer
        self.session_id = f"sess-{next(server._session_counter)}"
        # BOUNDED (maxsize = the hard slow-consumer limit): an
        # undrained reader must cost a bounded number of buffered
        # frames, never the server's memory. The drop/nack/disconnect
        # policy lives in send() below.
        self.outbound: asyncio.Queue[Optional[bytes]] = asyncio.Queue(
            maxsize=server.max_outbound_depth
        )
        self.closed = False
        # slow-consumer state: once the soft threshold is crossed,
        # sequenced-op fanout frames DROP (the client's own gap
        # refetch recovers them from delta storage) until the queue
        # drains to half the threshold — hysteresis, so the
        # drop-enter nack doesn't flap per frame
        self.dropping = False
        self.dropped_ops = 0
        self.connections: dict[str, DeltaConnection] = {}
        # doc -> tenant_id seen at connect (qos bucket scope key)
        self.tenant_ids: dict[str, str] = {}
        # documents this session has passed the token gate for (a
        # disconnect keeps the authorization; the token was validated)
        self.authorized: set[str] = set()
        # documents write-authorized (write-mode connect or doc:write
        # token) — the summary-upload plane requires write scope
        self.write_authorized: set[str] = set()
        # in-flight chunked summary uploads: upload_id -> state
        self.uploads: dict[str, dict] = {}
        # doc -> wire version agreed at connect_document (absent =
        # never negotiated on this session)
        self.wire_versions: dict[str, str] = {}

    def send(self, data: dict) -> None:
        """Enqueue one outbound frame under the slow-consumer policy:

        - sequenced-op fanout ("op") past the soft threshold DROPS
          (with ONE throttle nack on entering the dropping state, so
          the driver backs off); the client's inbound gap detection
          refetches dropped ops from delta storage — fanout frames
          are a delivery optimization, the op log is the truth;
        - anything still overflowing the hard maxsize (request
          replies, nacks — the session is hopeless by then) closes
          the connection LOUDLY. A reader that never drains costs a
          bounded queue, a counter and a disconnect; never the
          server's memory.
        """
        if self.closed:
            return
        if data.get("type") == "op":
            depth = self.outbound.qsize()
            soft = self.server.outbound_drop_threshold
            if self.dropping and depth <= soft // 2:
                self.dropping = False
            if self.dropping or depth >= soft:
                entered = not self.dropping
                self.dropping = True
                self.dropped_ops += 1
                _OUT_DROPPED.inc()
                if entered:
                    _NACKS_OUT.inc()
                    self._put(pack_frame({
                        "type": "nack",
                        "document_id": data.get("document_id"),
                        "operation": None,
                        "sequence_number": 0,
                        "error_type": int(NackErrorType.THROTTLING),
                        "message": (
                            "slow consumer: outbound queue at "
                            f"{depth} frames; dropping sequenced-op "
                            "fanout (refetch via read_ops)"
                        ),
                        "retry_after_seconds": 1.0,
                    }))
                return
        payload = pack_frame(data)
        if self.server.usage is not None:
            # per-tenant egress bytes: fanout and replies for a
            # tenant-attributed document charge the frame's packed
            # size (the same bytes the socket writes)
            d = data.get("document_id")
            tenant = self.tenant_ids.get(d, "") if d else ""
            if tenant:
                self.server.usage.charge(
                    tenant, 0.0, bytes_out=len(payload))
                _TENANT_BYTES_OUT.inc(len(payload))
        self._put(payload)

    def _put(self, frame: bytes) -> None:
        try:
            self.outbound.put_nowait(frame)
        except asyncio.QueueFull:
            # hard limit: the consumer has not drained ANYTHING for
            # maxsize frames — disconnect loudly (the counter + stderr
            # line are the "loud"; reconnect is the client's recovery)
            _SLOW_DISCONNECTS.inc()
            print(
                f"ingress[{self.session_id}]: outbound queue hit the "
                f"hard limit ({self.server.max_outbound_depth}); "
                "disconnecting slow consumer",
                file=sys.stderr,
            )
            self.close()

    async def writer_loop(self) -> None:
        while True:
            frame = await self.outbound.get()
            if frame is None:
                break
            try:
                self.writer.write(frame)
                await self.writer.drain()
            except (ConnectionResetError, BrokenPipeError, OSError):
                break

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        for conn in self.connections.values():
            conn.disconnect()
        self.connections.clear()
        try:
            self.outbound.put_nowait(None)
        except asyncio.QueueFull:
            # full of undelivered frames: displace one so the writer
            # pump still sees its shutdown sentinel
            self.outbound.get_nowait()
            self.outbound.put_nowait(None)
        if self.writer is not None:
            # actively tear the transport down: a hard-limit close
            # must unblock the read loop too, not wait for the peer
            try:
                self.writer.close()
            except (OSError, RuntimeError):
                pass


class AlfredServer:
    """asyncio ingress over a LocalServer (per-document LocalOrderer
    pipeline — deli/scriptorium/broadcaster/scribe equivalents)."""

    # slow-consumer bounds (frames). Soft: sequenced-op fanout starts
    # dropping (gap refetch recovers). Hard: the session disconnects.
    MAX_OUTBOUND_DEPTH = 8192
    OUTBOUND_DROP_THRESHOLD = 6144
    # normalizing capacity for the sequencer-inbox pressure source
    SEQUENCER_INBOX_CAPACITY = 1024

    def __init__(self, local: Optional[LocalServer] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 tenants: Optional[Any] = None,
                 qos: Optional[Any] = None,
                 slo: Optional[Any] = None,
                 fleet: Optional[Any] = None,
                 max_outbound_depth: Optional[int] = None,
                 outbound_drop_threshold: Optional[int] = None,
                 heat: Optional[Any] = None,
                 usage: Optional[Any] = None,
                 heat_top_k: int = 10):
        self.local = local or LocalServer()
        self.host = host
        self.port = port
        # optional riddler-analogue TenantManager (service/tenancy.py):
        # when set, connect_document must carry tenant_id + a valid
        # signed claims token (alfred's verifyToken gate)
        self.tenants = tenants
        # optional qos.AdmissionController: consulted BEFORE anything
        # reaches the sequencer (submitOp), the storage planes
        # (read_ops/fetch_summary) or the upload plane. None = the
        # open dev-service shape, like tenants=None.
        self.qos = qos
        # optional obs.SloEngine: answers the `slo` frame and
        # piggybacks its sampling tick on the dispatch path (the
        # engine is passive — it only reads registry families the
        # serving modules already bump). None = no objectives.
        self.slo = slo
        # optional obs.FederatedView: answers the `fleet-metrics`
        # frame with the MERGED leader/follower/partition-worker
        # registries. None = a single-node view over the process
        # registry, built lazily on first ask (the dev-service shape:
        # one process IS the fleet).
        self.fleet = fleet
        # optional cost-attribution plane (obs/heat.py): `heat` is the
        # per-document device-time ledger (the sidecar charges it at
        # its settle boundary), `usage` the per-tenant rollup ledger
        # this ingress charges at admission/ticket/upload time. Both
        # None = attribution off, zero cost on the serving path. The
        # wire-1.4 heat frame serves top-k cuts of both.
        self.heat = heat
        self.usage = usage
        self.heat_top_k = heat_top_k
        # doc -> tenant identity from the last validated connect (the
        # sidecar's tenant_of hook reads this; per-session identity
        # for the rollup stays on session.tenant_ids)
        self.doc_tenants: dict[str, str] = {}
        self.max_outbound_depth = (
            max_outbound_depth or self.MAX_OUTBOUND_DEPTH
        )
        self.outbound_drop_threshold = min(
            outbound_drop_threshold or self.OUTBOUND_DROP_THRESHOLD,
            self.max_outbound_depth,
        )
        self._session_counter = itertools.count()
        self._sessions: set[_ClientSession] = set()
        self._handler_tasks: set[asyncio.Task] = set()
        self._server: Optional[asyncio.base_events.Server] = None
        if qos is not None and getattr(qos, "pressure", None) \
                is not None:
            self._register_pressure_sources(qos.pressure)

    def _register_pressure_sources(self, pressure) -> None:
        """Default composite-pressure wiring: the depths THIS process
        can observe. ensure_source so operator/test-supplied sources
        (e.g. a sidecar's queued_ops, a broker's fanout lag) are
        never clobbered."""
        # normalized against the HARD limit: the drop policy parks a
        # persistently-slow consumer's queue at the soft threshold,
        # which lands the ratio at soft/hard (elevated/severe by
        # default) — sheds bulk traffic without starving writers;
        # only a genuinely stalled event loop reaches critical
        pressure.ensure_source(
            "session_outbound", self._max_outbound_depth_now,
            capacity=self.max_outbound_depth,
        )
        pressure.ensure_source(
            "sequencer_inbox",
            lambda: max(
                (o.inbox_depth
                 for o in getattr(self.local, "documents", {})
                 .values()),
                default=0,
            ),
            capacity=self.SEQUENCER_INBOX_CAPACITY,
        )
        # only LOCAL lag probes may sit on the serving path: the
        # pressure monitor samples inside admit() on the event loop,
        # and a RemoteOrderingQueue.fanout_lag is a blocking TCP
        # round trip — a hung broker would turn the admission gate
        # into the stall it exists to prevent. Remote lag belongs in
        # an off-loop sampler feeding add_source with a cached value.
        queue = getattr(self.local, "queue", None)
        if queue is not None and getattr(
                queue, "fanout_lag_is_local", False):
            pressure.ensure_source(
                "broker_fanout", queue.fanout_lag,
                capacity=self.SEQUENCER_INBOX_CAPACITY,
            )

    def _max_outbound_depth_now(self) -> int:
        depth = max(
            (s.outbound.qsize() for s in self._sessions), default=0
        )
        _OUT_DEPTH.set(depth)
        return depth

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def serve_forever(self) -> None:
        if self._server is None:
            await self.start()
        async with self._server:
            await self._server.serve_forever()

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        # wait_closed() only covers the listening socket on 3.10:
        # actively tear down live sessions (EOFs their read loops)
        # and wait for the handler tasks, so a loop shutdown right
        # after stop() can't strand half-torn-down pump coroutines
        for session in sorted(self._sessions,
                              key=lambda s: s.session_id):
            session.close()
        if self._handler_tasks:
            await asyncio.gather(
                *self._handler_tasks, return_exceptions=True)

    # ------------------------------------------------------------------

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._handler_tasks.add(task)
        session = _ClientSession(self, writer)
        self._sessions.add(session)
        pump = asyncio.ensure_future(session.writer_loop())
        try:
            while True:
                frame, nbytes = await read_frame_sized(reader)
                if frame is None or session.closed:
                    break
                try:
                    self._dispatch(session, frame, nbytes)
                except Exception as e:  # noqa: BLE001 - report, keep serving
                    _ERRORS_OUT.inc()
                    session.send({
                        "type": "error",
                        "rid": frame.get("rid"),
                        # structured kind: drivers must distinguish an
                        # auth rejection from a transport/server fault
                        # (a caching driver would otherwise serve a
                        # revoked client stale snapshots as "offline")
                        "error_kind": "permission"
                        if isinstance(e, PermissionError) else "server",
                        "message": f"{type(e).__name__}: {e}",
                    })
        finally:
            self._sessions.discard(session)
            session.close()
            await pump
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass
            if task is not None:
                self._handler_tasks.discard(task)

    def _check_read_access(self, session: _ClientSession,
                           doc: str, frame: dict) -> None:
        """When tokens are enforced, the storage planes (read_ops /
        fetch_summary) require either a prior successful
        connect_document for the document OR a doc:read token on the
        request itself (the loader fetches snapshot + trailing ops
        BEFORE joining the delta stream — container.ts load order) —
        otherwise an unauthenticated socket could read any document's
        full op log with no credentials."""
        if self.tenants is None or doc in session.authorized:
            return
        from .tenancy import SCOPE_READ, AuthError

        try:
            self.tenants.validate_token(
                frame.get("token", ""), frame.get("tenant_id", ""),
                doc, required_scope=SCOPE_READ,
            )
        except AuthError as e:
            raise PermissionError(
                f"not authorized for document {doc!r}: {e} "
                "(connect_document first, or send a doc:read token "
                "with the request)"
            ) from e
        session.authorized.add(doc)

    def _check_write_access(self, session: _ClientSession,
                            doc: str, frame: dict) -> None:
        """The summary-upload plane mutates storage: write scope
        required (historian gates its summary routes the same way)."""
        if self.tenants is None or doc in session.write_authorized:
            return
        from .tenancy import SCOPE_WRITE, AuthError

        try:
            self.tenants.validate_token(
                frame.get("token", ""), frame.get("tenant_id", ""),
                doc, required_scope=SCOPE_WRITE,
            )
        except AuthError as e:
            raise PermissionError(
                f"no write access to document {doc!r}: {e}"
            ) from e
        session.write_authorized.add(doc)

    def _send_nack(self, session: _ClientSession, doc: str,
                   nack: Nack) -> None:
        _NACKS_OUT.inc()
        session.send({
            "type": "nack", "document_id": doc, **nack_to_json(nack),
        })

    # -- qos admission gate --------------------------------------------

    def _admit(self, session: _ClientSession, klass: str, doc: str,
               frame: dict, ops: int = 1, nbytes: int = 0):
        """Consult the admission controller (None when qos is off).
        Returns the Admission, or None for 'admitted' fast-path."""
        if self.qos is None:
            return None
        # tenant scope key: ONLY the connect-validated identity — a
        # frame-supplied tenant_id is attacker-controlled (it would
        # let one client charge a victim tenant's budget, or rotate
        # fresh ids for an untouched bucket per frame). Pre-connect
        # storage requests fall to the anonymous "" tenant and their
        # per-connection budget.
        adm = self.qos.admit(
            klass,
            tenant=session.tenant_ids.get(doc or "", ""),
            document=doc or "",
            connection=session.session_id,
            ops=ops, nbytes=nbytes,
        )
        if adm.admitted:
            return None
        _THROTTLE_NACKS.labels(klass=klass).inc()
        return adm

    def _send_shed(self, session: _ClientSession, doc: str,
                   frame: dict, adm, as_nack: bool) -> None:
        """Tell the caller it was shed. Op-plane sheds go out as
        throttle NACKs (the driver's on_nack path — the container
        defers resubmit by retry_after_seconds); request/response
        sheds answer the rid with a structured throttle error the
        driver converts to a RetriableError."""
        if self.usage is not None:
            tenant = session.tenant_ids.get(doc or "", "")
            if tenant:
                self.usage.charge(tenant, 0.0, sheds=1)
                _TENANT_SHEDS.inc()
        if as_nack:
            self._send_nack(session, doc, Nack(
                operation=None,
                sequence_number=0,
                error_type=NackErrorType.THROTTLING,
                message=(
                    f"admission refused ({adm.reason}): retry after "
                    f"{adm.retry_after_seconds:.3f}s"
                ),
                retry_after_seconds=adm.retry_after_seconds,
                pressure_tier=adm.tier,
                shed_class=adm.shed_class,
            ))
            return
        _ERRORS_OUT.inc()
        out = {
            "type": "error",
            "rid": frame.get("rid"),
            "error_kind": "throttle",
            "message": (
                f"throttled ({adm.reason}): retry after "
                f"{adm.retry_after_seconds:.3f}s"
            ),
        }
        # optional-presence wire fields: a throttle error omits the
        # retry hint / shed attribution it has nothing to say about,
        # same discipline as nack_to_json
        if adm.retry_after_seconds is not None:
            out["retry_after_seconds"] = adm.retry_after_seconds
        if adm.tier is not None:
            out["pressure_tier"] = adm.tier
        if adm.shed_class is not None:
            out["shed_class"] = adm.shed_class
        session.send(out)

    def _dispatch(self, session: _ClientSession, frame: dict,
                  nbytes: int = 0) -> None:
        """Timing shell around the frame switch: every dispatched
        frame's event-loop occupancy lands in ``ingress_dispatch_ms``
        (the latency the default SLO binds to), and the SLO engine's
        rate-limited sampling tick rides the same path — a serving
        process needs no extra timer thread to keep its burn-rate
        windows populated."""
        t0 = time.perf_counter()
        try:
            self._dispatch_frame(session, frame, nbytes)
        finally:
            _DISPATCH_MS.observe((time.perf_counter() - t0) * 1000.0)
            if self.slo is not None:
                self.slo.maybe_tick()

    def _dispatch_frame(self, session: _ClientSession, frame: dict,
                        nbytes: int = 0) -> None:
        kind = frame.get("type")
        doc = frame.get("document_id")
        _FRAMES.labels(
            kind=kind if kind in _KNOWN_FRAME_KINDS else "unknown"
        ).inc()
        if kind == "metrics":
            # the /metrics-equivalent plane: the process-wide registry
            # in both expositions (`python -m fluidframework_tpu.
            # service --dump-metrics` and ops tooling read this).
            # Unauthenticated by design, like the reference's scraped
            # metrics ports: names/labels never carry tenant content.
            session.send({
                "type": "metrics", "rid": frame.get("rid"),
                "text": obs_metrics.REGISTRY.render_prometheus(),
                "metrics": obs_metrics.REGISTRY.snapshot(),
            })
            return
        if kind == "fleet-metrics":
            # the fleet half of the `metrics` plane: the federated
            # view re-merged as fresh as the ask (`--dump-fleet`
            # reads this). Unauthenticated like `metrics` — merged
            # names/labels never carry tenant content, and node ids
            # are code-chosen.
            if self.fleet is None:
                from ..obs.federation import FederatedView

                self.fleet = FederatedView()
                self.fleet.add_registry(
                    obs_metrics.REGISTRY.node, obs_metrics.REGISTRY)
            merged = self.fleet.refresh()
            session.send({
                "type": "fleet-metrics", "rid": frame.get("rid"),
                "nodes": self.fleet.nodes(),
                "text": self.fleet.registry.render_prometheus(),
                "metrics": merged,
            })
            return
        if kind == "slo":
            # the SLO plane's scrape point: tick + evaluate, so the
            # report is as fresh as the ask (`--dump-slo` reads this).
            # Unauthenticated like `metrics` — verdicts carry metric
            # names and burn rates, never tenant content.
            if self.slo is None:
                session.send({
                    "type": "slo", "rid": frame.get("rid"),
                    "report": None,
                    "message": "slo engine not enabled "
                               "(start the service with --slo)",
                })
                return
            session.send({
                "type": "slo", "rid": frame.get("rid"),
                "report": self.slo.report(),
            })
            return
        if kind == "heat":
            # the cost-attribution plane's scrape point (wire 1.4,
            # `--dump-heat` reads this): top-k hot documents (by
            # attributed device-ms) and tenants off the ledgers.
            # Unauthenticated on a dump connection like `metrics` —
            # but a session that DID negotiate is held to the compat
            # matrix: agreeing only pre-1.4 versions and sending the
            # frame anyway is a protocol error, same discipline as
            # the 1.1 upload gate.
            if session.wire_versions and all(
                    wire_version_lt(v, "1.4")
                    for v in session.wire_versions.values()):
                raise ValueError(
                    "heat frame requires wire version >= 1.4 "
                    "(connection agreed "
                    f"{sorted(set(session.wire_versions.values()))})"
                )
            k = frame.get("k")
            cut = int(k) if k is not None else self.heat_top_k
            docs = (self.heat.top_k(cut)
                    if self.heat is not None else [])
            tenants = (self.usage.top_k(cut)
                       if self.usage is not None else [])
            session.send({
                "type": "heat", "rid": frame.get("rid"),
                "docs": [[key, value] for key, value in docs],
                "tenants": [[key, value] for key, value in tenants],
            })
            return
        if self.usage is not None and doc:
            # per-tenant byte ingress: every frame of a
            # tenant-attributed document charges its wire bytes to
            # the CONNECT-VALIDATED tenant (never the frame's own
            # tenant_id — that field is client input)
            tenant = session.tenant_ids.get(doc, "")
            if tenant and nbytes:
                self.usage.charge(tenant, 0.0, bytes_in=nbytes)
                _TENANT_BYTES_IN.inc(nbytes)
        if kind == "connect_document":
            client_id = frame["client_id"]
            details = frame.get("details") or {}
            # wire-version negotiation: pick the newest shared version
            # (clients predating the field implicitly offer 1.0)
            offered = frame.get("versions") or ["1.0"]
            agreed = next(
                (v for v in WIRE_VERSIONS if v in offered), None
            )
            if agreed is None:
                session.send({
                    "type": "connect_document_error",
                    "document_id": doc,
                    "message": (
                        f"no common wire version: client {offered}, "
                        f"server {list(WIRE_VERSIONS)}"
                    ),
                })
                return
            # "read" connections subscribe without joining the quorum
            # (alfred gates the required scope by requested mode)
            mode = frame.get("mode", "write")
            if self.tenants is not None:
                from .tenancy import SCOPE_READ, SCOPE_WRITE, AuthError

                try:
                    self.tenants.validate_token(
                        frame.get("token", ""),
                        frame.get("tenant_id", ""),
                        doc,
                        required_scope=SCOPE_WRITE if mode == "write"
                        else SCOPE_READ,
                    )
                except AuthError as e:
                    session.send({
                        "type": "connect_document_error",
                        "document_id": doc,
                        "message": str(e),
                    })
                    return
            # a retried connect supersedes the old connection: leaving
            # it joined would pin the document's msn at its refSeq and
            # double-deliver every op to this session
            stale = session.connections.pop(doc, None)
            if stale is not None:
                stale.disconnect()
            conn = self.local.connect(
                doc, client_id,
                on_message=lambda msg, d=doc: session.send({
                    "type": "op", "document_id": d,
                    "msg": message_to_json(msg),
                }),
                on_nack=lambda nack, d=doc: self._send_nack(
                    session, d, nack),
                detail=ClientDetail(client_id, **details)
                if details else None,
                read_only=(mode == "read"),
            )
            session.connections[doc] = conn
            session.authorized.add(doc)
            if mode == "write":
                session.write_authorized.add(doc)
            session.wire_versions[doc] = agreed
            session.tenant_ids[doc] = frame.get("tenant_id") or ""
            if session.tenant_ids[doc]:
                # server-level doc -> tenant map: the sidecar's
                # attribution tenant_of hook resolves through this
                self.doc_tenants[doc] = session.tenant_ids[doc]
            session.send({
                "type": "connected", "document_id": doc,
                "client_id": client_id, "version": agreed,
            })
        elif kind == "submitOp":
            conn = session.connections[doc]
            # "ops" (wire >= 1.2) = one boxcarred batch. This handler
            # runs synchronously on the event loop with no awaits, so
            # the array tickets as one contiguous seq run — no other
            # session's frame can interleave a foreign op mid-batch
            # (the reference's alfred handles socket.io message arrays
            # the same way).
            boxcar = frame.get("ops")
            if boxcar is not None and wire_version_lt(
                    session.wire_versions.get(doc, "1.0"), "1.2"):
                raise ValueError(
                    "boxcarred submit requires wire version >= 1.2 "
                    f"(connection agreed "
                    f"{session.wire_versions.get(doc, '1.0')})"
                )
            cols = frame.get("cols")
            if cols is not None:
                # "cols" (wire >= 1.3) = one columnar SoA batch
                # (protocol/columnar.py). Same atomic-ticket shape as
                # the boxcar; the column layout is interpreted exactly
                # ONCE, below, never per op.
                if boxcar is not None or frame.get("op") is not None:
                    raise ValueError(
                        "submitOp carries exactly one of op/ops/cols"
                    )
                if wire_version_lt(
                        session.wire_versions.get(doc, "1.0"), "1.3"):
                    raise ValueError(
                        "columnar submit requires wire version >= 1.3 "
                        f"(connection agreed "
                        f"{session.wire_versions.get(doc, '1.0')})"
                    )
                # the whole column layout is validated BEFORE anything
                # slices it; a malformed column refuses the batch as a
                # unit with a BAD_REQUEST nack — nothing sequenced,
                # nothing sliced
                try:
                    n_ops = validate_columns(cols)
                except ValueError as e:
                    _NACKS_OUT.inc()
                    session.send({
                        "type": "nack", "document_id": doc,
                        "operation": None,
                        "sequence_number": 0,
                        "error_type": int(NackErrorType.BAD_REQUEST),
                        "message": str(e),
                    })
                    return
                _COLUMNAR.inc()
                ops_json = None
                # columnar batches are writes by construction: the
                # column vocabulary is INSERT/REMOVE only, so no
                # summarize proposal can ride one
                klass = CLASS_WRITE
            else:
                ops_json = boxcar if boxcar is not None \
                    else [frame["op"]]
                if boxcar is not None:
                    _BOXCARS.inc()
                # Summarize proposals classify as summary traffic
                # (first to shed). ALL-summarize only: the client's
                # summarizer submits solo frames, so this is the legit
                # shape — a mixed batch must classify as write, or
                # co-batching one SUMMARIZE would shed writer ops at
                # ELEVATED and dodge the op/byte budgets (charging the
                # summary buckets instead)
                klass = CLASS_SUMMARY if ops_json and all(
                    o.get("type") == int(MessageType.SUMMARIZE)
                    for o in ops_json
                ) else CLASS_WRITE
                n_ops = len(ops_json)
            # the admission gate sits BEFORE decode: at 10x offered
            # load, the shed path must cost a dict lookup and a
            # bucket peek, not a full op decode. Offered counts
            # BEFORE the gate: the goodput SLO's denominator must
            # include what admission shed, or the objective could
            # never see an overload
            _OPS_OFFERED.inc(n_ops)
            tenant = session.tenant_ids.get(doc or "", "")
            if self.usage is not None and tenant:
                self.usage.charge(tenant, 0.0, ops_offered=n_ops)
                _TENANT_OPS_OFFERED.inc(n_ops)
            adm = self._admit(session, klass, doc, frame,
                              ops=n_ops, nbytes=nbytes)
            if adm is not None:
                self._send_shed(session, doc, frame, adm,
                                as_nack=True)
                return
            # decode the WHOLE array before submitting anything: a
            # malformed op mid-boxcar must fail the batch as a unit
            # (error frame, nothing sequenced) — partially ticketing
            # it would put a torn batch on the wire, the exact state
            # the boxcar protocol exists to rule out. The columnar
            # batch was already validated as a unit above; this is
            # its one column->message slicing pass, at the sequencer
            # boundary (single-sourced sequencing: interpreted once).
            decoded = decode_columns(cols) if cols is not None \
                else [document_message_from_json(o) for o in ops_json]
            _OPS_IN.inc(len(decoded))
            for op in decoded:
                # the front-door hop: client-side stamps arrived on
                # the frame; this marks event-loop receipt
                trace_stamp(op.traces, "ingress", "receive")
            if ops_json is None:
                # columnar: the nack echo below reconstructs the row
                # form lazily (rejections only — the served path never
                # pays a per-op re-encode)
                ops_json = [None] * len(decoded)
            ticketed = 0
            for op_json, op in zip(ops_json, decoded):
                try:
                    conn.submit(op)
                    # goodput numerator: only ops the sequencer
                    # actually accepted — counting at decode would
                    # read an all-nacked fleet as 100% served
                    _OPS_TICKETED.inc()
                    ticketed += 1
                except PermissionError as e:
                    # read-mode connection: reject as a NACK so the
                    # driver's on_nack fires (parity with the in-proc
                    # path, which raises to the caller directly)
                    _NACKS_OUT.inc()
                    session.send({
                        "type": "nack", "document_id": doc,
                        "operation": (
                            op_json if op_json is not None
                            else document_message_to_json(op)
                        ),
                        "sequence_number": 0,
                        "error_type": int(NackErrorType.INVALID_SCOPE),
                        "message": str(e),
                    })
            if self.usage is not None and tenant and ticketed:
                self.usage.charge(tenant, 0.0, ops_ticketed=ticketed)
                _TENANT_OPS_TICKETED.inc(ticketed)
        elif kind == "read_ops":
            adm = self._admit(session, CLASS_CATCHUP, doc, frame)
            if adm is not None:
                self._send_shed(session, doc, frame, adm,
                                as_nack=False)
                return
            self._check_read_access(session, doc, frame)
            msgs = self.local.read_ops(
                doc, frame["from_seq"], frame.get("to_seq")
            )
            session.send({
                "type": "ops", "rid": frame.get("rid"),
                "msgs": [message_to_json(m) for m in msgs],
            })
        elif kind == "fetch_summary":
            adm = self._admit(session, CLASS_CATCHUP, doc, frame)
            if adm is not None:
                self._send_shed(session, doc, frame, adm,
                                as_nack=False)
                return
            self._check_read_access(session, doc, frame)
            latest = self.local.latest_summary(doc)
            payload: dict[str, Any] = {
                "type": "summary", "rid": frame.get("rid"),
            }
            if latest is None:
                payload["sequence_number"] = None
                payload["summary"] = None
            else:
                payload["sequence_number"] = latest.sequence_number
                payload["summary"] = encode_contents(latest.summary)
            session.send(payload)
        elif kind == "upload_summary_chunk":
            # the upload plane requires a PRIOR connect_document for
            # the document: the negotiated wire version is what
            # authorizes 1.1 frames. Un-negotiated frames used to be
            # waved through as "self-evidently 1.1", which made the
            # version gate advisory — a client could skip negotiation
            # entirely and never be held to the compat matrix
            # (round-5 advisor finding).
            agreed = session.wire_versions.get(doc)
            if agreed is None:
                raise ValueError(
                    f"summary upload before connect_document for "
                    f"{doc!r}: negotiate the wire version first"
                )
            if wire_version_lt(agreed, "1.1"):
                raise ValueError(
                    f"summary upload requires wire version >= 1.1 "
                    f"(connection agreed {agreed})"
                )
            # admission gates NEW uploads only (chunk 0), charged the
            # whole upload's estimated bytes up front — shedding a
            # continuation chunk would strand the staged prefix and
            # surface as a misleading out-of-order error later (the
            # same reasoning as the loud at-cap rejection below)
            if int(frame.get("chunk", 0)) == 0:
                est = len(str(frame.get("data", ""))) * max(
                    1, int(frame.get("total", 1))
                )
                adm = self._admit(session, CLASS_SUMMARY, doc, frame,
                                  ops=1, nbytes=est)
                if adm is not None:
                    self._send_shed(session, doc, frame, adm,
                                    as_nack=False)
                    return
            self._check_write_access(session, doc, frame)
            self._handle_upload_chunk(session, doc, frame)
            if self.usage is not None and \
                    int(frame.get("chunk", 0)) + 1 == \
                    int(frame.get("total", 1)):
                # the final chunk staged the tree: one completed
                # upload charged to the connect-validated tenant
                tenant = session.tenant_ids.get(doc or "", "")
                if tenant:
                    self.usage.charge(tenant, 0.0, summary_uploads=1)
                    _TENANT_UPLOADS.inc()
        elif kind == "disconnect_document":
            conn = session.connections.pop(doc, None)
            if conn is not None:
                conn.disconnect()
        else:
            raise ValueError(f"unknown frame type {kind!r}")

    # upload size guards: a hostile client must not balloon server
    # memory through the staging buffers. Bytes are accounted PER
    # SESSION across all in-flight uploads. Past the concurrency cap,
    # uploads idle beyond UPLOAD_IDLE_TTL are reclaimed (abandoned
    # upload_ids must not hold slots/bytes forever), then a NEW
    # upload_id is rejected loudly — never an in-progress one
    # (ADVICE r4: evicting a live upload surfaced as a misleading
    # out-of-order error on its next chunk).
    MAX_UPLOAD_CHUNK = 1 << 20       # 1 MiB per frame
    MAX_UPLOAD_TOTAL = 256 << 20     # 256 MiB staged per session
    MAX_UPLOADS_IN_FLIGHT = 4
    UPLOAD_IDLE_TTL = 60.0           # seconds without a chunk

    def _handle_upload_chunk(self, session: _ClientSession, doc: str,
                             frame: dict) -> None:
        """Chunked client summary upload
        (driver-definitions/src/storage.ts:119
        uploadSummaryWithContext; historian's summary POST routes).
        Chunks arrive in order on the session's TCP stream;
        intermediate chunks are fire-and-forget (the driver pipelines
        them and only the final, rid-carrying chunk waits), and the
        final chunk stages the tree in the document's
        content-addressed store, returning the root handle for the
        summarize op."""
        upload_id = str(frame["upload_id"])
        chunk_i = int(frame["chunk"])
        total = int(frame["total"])
        data = frame["data"]
        if not isinstance(data, str) or \
                len(data) > self.MAX_UPLOAD_CHUNK:
            raise ValueError("upload chunk too large or malformed")
        if total < 1:
            raise ValueError("malformed upload")
        now = time.monotonic()
        # reclaim abandoned uploads (e.g. a driver that timed out
        # mid-upload and never sends the final chunk) on EVERY chunk,
        # not only at the count cap: an under-cap abandoned upload
        # would otherwise hold its staged bytes against
        # MAX_UPLOAD_TOTAL for the session's lifetime
        for uid in [
            uid for uid, st in session.uploads.items()
            if uid != upload_id
            and now - st["touched"] > self.UPLOAD_IDLE_TTL
        ]:
            session.uploads.pop(uid)
        state = session.uploads.get(upload_id)
        if state is None and chunk_i != 0:
            # a continuation for an upload we don't know: it was
            # rejected at the cap, reclaimed by the idle TTL, or never
            # started — say so, instead of creating fresh state and
            # failing with a misleading out-of-order error
            raise ValueError(
                "unknown upload (rejected, expired, or never started)"
            )
        if state is None:
            if len(session.uploads) >= self.MAX_UPLOADS_IN_FLIGHT:
                # Reject loudly: evicting a fresh upload would kill a
                # legitimately in-progress one on a multiplexed
                # connection, and its next chunk would then fail with
                # a misleading out-of-order error (ADVICE r4).
                raise ValueError(
                    "too many concurrent uploads "
                    f"(max {self.MAX_UPLOADS_IN_FLIGHT})"
                )
            state = session.uploads[upload_id] = {
                "doc": doc, "parts": [], "total": total,
                "touched": now,
            }
        state["touched"] = now
        if state["doc"] != doc or state["total"] != total \
                or chunk_i != len(state["parts"]):
            session.uploads.pop(upload_id, None)
            raise ValueError("upload chunk out of order")
        staged_bytes = sum(
            len(p) for st in session.uploads.values()
            for p in st["parts"]
        )
        if staged_bytes + len(data) > self.MAX_UPLOAD_TOTAL:
            session.uploads.pop(upload_id, None)
            raise ValueError("upload too large")
        state["parts"].append(data)
        if chunk_i + 1 < total:
            if frame.get("rid") is not None:
                session.send({
                    "type": "upload_ack", "rid": frame["rid"],
                    "received": chunk_i,
                })
            return
        fault = _SITE_UPLOAD.fire(doc=doc)
        if fault is not None:
            # the staged chunks are DISCARDED with the failure (a
            # retry resends the whole upload under a fresh upload_id
            # — there is no resume protocol); raising here answers
            # the waited rid with the transient error shape the
            # driver converts, and the container falls back inline
            session.uploads.pop(upload_id, None)
            raise _SITE_UPLOAD.transient(fault)
        session.uploads.pop(upload_id, None)
        summary = decode_contents(json.loads("".join(state["parts"])))
        handle = self.local.get_orderer(doc).summary_store.stage(
            summary
        )
        session.send({
            "type": "summary_uploaded", "rid": frame.get("rid"),
            "handle": handle,
        })


def default_slo_objectives() -> list:
    """The service plane's default objectives (docs/OBSERVABILITY.md
    "Serving SLOs"). They bind ONLY to families this module owns —
    obs must never import what it observes, so the objective
    declarations live with the layer that registers the histograms:

    - ``ingress-dispatch-p99``: 99% of dispatched frames occupy the
      event loop < 50ms. The loop IS the serving capacity of this
      process; a frame past 50ms is starving every other session.
    - ``ingress-goodput``: >= 95% of offered client ops decode and
      ticket (the rest were shed by admission or failed) over the
      burn window — the "is the service actually serving" floor.
    """
    from ..obs.slo import Objective

    return [
        Objective("ingress-dispatch-p99",
                  metric="ingress_dispatch_ms",
                  threshold_ms=50.0, target=0.99),
        Objective("ingress-goodput", kind="goodput",
                  good_metric="ingress_ops_ticketed_total",
                  total_metric="ingress_ops_offered_total",
                  target=0.95),
    ]


def _parse_hostport(value: str, default_host: str = "127.0.0.1"
                    ) -> tuple[str, int]:
    """Parse "host:port" (IPv6 literals bracketed: "[::1]:7081") with
    a usable error instead of an int() traceback."""
    host, sep, port = value.rpartition(":")
    if not sep or not port.isdigit():
        raise SystemExit(
            f"--broker expects host:port, got {value!r}"
        )
    host = host or default_host
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]
    return host, int(port)


def _check_durable_layout(data_dir: Optional[str],
                          partitions: int,
                          queue_source: str = "local") -> None:
    """The inline and partitioned modes use different on-disk layouts,
    the partition count is baked into the queue's document->partition
    routing, and the QUEUE SOURCE (local file queue vs networked
    broker) determines where unconsumed records live. Restarting an
    existing data dir under a different configuration would silently
    come up empty (or misroute documents, or orphan unpumped records
    in the abandoned queue) — refuse loudly instead."""
    if data_dir is None:
        return
    import json as _json
    import os as _os

    marker = _os.path.join(data_dir, "layout.json")
    current = {"mode": "partitioned" if partitions > 0 else "inline",
               "partitions": partitions}
    if partitions > 0:
        current["queue"] = queue_source
    if _os.path.exists(marker):
        with open(marker) as f:
            stored = _json.load(f)
        # pre-queue-field markers: local was the only option then;
        # early markers stored the broker ADDRESS — normalize to the
        # kind (an address respelling must not brick the dir)
        if stored.get("mode") == "partitioned":
            stored.setdefault("queue", "local")
            if str(stored["queue"]).startswith("broker:"):
                stored["queue"] = "broker"
        if stored != current:
            raise SystemExit(
                f"data dir {data_dir!r} was created with layout "
                f"{stored}, refusing to start with {current}: document "
                "history would be ignored or misrouted. Use the "
                "original flags or a fresh --data-dir."
            )
        return
    if _os.path.isdir(data_dir) and _os.listdir(data_dir):
        # data without a marker (pre-marker release or foreign dir):
        # adopting a layout could silently orphan that history
        raise SystemExit(
            f"data dir {data_dir!r} contains data but no layout.json; "
            "refusing to guess its layout. Create layout.json "
            f"({current} for the current flags) after verifying, or "
            "use a fresh --data-dir."
        )
    _os.makedirs(data_dir, exist_ok=True)
    with open(marker, "w") as f:
        _json.dump(current, f)


def run_server(host: str = "127.0.0.1", port: int = 7070,
               data_dir: Optional[str] = None,
               partitions: int = 0,
               broker: Optional[str] = None,
               qos_enabled: bool = False,
               qos_ops_per_sec: float = 2000.0,
               slo_enabled: bool = False) -> None:
    """Blocking entry point (the tinylicious analogue; see
    service/__main__.py). ``data_dir`` makes every document durable:
    op log, summaries and deli checkpoints survive restarts.
    ``partitions`` > 0 routes everything through the partitioned
    queue pipeline (the kafka-deployment shape) instead of the inline
    orderer; ``broker`` = "host:port" of a running
    ``service.broker`` — the NETWORKED queue, so partitions span
    processes/hosts (services-ordering-rdkafka's role).
    ``qos_enabled`` turns on admission control + backpressure
    (docs/QOS.md): token-bucket limits scaled from
    ``qos_ops_per_sec``, pressure-tier shedding, and a circuit
    breaker around checkpoint writes. ``slo_enabled`` attaches the
    default serving objectives (:func:`default_slo_objectives`) to
    an obs.SloEngine serving the ``slo`` frame / ``--dump-slo``."""
    queue = None
    if broker is not None:
        from .broker import RemoteOrderingQueue

        bhost, bport = _parse_hostport(broker)
        queue = RemoteOrderingQueue(bhost, bport)
        if partitions <= 0:
            partitions = queue.n_partitions
        elif partitions != queue.n_partitions:
            # document->partition routing is crc32 % N: a consumer
            # disagreeing with the broker's N splits document ordering
            # across partitions (or produces out-of-range)
            raise SystemExit(
                f"--partitions {partitions} disagrees with the "
                f"broker's {queue.n_partitions}; drop --partitions "
                "or match it"
            )
        import os as _os

        fresh_state = data_dir is None or not _os.path.exists(
            _os.path.join(data_dir, "layout.json")
        )
        if fresh_state and any(
            queue.committed(p) >= 0 for p in range(partitions)
        ):
            # the broker has committed progress but this consumer has
            # no prior document state (no --data-dir, or an empty
            # one): resuming past the committed offsets would bring
            # every document up silently EMPTY
            raise SystemExit(
                "broker has committed offsets but this server has no "
                "prior state: resuming would skip all applied "
                "history. Point --data-dir at the original state (or "
                "a replacement host's copy)."
            )
    # the marker records WHICH KIND of queue (local file vs networked
    # broker), not the broker's address — a respelled host or a
    # re-launched broker port must not brick the data dir
    _check_durable_layout(
        data_dir, partitions,
        queue_source="broker" if broker else "local",
    )
    qos = None
    storage_breaker = None
    if qos_enabled:
        from ..qos import (
            AdmissionController,
            CircuitBreaker,
            PressureMonitor,
            default_limits,
        )

        if data_dir is not None:
            storage_breaker = CircuitBreaker(
                "checkpoint-storage", failure_threshold=3,
                reset_timeout_s=5.0,
            )
        qos = AdmissionController(
            limits=default_limits(qos_ops_per_sec),
            # cost-bounded sampling on the serving path: at overload
            # the gate runs per frame; 50ms staleness is immaterial
            # against queue depths that build over seconds
            pressure=PressureMonitor(min_interval_s=0.05),
        )
    if partitions > 0:
        from .partitioning import PartitionedServer

        local = PartitionedServer(
            n_partitions=partitions, durable_dir=data_dir,
            queue=queue, storage_breaker=storage_breaker)
    else:
        local = LocalServer(durable_dir=data_dir,
                            storage_breaker=storage_breaker)
    # cost-attribution plane (obs/heat.py): the per-document heat
    # ledger (charged by a sidecar when one is wired; served either
    # way) and the per-tenant usage rollup — both LRU-capped, both
    # behind the wire-1.4 heat frame / --dump-heat
    from ..obs.heat import HeatLedger, usage_ledger

    heat = HeatLedger()
    usage = usage_ledger()
    slo = None
    if slo_enabled:
        from ..obs.slo import SloEngine

        slo = SloEngine(default_slo_objectives())
        if qos is not None and getattr(qos, "pressure", None) \
                is not None:
            # burn-rate verdicts cite the overload context: "goodput
            # burned through its budget WHILE pressure sat at severe"
            slo.add_context("pressure", qos.pressure.context)
        # ... and WHO was burning it: every verdict carries the top-k
        # hot tenants off the usage ledger, so an overload breach
        # names its cause instead of just its symptom
        slo.add_context("hot_tenants", lambda: usage.top_k(5))
    server = AlfredServer(local, host=host, port=port, qos=qos,
                          slo=slo, heat=heat, usage=usage)

    async def main():
        await server.start()
        print(f"fluidframework-tpu dev service listening on "
              f"{server.host}:{server.port}", flush=True)
        await server.serve_forever()

    try:
        asyncio.run(main())
    except KeyboardInterrupt:  # pragma: no cover - interactive
        pass
