"""Tree serving plane: SharedTree documents served doc-parallel
through the sidecar dispatch loop.

The merge sidecar's pipelined pack->dispatch->settle contract
(tpu_sidecar.py), instantiated for the second kernelized DDS
(ROADMAP item 6): forest state lives on device as SoA arrays
``[docs, slots]`` (ops/tree_apply.py) and every round's queued
insert/remove/move/annotate changesets apply across all tracked tree
documents in ONE dispatch — trunk-suffix rebase as a ``lax.scan``
over the per-doc trunk ring vmapped over docs, then the batched
forest-apply kernel on the validated executor route (``atom`` is the
per-atom parity reference, ``macro`` the one-sort macro step; both
bit-identical by the service differential suite).

The same tier policy as the merge plane, in the same order: primary
slab ladder (2x regrows re-applying the failed window from the
pre-dispatch snapshot), then the pooled tier (``TreeSeqPool`` — a
larger chip-local slab; the tree kernels' per-changeset sorts do not
decompose over a slot-sharded axis, so the pool's capacity unlock is
slab size, not slot sharding), then host eviction to a scalar
EditManager replica (full fidelity: nested fields, unbounded width).
Two tree-specific eviction triggers ride the same path: a
device-inexpressible changeset (``encode_tree_commit`` ValueError)
and a commit whose ref predates the device trunk ring
(``ring_safe`` — the ring holds the last ``TRUNK_RING`` rebased
trunk commits, and a straggler that must rebase over more than that
is host work by design).

``ChannelKindRouter`` is the ingress-side routing point: the attach
op announces ``channelType`` (the IChannelFactory boundary), and the
router feeds sharedstring channels to the merge sidecar and
sharedtree channels to this one — flat merge documents never
traverse tree code on their hot path, and vice versa.
"""
from __future__ import annotations

import copy
import json
import os
import time
from collections import deque
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..models.tree.editmanager import Commit, EditManager
from ..obs import metrics as obs_metrics
from ..obs.flight_recorder import FlightRecorder
from ..obs.profiler import device_trace
from ..ops.bucket_ladder import BucketLadder
from ..ops.event_graph import validate_executor
from ..ops.tree_apply import (
    DEFAULT_ATOMS,
    TREE_EXECUTOR_ROUTES,
    TRUNK_RING,
    apply_tree_window,
    decode_tree_row,
    encode_tree_commit,
    make_tree_table,
    noop_tree_commit,
    pack_tree_window,
    pad_tree_capacity,
    ring_safe,
)
from ..protocol.messages import MessageType, SequencedMessage
from ..protocol.tree_payload import tree_change_from_json
from ..qos.faults import KIND_ERROR, KIND_ERROR_BURST, PLANE as _CHAOS

_M_ROUNDS = obs_metrics.REGISTRY.counter(
    "tree_sidecar_rounds_total", "tree dispatch rounds flushed")
_M_COMMITS = obs_metrics.REGISTRY.counter(
    "tree_sidecar_commits_total",
    "sequenced tree changesets applied on device")
_M_GROW = obs_metrics.REGISTRY.counter(
    "tree_sidecar_grow_total", "tree capacity-ladder regrows")
_M_EVICT = obs_metrics.REGISTRY.counter(
    "tree_sidecar_evict_total",
    "tree documents evicted to host EditManager replicas")
_M_RING_EVICT = obs_metrics.REGISTRY.counter(
    "tree_sidecar_ring_evict_total",
    "tree documents evicted because a commit's ref predated the "
    "device trunk ring (ring_safe)")
_M_RECOVER = obs_metrics.REGISTRY.counter(
    "tree_sidecar_overflow_recoveries_total",
    "tree settle boundaries that found the overflow flag set")
_M_POOL_ADMIT = obs_metrics.REGISTRY.counter(
    "tree_sidecar_pool_admit_total",
    "tree documents admitted to the pooled tier")
_M_DUP_DROPS = obs_metrics.REGISTRY.counter(
    "tree_sidecar_duplicate_drops_total",
    "duplicate sequenced deliveries dropped by the per-document "
    "sequence-number guard")
_M_DISPATCH_FAULTS = obs_metrics.REGISTRY.counter(
    "tree_sidecar_dispatch_faults_total",
    "tree dispatch rounds that failed transiently before mutating "
    "anything (commits stay queued; the next apply retries exactly)")
_M_PACK_MS = obs_metrics.REGISTRY.histogram(
    "tree_sidecar_pack_ms", "host half of a tree round (encode+pack)")
_M_SETTLE_MS = obs_metrics.REGISTRY.histogram(
    "tree_sidecar_settle_ms",
    "device-wait at the tree settle boundary")
_M_TRACKED = obs_metrics.REGISTRY.gauge(
    "tree_sidecar_tracked_channels",
    "tree channels on the device batch path")
_M_HOSTED = obs_metrics.REGISTRY.gauge(
    "tree_sidecar_host_docs",
    "tree documents on host EditManager replicas")
_M_CAPACITY = obs_metrics.REGISTRY.gauge(
    "tree_sidecar_capacity",
    "current tree slab capacity (node slots/doc)")
_M_POOL_MEMBERS = obs_metrics.REGISTRY.gauge(
    "tree_pool_members", "tree documents on the pooled tier")
_M_POOL_DISPATCH = obs_metrics.REGISTRY.counter(
    "tree_pool_dispatches_total",
    "tree-pool incremental dispatches")

# chaos seam: fires BEFORE the round mutates anything (queues intact,
# so a retry is exact) — the same recovery-path contract as
# sidecar.dispatch (docs/ROBUSTNESS.md)
_SITE_DISPATCH = _CHAOS.site(
    "tree_sidecar.dispatch", (KIND_ERROR, KIND_ERROR_BURST))


def default_tree_executor() -> str:
    """Tree-plane route policy, mirroring ``default_executor``: the
    per-atom scan is the CPU default (launches are ~free there and a
    fused scan step beats the macro sort), the one-sort macro step is
    the launch-taxed TPU default (2 launches per changeset vs 2A scan
    steps). ``FFTPU_TREE_EXECUTOR=atom|macro`` overrides either way
    and fails LOUDLY on a typo."""
    env = os.environ.get("FFTPU_TREE_EXECUTOR")
    if env:
        validate_executor(env, "FFTPU_TREE_EXECUTOR",
                          routes=TREE_EXECUTOR_ROUTES)
        return env
    import jax

    try:
        backend = jax.default_backend()
    except RuntimeError as e:  # pragma: no cover - backend init failure
        import sys

        print(
            "default_tree_executor: jax backend init failed "
            f"({e}); routing as cpu",
            file=sys.stderr,
        )
        backend = "cpu"
    return "macro" if backend == "tpu" else "atom"


def _fresh_replica(slot: int) -> EditManager:
    return EditManager(session_id=f"tree-host-{slot}")


class TreeSeqPool:
    """Pooled tier for tree documents that outgrow the primary slab
    ladder: a fixed-row table at a LARGER per-doc capacity. The tree
    kernels' per-changeset sorts (ops/tree_apply.py) do not decompose
    over a slot-sharded axis — the same reason SeqShardedPool keeps
    the scan-collective route — so this pool's capacity unlock is a
    bigger chip-local slab, with host eviction the last resort.
    Admission rebuilds the pool table at the next pow2 row bucket and
    replays every member's canonical encoded-commit stream in chunked
    dispatches; incremental traffic dispatches watermarked stream
    tails at the settle boundary (exactly-once by construction, the
    SeqShardedPool contract)."""

    def __init__(self, mesh, per_doc_capacity: int,
                 executor: Optional[str] = None,
                 ring: int = TRUNK_RING, atoms: int = DEFAULT_ATOMS,
                 ladder: Optional[BucketLadder] = None):
        validate_executor(executor, "executor",
                          routes=TREE_EXECUTOR_ROUTES)
        self.mesh = mesh  # accepted for select_pool API parity only
        self.capacity = per_doc_capacity
        self.executor = executor or default_tree_executor()
        self.ring = ring
        self.atoms = atoms
        self.ladder = ladder or BucketLadder()
        self.members: list[int] = []
        self.row_of: dict[int, int] = {}
        # per-member stream watermark: encoded commits already
        # reflected by the pool table (rebuilds advance it to the
        # head, so a tail a rebuild subsumed can never dispatch again)
        self.applied_upto: dict[int, int] = {}
        self._table = None
        self.dispatch_count = 0

    def _bucket(self) -> int:
        b = 1
        while b < max(1, len(self.members)):
            b *= 2
        return b

    def _replay_all(self, encoded: list[list[dict]]) -> None:
        if not self.members:
            self._table = None
            return
        rows = self._bucket()
        table = make_tree_table(rows, self.capacity, ring=self.ring,
                                atoms=self.atoms)
        chunk = BucketLadder.replay_chunk(self.capacity)
        depth = max(len(encoded[s]) for s in self.members)
        for start in range(0, max(depth, 1), chunk):
            queued = {
                row: encoded[slot][start:start + chunk]
                for row, slot in enumerate(self.members)
                if encoded[slot][start:start + chunk]
            }
            program = pack_tree_window(
                rows, queued, self.ladder, bucket_floor=chunk,
                width=self.atoms)
            table = apply_tree_window(table, program, self.executor)
        self._table = table
        self.applied_upto = {
            slot: len(encoded[slot]) for slot in self.members
        }
        _M_POOL_MEMBERS.set(len(self.members))

    def admit(self, slots: list, encoded: list[list[dict]]) -> list:
        """Admit sidecar slots; returns the slots that FAILED (exceed
        even pooled capacity) and were rolled back out."""
        for slot in slots:
            if slot not in self.row_of:
                self.row_of[slot] = len(self.members)
                self.members.append(slot)
        self._replay_all(encoded)
        failed = self.overflowed_slots()
        if failed:
            for slot in failed:
                self.remove(slot)
            self._replay_all(encoded)
        return failed

    def remove(self, slot: int) -> None:
        """Bookkeeping only — callers follow with rebuild() before
        the next read or dispatch (the SeqShardedPool contract)."""
        if slot not in self.row_of:
            return
        row = self.row_of.pop(slot)
        self.applied_upto.pop(slot, None)
        self.members.pop(row)
        for s2, r2 in self.row_of.items():
            if r2 > row:
                self.row_of[s2] = r2 - 1

    def rebuild(self, encoded: list[list[dict]]) -> None:
        self._replay_all(encoded)

    def dispatch_pending(self, encoded: list[list[dict]]) -> list:
        """Apply every member's un-applied stream tail in one
        dispatch; returns slots that overflowed the pool."""
        if self._table is None:
            return []
        pending = {}
        upto = {}
        for slot, row in self.row_of.items():
            tail = encoded[slot][self.applied_upto.get(slot, 0):]
            if tail:
                pending[row] = tail
                upto[slot] = len(encoded[slot])
        if not pending:
            return []
        self.dispatch_count += 1
        _M_POOL_DISPATCH.inc()
        program = pack_tree_window(
            self._table.docs, pending, self.ladder,
            width=self.atoms)
        self._table = apply_tree_window(
            self._table, program, self.executor)
        self.applied_upto.update(upto)
        return self.overflowed_slots()

    def prewarm(self) -> None:
        """Compile the first-admission shapes (row bucket 1 at the
        incremental floor bucket and the replay chunk bucket) before
        any admission reaches them mid-serve; wider row buckets and
        deeper windows pay as they land — admission is rare and
        already O(history), the SeqShardedPool discipline."""
        noop = noop_tree_commit(self.atoms)
        chunk = BucketLadder.replay_chunk(self.capacity)
        for floor in sorted({self.ladder.window_floor, chunk}):
            program = pack_tree_window(
                1, {0: [noop]}, self.ladder, bucket_floor=floor,
                width=self.atoms)
            table = make_tree_table(1, self.capacity, ring=self.ring,
                                    atoms=self.atoms)
            out = apply_tree_window(table, program, self.executor)
            apply_tree_window(out, program, self.executor)

    def overflowed_slots(self) -> list:
        if self._table is None:
            return []
        flags = np.asarray(self._table.overflow)
        return [self.members[r]
                for r in np.nonzero(flags)[0].tolist()
                if r < len(self.members)]


class TreeSidecar:
    """Batched forest state for up to ``max_docs`` sharedtree
    channels. One tracked channel (doc slot) = one (document,
    datastore, channel) sequenced changeset stream; ``ingest``
    consumes the document's sequenced envelope stream, ``apply``
    flushes accumulated commit windows in a single pipelined
    dispatch, and ``_settle`` is the ONLY host<->device sync (the
    merge sidecar's pipeline/settle contract)."""

    def __init__(self, max_docs: int = 64, capacity: int = 64,
                 max_capacity: int = 4096,
                 pool_mesh=None, pool_capacity: Optional[int] = None,
                 executor: Optional[str] = None,
                 pipeline: Optional[bool] = None,
                 ladder: Optional[BucketLadder] = None,
                 ring: int = TRUNK_RING,
                 width: int = DEFAULT_ATOMS):
        self.max_docs = max_docs
        self.capacity = capacity
        self.max_capacity = max_capacity
        self.ring = ring
        self.width = width
        # the constructor-arg route typo is exactly as loud as the
        # env one (the select_pool discipline)
        validate_executor(executor, "executor",
                          routes=TREE_EXECUTOR_ROUTES)
        self.executor = executor or default_tree_executor()
        if pipeline is not None:
            self.pipeline = pipeline
        else:
            env_pipe = os.environ.get("FFTPU_SIDECAR_PIPELINE")
            if env_pipe and env_pipe not in ("0", "1"):
                raise ValueError(
                    f"FFTPU_SIDECAR_PIPELINE={env_pipe!r}: expected "
                    "'0' or '1'"
                )
            self.pipeline = env_pipe != "0"
        self.ladder = ladder or BucketLadder()
        self.flight = FlightRecorder(256, name="tree-sidecar")
        self.last_flight_dump: Optional[str] = None
        self._pool = None
        if pool_mesh is not None:
            from .tpu_sidecar import select_pool

            self._pool = select_pool(
                pool_mesh, pool_capacity, executor=self.executor,
                max_capacity=max_capacity, plane="tree",
            )
            self._pool.ring = ring
            self._pool.atoms = width
        self.pool_admit_count = 0
        self._table = make_tree_table(max_docs, capacity, ring=ring,
                                      atoms=width)
        self._slots: dict[tuple[str, str, str], int] = {}
        self._doc_slots: dict[str, list[tuple[int, str, str]]] = {}
        self._last_ingested: dict[str, int] = {}
        # per-slot canonical histories: raw scalar commits (evictions
        # replay these into the EditManager replica) and the encoded
        # device form (grow re-applies the window; the pool replays
        # the encoded stream)
        self._raw: list[list[Commit]] = []
        self._encoded: list[list[dict]] = []
        self._queued: list[list[dict]] = []
        # host payload tables per slot (node content / value dicts;
        # device arrays carry only indices into these)
        self._content_tables: list[list] = []
        self._value_tables: list[list] = []
        # host mirror of the device ring occupancy: seqs of the last
        # ``ring`` encoded commits per slot (ring_safe reads it at
        # ingest — commits queued ahead of this one will have pushed
        # the device ring by the time this one rebases)
        self._ring_hist: list[deque] = []
        self._session_ord: dict[str, int] = {}
        self._host: dict[int, EditManager] = {}
        self._prev_table = None
        self._last_program = None
        self._unsettled = False
        self.grow_count = 0
        self.evict_count = 0
        self.ring_evict_count = 0
        self.stats = {"pack_s": 0.0, "settle_s": 0.0, "rounds": 0}
        _M_CAPACITY.set(self.capacity)

    # ------------------------------------------------------------------
    # registration + ingest

    def track(self, document_id: str, datastore_id: str,
              channel_id: str) -> int:
        key = (document_id, datastore_id, channel_id)
        if key in self._slots:
            return self._slots[key]
        if len(self._raw) >= self.max_docs:
            raise RuntimeError(
                "tree sidecar document capacity exhausted")
        slot = len(self._raw)
        self._slots[key] = slot
        self._doc_slots.setdefault(document_id, []).append(
            (slot, datastore_id, channel_id)
        )
        self._raw.append([])
        self._encoded.append([])
        self._queued.append([])
        self._content_tables.append([])
        self._value_tables.append([])
        self._ring_hist.append(deque(maxlen=self.ring))
        _M_TRACKED.set(len(self._raw))
        return slot

    def subscribe(self, server, document_id: str, datastore_id: str,
                  channel_id: str) -> None:
        """Attach to a LocalServer document's broadcaster (after deli,
        beside scriptorium — the merge sidecar's seat)."""
        self.track(document_id, datastore_id, channel_id)
        orderer = server.get_orderer(document_id)
        orderer.broadcaster.subscribe(
            f"tree-sidecar-{id(self)}/{document_id}/{datastore_id}/"
            f"{channel_id}",
            lambda msg: self.ingest(document_id, msg),
        )

    def _session(self, client_id: Optional[str]) -> int:
        sid = client_id or ""
        if sid not in self._session_ord:
            self._session_ord[sid] = len(self._session_ord) + 1
        return self._session_ord[sid]

    def ingest(self, document_id: str, msg: SequencedMessage) -> None:
        """Consume one sequenced message of a document. Only
        ``{"type": "tree"}`` channel ops for tracked channels carry
        forest state; everything else (joins, other channels,
        tree-schema ops) is ignored — the tree plane keeps no collab
        window, so non-changeset traffic has no device effect.

        AT-LEAST-ONCE GUARD: same per-document dedupe as the merge
        sidecar's ingest — a duplicate delivery would extend the
        canonical histories and apply twice."""
        last = self._last_ingested.get(document_id, 0)
        if msg.sequence_number <= last:
            _M_DUP_DROPS.inc()
            return
        self._last_ingested[document_id] = msg.sequence_number
        for slot, ds_id, ch_id in self._doc_slots.get(document_id, ()):
            envelope = msg.contents \
                if isinstance(msg.contents, dict) else {}
            if not (
                msg.type == MessageType.OPERATION
                and envelope.get("kind", "op") == "op"
                and envelope.get("address") == ds_id
                and envelope.get("channel") == ch_id
            ):
                continue
            changes = tree_change_from_json(envelope.get("contents"))
            if changes is None:
                continue  # tree-schema etc: no forest effect
            commit = Commit(
                session_id=msg.client_id or "",
                seq=msg.sequence_number,
                ref_seq=msg.reference_sequence_number,
                changes=copy.deepcopy(changes),
            )
            self._ingest_commit(slot, commit)

    def _ingest_commit(self, slot: int, commit: Commit) -> None:
        if slot in self._host:
            self._host[slot].add_sequenced_change(commit, False)
            return
        if not ring_safe(list(self._ring_hist[slot]), commit.ref_seq,
                         self.ring):
            # the commit must rebase over more trunk commits than the
            # device ring retains: host work by design
            self.ring_evict_count += 1
            _M_RING_EVICT.inc()
            self._settle()
            self._evict(slot)
            self._host[slot].add_sequenced_change(commit, False)
            return
        try:
            if set(commit.changes) - {"root"}:
                raise ValueError(
                    "non-root tree fields: host path only")
            enc = encode_tree_commit(
                commit.changes.get("root", []),
                self._content_tables[slot],
                self._value_tables[slot],
                seq=commit.seq, ref=commit.ref_seq,
                session=self._session(commit.session_id),
                width=self.width,
            )
        except ValueError:
            # device-inexpressible (nested fields, width overflow,
            # repair-store marks): the full-fidelity host replica
            # takes over — the merge sidecar's eviction discipline
            self._settle()
            self._evict(slot)
            self._host[slot].add_sequenced_change(commit, False)
            return
        self._raw[slot].append(commit)
        self._encoded[slot].append(enc)
        self._queued[slot].append(enc)
        self._ring_hist[slot].append(commit.seq)

    # ------------------------------------------------------------------
    # device application (the dispatch pipeline)

    @property
    def queued_commits(self) -> int:
        return sum(len(q) for q in self._queued)

    def apply(self) -> int:
        """Flush all queued commit windows in one batched dispatch;
        returns the number of commits dispatched. Pipelined (the
        default): returns at enqueue — this round's overflow flag is
        read at the next apply/read, inside ``_settle``."""
        if self.queued_commits == 0:
            return 0
        real = self._dispatch()
        if not self.pipeline:
            self._settle()
        return real

    def sync(self) -> None:
        """Barrier: settle the in-flight round (overflow recovery,
        pool dispatch)."""
        self._settle()

    def _dispatch(self) -> int:
        # chaos seam BEFORE any mutation: queues intact, a retry is
        # exactly the same round
        fault = _SITE_DISPATCH.fire(queued=self.queued_commits)
        if fault is not None:
            _M_DISPATCH_FAULTS.inc()
            raise _SITE_DISPATCH.transient(fault)
        t0 = time.perf_counter()
        packed: dict[int, list[dict]] = {}
        pool_commits = 0
        for slot, q in enumerate(self._queued):
            if not q:
                continue
            if self._pool is not None and slot in self._pool.row_of:
                # pooled docs dispatch from their watermarked encoded
                # streams at the settle boundary
                pool_commits += len(q)
                continue
            packed[slot] = list(q)
        program = pack_tree_window(
            self.max_docs, packed, self.ladder, width=self.width)
        real = sum(len(v) for v in packed.values())
        for q in self._queued:
            q.clear()
        pack_s = time.perf_counter() - t0
        self.stats["pack_s"] += pack_s
        self.stats["rounds"] += 1
        _M_ROUNDS.inc()
        _M_COMMITS.inc(real + pool_commits)
        _M_PACK_MS.observe(pack_s * 1000.0)
        self.flight.record(
            "dispatch", round=self.stats["rounds"], commits=real,
            pool_commits=pool_commits,
            pack_ms=round(pack_s * 1000.0, 3),
            capacity=self.capacity,
        )
        # SYNC BOUNDARY — read the previous round's overflow flag
        # before its snapshot is retired below
        self._settle()
        self._prev_table = self._table
        self._last_program = program
        self._unsettled = True
        with device_trace(
                f"tree-sidecar:dispatch:r{self.stats['rounds']}"):
            self._table = apply_tree_window(
                self._prev_table, program, self.executor)
        return real + pool_commits

    def _settle(self) -> None:
        """The designated host<->device sync boundary: read the
        in-flight round's overflow flag, run recovery if set, flush
        the pool dispatch. Reads and the next dispatch both funnel
        through here; nothing else in the apply loop may force a
        device->host transfer."""
        if not self._unsettled:
            return
        self._unsettled = False
        t0 = time.perf_counter()
        overflowed = bool(np.asarray(self._table.overflow).any())
        settle_s = time.perf_counter() - t0
        self.stats["settle_s"] += settle_s
        _M_SETTLE_MS.observe(settle_s * 1000.0)
        self.flight.record(
            "settle", settle_ms=round(settle_s * 1000.0, 3),
            overflow=overflowed,
        )
        if overflowed:
            _M_RECOVER.inc()
            self.last_flight_dump = self.flight.dump_to(
                reason="tree _settle found the overflow flag set "
                       "(recovery running)")
            self._recover()
        self._prev_table = None
        self._last_program = None
        if self._pool is not None and self._pool.members:
            # inside the just-settled branch on purpose (the merge
            # sidecar's tier-consistency rule): the pool advances
            # only when a flush was in flight
            for slot in self._pool.dispatch_pending(self._encoded):
                self._evict(slot)  # beyond even pooled capacity

    # ------------------------------------------------------------------
    # overflow recovery: grow ladder -> pooled tier -> host eviction

    def _recover(self) -> None:
        while True:
            overflowed = np.nonzero(
                np.asarray(self._table.overflow))[0]
            if overflowed.size == 0:
                return
            if self.capacity * 2 <= self.max_capacity:
                self._grow(self.capacity * 2)
            elif self._pool is not None:
                failed = self._admit_to_pool(overflowed.tolist())
                for slot in failed:
                    self._evict(slot)
                return
            else:
                for slot in overflowed.tolist():
                    self._evict(slot)
                return

    def _grow(self, new_capacity: int) -> None:
        """Grow the slab 2x and retry the failed window: pad the
        pre-dispatch snapshot and re-apply the SAME window at the new
        capacity — O(window), exact, because a parked doc's state,
        ring and overflow flag all predate the window (the kernel's
        park contract), so the snapshot re-apply is the first time
        the window touches it."""
        self.grow_count += 1
        _M_GROW.inc()
        self.capacity = new_capacity
        _M_CAPACITY.set(new_capacity)
        self.flight.record("recover-grow", capacity=new_capacity)
        if self._prev_table is None:  # pragma: no cover - first flush
            self._prev_table = make_tree_table(
                self.max_docs, new_capacity, ring=self.ring,
                atoms=self.width)
        else:
            self._prev_table = pad_tree_capacity(
                self._prev_table, new_capacity)
        self._table = apply_tree_window(
            self._prev_table, self._last_program, self.executor)

    def _retire_rows(self, slots: list) -> None:
        """Zero the primary-table count/overflow of ``slots`` — reads
        route elsewhere for these docs, and a stale overflow flag
        would re-trigger (or wedge) recovery."""
        if not slots:
            return
        count = np.asarray(self._table.count).copy()
        overflow = np.asarray(self._table.overflow).copy()
        for slot in slots:
            count[slot] = 0
            overflow[slot] = 0
        self._table = self._table._replace(
            count=jnp.asarray(count), overflow=jnp.asarray(overflow),
        )

    def _admit_to_pool(self, slots: list) -> list:
        """Move slots to the pooled tier; retire their primary rows.
        Returns slots the pool could not hold. Already-members can
        reappear via the pipelined straggler window (the merge
        sidecar's case): they need only the row retirement again."""
        fresh = [s for s in slots if s not in self._pool.row_of]
        failed = self._pool.admit(fresh, self._encoded) \
            if fresh else []
        admitted = [s for s in slots if s not in failed]
        newly = len([s for s in fresh if s not in failed])
        self.pool_admit_count += newly
        _M_POOL_ADMIT.inc(newly)
        self.flight.record("recover-pool", admitted=newly,
                           failed=len(failed))
        self._retire_rows(admitted)
        for slot in admitted:
            self._queued[slot].clear()  # replayed from the stream
        return failed

    def _evict(self, slot: int) -> None:
        """Move one document to a host-side scalar EditManager
        replica — full fidelity, off the device batch path."""
        # retire device state FIRST, even for an already-evicted doc
        # (a pipelined straggler round can re-flag a retired row)
        self._retire_rows([slot])
        if slot in self._host:
            return
        self.evict_count += 1
        _M_EVICT.inc()
        self.flight.record("recover-evict", slot=slot)
        if self._pool is not None and slot in self._pool.row_of:
            self._pool.remove(slot)
            self._pool.rebuild(self._encoded)
        replica = _fresh_replica(slot)
        for commit in self._raw[slot]:
            replica.add_sequenced_change(
                Commit(commit.session_id, commit.seq, commit.ref_seq,
                       copy.deepcopy(commit.changes)),
                False,
            )
        self._host[slot] = replica
        _M_HOSTED.set(len(self._host))
        if self._pool is not None:
            _M_POOL_MEMBERS.set(len(self._pool.members))
        self._queued[slot].clear()

    # ------------------------------------------------------------------
    # prewarm

    def prewarm(self, max_bucket: Optional[int] = None) -> float:
        """Compile every shape the (docs, window, capacity) ladder
        can reach on BOTH executor routes — steady windows, regrows
        and a route-flipped shadow sidecar all hit warm programs —
        plus the pad step between rungs and the pool tier's
        first-admission shapes. Returns seconds spent."""
        t0 = time.perf_counter()
        noop = noop_tree_commit(self.width)
        dummy_prev = None
        for rung in BucketLadder.capacity_rungs(
                self.capacity, self.max_capacity):
            table = make_tree_table(self.max_docs, rung,
                                    ring=self.ring, atoms=self.width)
            for bucket in self.ladder.window_buckets(max_bucket):
                program = pack_tree_window(
                    self.max_docs, {0: [noop]}, self.ladder,
                    bucket_floor=bucket, width=self.width)
                for route in TREE_EXECUTOR_ROUTES:
                    # each shape needs BOTH input signatures (the
                    # merge pool's prewarm rule): a fresh
                    # make_tree_table and a table that came out of a
                    # dispatch, which carries the committed output
                    # sharding — a distinct jit signature every
                    # steady-state round after the first one uses
                    out = apply_tree_window(table, program, route)
                    out = apply_tree_window(out, program, route)
                table = out
            if dummy_prev is not None:
                pad_tree_capacity(dummy_prev, rung)
            dummy_prev = table
        if self._pool is not None:
            self._warm_pool()
        np.asarray(table.count)  # force completion
        return time.perf_counter() - t0

    def _warm_pool(self) -> None:
        """Walk the pool tier's dispatch programs (see
        ``TreeSeqPool.prewarm``) — reached through the attribute-held
        pool, the shapecheck.PREWARM_INDIRECT edge."""
        self._pool.prewarm()

    # ------------------------------------------------------------------
    # reads (service-side summarization / validation)

    def _slot(self, document_id: str, datastore_id: str,
              channel_id: str) -> int:
        return self._slots[(document_id, datastore_id, channel_id)]

    def nodes(self, document_id: str, datastore_id: str,
              channel_id: str) -> list:
        """The served root-field node list (every tier)."""
        self._settle()
        slot = self._slot(document_id, datastore_id, channel_id)
        if slot in self._host:
            content = self._host[slot].forest().content()
            return copy.deepcopy(content.get("root", []))
        if self._pool is not None and slot in self._pool.row_of:
            table, row = self._pool._table, self._pool.row_of[slot]
        else:
            table, row = self._table, slot
        return decode_tree_row(
            np.asarray(table.content)[row],
            np.asarray(table.value)[row],
            int(np.asarray(table.count)[row]),
            self._content_tables[slot], self._value_tables[slot],
        )

    def signature(self, document_id: str, datastore_id: str,
                  channel_id: str) -> str:
        """Canonical forest signature (the Forest.signature
        convention: sorted-key JSON over the served fields)."""
        nodes = self.nodes(document_id, datastore_id, channel_id)
        return json.dumps({"root": nodes}, sort_keys=True,
                          default=str)

    def host_mode_docs(self) -> int:
        return len(self._host)

    def pooled_docs(self) -> int:
        return len(self._pool.members) if self._pool else 0

    def overflowed(self) -> bool:
        self._settle()
        return bool(np.asarray(self._table.overflow).any())


class ChannelKindRouter:
    """Ingress-side channel-kind routing at the IChannelFactory
    boundary: subscribe once per document, watch the sequenced stream
    for attach ops, and feed each announced channel's stream to the
    sidecar serving its kind — ``sharedstring`` to the merge sidecar,
    ``sharedtree`` to the tree sidecar. A document's flat merge
    channels never traverse tree code (and vice versa); channels of
    other kinds stay unrouted."""

    KINDS = {"sharedstring": "merge", "sharedtree": "tree"}

    def __init__(self, merge=None, tree=None):
        self.merge = merge
        self.tree = tree
        # (document, datastore, channel) -> sidecar already routed
        self._routed: dict[tuple[str, str, str], object] = {}

    def subscribe(self, server, document_id: str) -> None:
        orderer = server.get_orderer(document_id)
        orderer.broadcaster.subscribe(
            f"kind-router-{id(self)}/{document_id}",
            lambda msg: self.route(document_id, msg),
        )

    def _sidecar_for(self, channel_type: str):
        plane = self.KINDS.get(channel_type)
        return self.merge if plane == "merge" else \
            self.tree if plane == "tree" else None

    def route(self, document_id: str, msg: SequencedMessage) -> None:
        envelope = msg.contents if isinstance(msg.contents, dict) \
            else {}
        if (
            msg.type == MessageType.OPERATION
            and envelope.get("kind") == "attach"
            and isinstance(envelope.get("contents"), dict)
        ):
            ctype = envelope["contents"].get("channelType")
            sidecar = self._sidecar_for(ctype)
            ds, ch = envelope.get("address"), envelope.get("channel")
            key = (document_id, ds, ch)
            if sidecar is not None and key not in self._routed:
                sidecar.track(document_id, ds, ch)
                self._routed[key] = sidecar
        # forward to every sidecar serving a channel of this document
        # (each sidecar's own ingest filters by address/channel and
        # runs the per-document dedupe guard)
        seen = []
        for (doc, _ds, _ch), sidecar in self._routed.items():
            if doc == document_id and sidecar not in seen:
                seen.append(sidecar)
                sidecar.ingest(document_id, msg)
