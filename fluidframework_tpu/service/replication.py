"""Replicated sequencer: op-log replication + leader failover — the
ordering plane survives host loss with zero client-visible downtime.

PR9 proved single-node crash-restart converges bit-identically, but a
dead host still blacked out every document it ordered until an
operator restarted it. This module replicates the sequencer's durable
op log to N follower nodes behind an explicit ack barrier and elects
a follower into the leader role when the leader's lease lapses — the
contract "On Coordinating Collaborative Objects" (arXiv 1007.5093)
frames: ONE total order per document, never re-issued, never forked,
across the handoff.

The three load-bearing pieces:

- **The ack barrier** — PR9's fsync-before-fanout extends to
  *fsync-AND-replicate-before-fanout*: ``ReplicatedOpLog`` makes the
  local fsynced append, then blocks in
  ``ReplicatedSequencerGroup.replicate_before_fanout`` until a QUORUM
  of nodes holds the op durably, and only then does the pipeline fan
  it out (scriptorium runs before the broadcaster, so the barrier
  sits exactly where PR9's fsync sat). An op any client was ever told
  about therefore survives the loss of any non-quorum subset of
  nodes; an op the quorum never accepted was never fanned out, and
  the submitting client still holds it pending (the PR9
  reconnect/resubmit path replays it — no new client machinery).

- **The epoch fence** — every leader writes under the epoch its lease
  acquisition minted (``EpochFence.advance``). A deposed leader that
  still *thinks* it holds the lease (the split-brain candidate: its
  renewal was lost, or the lease service hiccuped) is refused at the
  write seam: ``EpochFence.check`` raises ``FencedWriteError`` and
  counts ``sequencer_fenced_writes_total`` BEFORE anything could fan
  out, and every follower independently refuses stale epochs as the
  backstop (fencing tokens: the RESOURCE checks the token, not the
  leader's belief). The fluidlint rule ``qoscheck:fence-before-fanout``
  pins the ordering statically.

- **Promotion at exactly the replicated head** — failover flushes the
  candidate's buffered (lagging) tail, anti-entropies any missing
  suffix from every surviving peer (any fanned-out op is on at least
  one surviving follower's contiguous prefix, because quorum heads
  imply contiguous prefixes), then boots a fresh
  ``ReplicatedLocalServer`` over the candidate's directory: the
  orderer fast-forwards the sequencer to the log head and ticketing
  resumes at exactly seq+1. Buffered ops still gapped after
  anti-entropy were never quorum-durable — dropped; their submitters
  resubmit.

Layout: ``<root>/node-0`` is the initial leader's durable dir (a
normal ``DocumentStorage`` tree per document); each follower keeps
the SAME ``<node>/<doc>/ops.jsonl`` layout, which is what makes
promotion "build a LocalServer over the follower's dir" instead of a
data migration.

Chaos seams (docs/ROBUSTNESS.md): ``repl.lag`` (a follower defers
durability — replication lag), ``repl.append_ack`` (a follower's ack
is lost / errors), ``repl.lease_expire`` (renewal dropped, or the
lease service lapses the grant NOW — the split-brain trigger),
``repl.promote`` (a transient election failure, retried).
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Optional

from ..obs import metrics as obs_metrics
from ..obs.trace import stamp as _stamp
from ..protocol.messages import SequencedMessage
from ..protocol.serialization import message_from_json, message_to_json
from ..qos.faults import (
    KIND_DEFER,
    KIND_DROP,
    KIND_ERROR,
    PLANE,
)
from .local_orderer import LocalOrderer
from .local_server import LocalServer
from .storage import DocumentStorage, FileOpLog, atomic_write, \
    read_jsonl_tolerant

# chaos seams (one schedule drives the document plane and the
# partitioned-queue counterpart in partitioning.py — shared names,
# exactly like socket.frame_in/out across harnesses)
_SITE_LAG = PLANE.site("repl.lag", (KIND_DEFER,))
_SITE_ACK = PLANE.site("repl.append_ack", (KIND_DROP, KIND_ERROR))
_SITE_LEASE = PLANE.site("repl.lease_expire", (KIND_DROP, KIND_ERROR))
# error only: a deferred election would be indistinguishable from a
# slightly-later failover call on the step clock — a kind the code
# never acts on is exactly the vacuous vocabulary the sweep guard
# exists to forbid
_SITE_PROMOTE = PLANE.site("repl.promote", (KIND_ERROR,))

def _group_metrics(registry: obs_metrics.MetricsRegistry) -> dict:
    """Register (or fetch) the replication families on ``registry``.

    PR13 made every replication metric holder INJECTABLE: in-process
    multi-node harnesses (chaos, test_replication) give the leader
    and each follower their own registry so per-node series never
    double-count into one process aggregate, and
    ``obs.federation.FederatedView`` merges them back into the fleet
    view. Default (registry=None at every ctor) stays the
    process-wide REGISTRY — production topology is one node per
    process, unchanged. Names stay literals HERE so fluidlint's
    slo-unbound-objective collection sees them statically."""
    return {
        "followers": registry.gauge(
            "repl_followers", "follower replicas behind the leader",
            labelnames=("partition",)),
        "lag": registry.gauge(
            "repl_lag_ops",
            "worst follower replication lag at the last append (ops)"),
        "failovers": registry.counter(
            "sequencer_failovers_total",
            "follower promotions into the leader role"),
        "anti_entropy": registry.counter(
            "repl_anti_entropy_ops_total",
            "ops applied via anti-entropy catch-up and promotion "
            "suffix pulls"),
    }


def _fence_metrics(registry: obs_metrics.MetricsRegistry) -> dict:
    return {
        "epoch": registry.gauge(
            "repl_epoch", "current sequencer leadership epoch"),
        "fenced": registry.counter(
            "sequencer_fenced_writes_total",
            "writes refused by the epoch fence (deposed-leader "
            "attempts)"),
    }


def _note(timeline, kind: str, node: str = "", **fields) -> None:
    """Record a fleet-timeline event when a timeline is attached
    (obs/timeline.py); replication runs timeline-less by default."""
    if timeline is not None:
        timeline.record(kind, node=node, **fields)


class FencedWriteError(RuntimeError):
    """A write carried a stale leadership epoch: the writer was
    deposed. Refusing it here (BEFORE fan-out) is what makes a
    split-brain candidate harmless — the op was never sequenced as
    far as any client can observe, so the submitter resubmits it to
    the real leader."""


class LeaseHeldError(RuntimeError):
    """Acquisition attempted while a live (unexpired) lease is held
    by another node."""


class EpochFence:
    """The monotone leadership epoch and THE check every replicated
    write makes before anything can fan out. ``advance()`` is called
    only by lease acquisition — one epoch per leadership term."""

    def __init__(self, epoch: int = 0, registry=None, timeline=None):
        self.epoch = epoch
        self.timeline = timeline
        m = _fence_metrics(registry or obs_metrics.REGISTRY)
        self._g_epoch = m["epoch"]
        self._c_fenced = m["fenced"]

    def advance(self) -> int:
        self.epoch += 1
        self._g_epoch.set(self.epoch)
        _note(self.timeline, "epoch_advance", epoch=self.epoch)
        return self.epoch

    def check(self, epoch: int, **context) -> None:
        if epoch != self.epoch:
            self._c_fenced.inc()
            _note(self.timeline, "fenced_write", epoch=epoch,
                  current=self.epoch,
                  **{k: v for k, v in context.items()
                     if isinstance(v, (int, float, str, bool))})
            raise FencedWriteError(
                f"epoch fence: write under epoch {epoch} refused, "
                f"current epoch is {self.epoch} ({context}) — the "
                "writer was deposed; the op stays with its submitter "
                "and resubmits to the current leader")


class SequencerLease:
    """The lease seam: leadership is a TTL'd grant renewed on the
    replication heartbeat. Clock-injectable (the chaos harness drives
    it on the step clock), so lease expiry — and therefore failover
    timing — is deterministic. Acquisition advances the epoch fence;
    renewal consults the ``repl.lease_expire`` chaos site, whose
    faults model the two real-world lease failure shapes: a renewal
    lost in transit (``drop`` — the TTL keeps running) and the lease
    service lapsing the grant NOW without telling the holder
    (``error`` — the split-brain trigger)."""

    def __init__(self, fence: EpochFence, ttl: float = 0.3,
                 clock=None, timeline=None):
        self.fence = fence
        self.ttl = ttl
        self.clock = clock or time.monotonic
        self.timeline = timeline
        self.holder: Optional[str] = None
        self.expires_at = float("-inf")

    @property
    def epoch(self) -> int:
        return self.fence.epoch

    def expired(self) -> bool:
        return self.clock() >= self.expires_at

    def acquire(self, node_id: str) -> int:
        if self.holder not in (None, node_id) and not self.expired():
            raise LeaseHeldError(
                f"lease held by {self.holder!r} for another "
                f"{self.expires_at - self.clock():.3f}s")
        self.holder = node_id
        self.expires_at = self.clock() + self.ttl
        _note(self.timeline, "lease_grant", node=node_id,
              ttl=self.ttl)
        return self.fence.advance()

    def renew(self, node_id: str, epoch: int) -> bool:
        if node_id != self.holder or epoch != self.fence.epoch:
            return False  # deposed caller: the grant moved on
        fault = _SITE_LEASE.fire(holder=node_id)
        if fault == KIND_DROP:
            return False  # renewal lost in transit; TTL keeps running
        if fault == KIND_ERROR:
            # lease-service hiccup: the grant lapses NOW and the
            # holder is NOT told — it keeps writing until the epoch
            # fence refuses it (the split-brain candidate the
            # deposed-race chaos mode exercises)
            self.expires_at = self.clock()
            _note(self.timeline, "lease_expire", node=node_id,
                  origin="fault")
            return False
        self.expires_at = self.clock() + self.ttl
        _note(self.timeline, "lease_renew", node=node_id)
        return True

    def force_expire(self, reason: str = "forced") -> None:
        """Harness-driven lapse (the deposed-race schedule), recorded
        through the plane like any crash-time forced state."""
        _SITE_LEASE.force(KIND_ERROR, reason=reason)
        self.expires_at = self.clock()
        _note(self.timeline, "lease_expire",
              node=self.holder or "", origin="forced", reason=reason)


class FollowerReplica:
    """One follower sequencer node: a durable, per-document,
    contiguous copy of the leader's op log, in EXACTLY the layout a
    ``LocalServer`` durable dir uses (``<root>/<doc>/ops.jsonl``) —
    so promotion is "boot a server over this directory", not a data
    migration. Appends fsync before acking (the follower's half of
    the ack barrier); a deferred (lagging) append is buffered
    in-memory and acked only once durable."""

    def __init__(self, root: str, node_id: str, registry=None,
                 timeline=None, stamp_ts=None):
        self.root = root
        self.node_id = node_id
        # the follower's OWN registry (satellite fix: follower series
        # used to alias the process-wide REGISTRY, double-counting
        # leader + follower into one registry in in-process multi-node
        # tests); default None keeps the process-wide aggregate —
        # production runs one node per process
        self._c_fenced = _fence_metrics(
            registry or obs_metrics.REGISTRY)["fenced"]
        self.timeline = timeline
        # timestamp source for the repl:follower_append hop stamp:
        # None = stamp()'s wall default; the group passes its injected
        # clock through so recorded corpora stay byte-stable per seed
        self._stamp_ts = stamp_ts
        os.makedirs(root, exist_ok=True)
        self.max_epoch_seen = 0
        self._heads: dict[str, int] = {}
        self._fhs: dict[str, Any] = {}
        self._lag: dict[str, list[SequencedMessage]] = {}
        # resume replicated heads from disk (a follower surviving its
        # own restart) — torn tails tolerated exactly like the
        # leader's log: the torn op never acked, so discarding it is
        # exact
        for doc in sorted(os.listdir(root)):
            path = self._log_path(doc)
            if not os.path.isfile(path):
                continue
            rows, torn = read_jsonl_tolerant(path, "repl")
            if torn:
                atomic_write(path, "".join(
                    json.dumps(r) + "\n" for r in rows))
            if rows:
                self._heads[doc] = rows[-1]["sequenceNumber"]

    def _log_path(self, doc: str) -> str:
        return os.path.join(self.root, doc, "ops.jsonl")

    def _fh(self, doc: str):
        fh = self._fhs.get(doc)
        if fh is None:
            os.makedirs(os.path.join(self.root, doc), exist_ok=True)
            fh = open(self._log_path(doc), "a")
            self._fhs[doc] = fh
        return fh

    # -- state ----------------------------------------------------------

    def documents(self) -> list[str]:
        return sorted(set(self._heads) | set(self._lag))

    def head(self, doc: str) -> int:
        """Last DURABLY replicated seq for ``doc`` (0 = none)."""
        return self._heads.get(doc, 0)

    def total_head(self) -> int:
        return sum(self._heads.values())

    def lag_depth(self) -> int:
        return sum(len(v) for v in self._lag.values())

    # -- the replication stream ----------------------------------------

    def _check_epoch(self, epoch: int, doc: str) -> None:
        if epoch < self.max_epoch_seen:
            self._c_fenced.inc()
            _note(self.timeline, "fenced_write", node=self.node_id,
                  epoch=epoch, current=self.max_epoch_seen, doc=doc)
            raise FencedWriteError(
                f"follower {self.node_id}: append under epoch "
                f"{epoch} refused (seen {self.max_epoch_seen}, "
                f"doc {doc!r}) — fencing-token backstop")
        self.max_epoch_seen = epoch

    def note_epoch(self, epoch: int) -> None:
        """A new leader's first contact: stale-epoch writes from the
        deposed leader are refused from here on."""
        self.max_epoch_seen = max(self.max_epoch_seen, epoch)

    def buffer_lag(self, doc: str, epoch: int,
                   msg: SequencedMessage) -> None:
        """Replication lag: the op arrived but is NOT yet durable —
        no ack. ``flush_lag`` makes the contiguous prefix durable."""
        self._check_epoch(epoch, doc)
        self._lag.setdefault(doc, []).append(msg)

    def append_durable(self, doc: str, epoch: int,
                       msg: SequencedMessage) -> None:
        self._check_epoch(epoch, doc)
        self._append_raw(doc, msg)

    def _append_raw(self, doc: str, msg: SequencedMessage) -> None:
        assert msg.sequence_number == self.head(doc) + 1, (
            f"follower {self.node_id} log must stay contiguous: "
            f"append seq {msg.sequence_number} onto head "
            f"{self.head(doc)} (doc {doc!r})")
        # the cross-node hop: this follower holds the op durably (one
        # stamp per follower that appends — catch-up/anti-entropy
        # appends stamp too, honestly dating when the copy landed)
        _stamp(msg.traces, "repl", "follower_append",
               timestamp=self._stamp_ts() if self._stamp_ts else None)
        fh = self._fh(doc)
        fh.write(json.dumps(message_to_json(msg)) + "\n")
        fh.flush()
        os.fsync(fh.fileno())  # durable BEFORE the ack counts
        self._heads[doc] = msg.sequence_number

    def flush_lag(self, doc: Optional[str] = None) -> int:
        """Durably apply the buffered tail's CONTIGUOUS prefix;
        anything gapped (an earlier op was dropped in transit) stays
        buffered until catch-up supplies the middle. Returns ops
        applied."""
        applied = 0
        for d in ([doc] if doc is not None else list(self._lag)):
            pending = sorted(self._lag.get(d, []),
                             key=lambda m: m.sequence_number)
            keep: list[SequencedMessage] = []
            for msg in pending:
                if msg.sequence_number <= self.head(d):
                    continue  # catch-up already supplied it
                if msg.sequence_number == self.head(d) + 1:
                    self._append_raw(d, msg)
                    applied += 1
                else:
                    keep.append(msg)
            if keep:
                self._lag[d] = keep
            else:
                self._lag.pop(d, None)
        return applied

    def drop_lag(self) -> int:
        """Discard buffered ops still gapped after anti-entropy: no
        surviving node holds the middle, so they were never
        quorum-durable — never fanned out — and their submitters
        still hold them pending. Returns ops dropped."""
        dropped = self.lag_depth()
        self._lag.clear()
        return dropped

    def sync_from(self, doc: str, msgs: list[SequencedMessage]) -> int:
        """Anti-entropy: apply a peer/leader-supplied range (ops at or
        below our head are skipped — at-least-once safe)."""
        applied = 0
        for msg in msgs:
            if msg.sequence_number <= self.head(doc):
                continue
            self._append_raw(doc, msg)
            applied += 1
        return applied

    def read_log(self, doc: str,
                 from_seq: int = 0) -> list[SequencedMessage]:
        """Ops with seq > from_seq from the durable replica log."""
        path = self._log_path(doc)
        if not os.path.isfile(path):
            return []
        rows, _ = read_jsonl_tolerant(path, "repl")
        return [message_from_json(r) for r in rows
                if r["sequenceNumber"] > from_seq]

    def close(self) -> None:
        for fh in self._fhs.values():
            fh.close()
        self._fhs.clear()


class ReplicatedOpLog(FileOpLog):
    """The leader's per-document op log under the extended ack
    barrier: fence check, local fsynced append (PR9's barrier), then
    BLOCK until a quorum of followers holds the op durably — all
    before ``OpLog.append`` returns to scriptorium, which runs before
    the broadcaster, so nothing fans out un-replicated."""

    def __init__(self, path: str, group: "ReplicatedSequencerGroup",
                 document_id: str, epoch: int):
        self._group = group
        self._doc = document_id
        self._epoch = epoch
        super().__init__(path)

    def _persist_append(self, msg: SequencedMessage) -> None:
        try:
            self._group.fence.check(self._epoch, doc=self._doc,
                                    op="append")
        except FencedWriteError:
            # OpLog.append adds to the in-memory list BEFORE
            # persisting: the refused op must not linger there either,
            # or a deposed leader's read path would serve an op the
            # quorum never accepted
            self._ops.pop()
            raise
        _stamp(msg.traces, "repl", "fence_check",
               timestamp=self._group._trace_ts())
        super()._persist_append(msg)  # local fsync (the PR9 barrier)
        self._group.replicate_before_fanout(
            self._doc, self._epoch, msg, self)

    def truncate_below(self, seq: int) -> int:
        # summary truncation must never outrun a laggard: this log is
        # every follower's catch-up source, and dropping records a
        # follower still needs would turn its next catch-up into an
        # unfillable gap
        return super().truncate_below(
            min(seq, self._group.replication_floor(self._doc)))


class ReplicatedDocumentStorage(DocumentStorage):
    """DocumentStorage whose op log is a :class:`ReplicatedOpLog`
    (summaries and checkpoints stay node-local: the replicated log is
    the recovery truth, and a promoted follower rebuilds everything
    else from it)."""

    def __init__(self, root: str, group: "ReplicatedSequencerGroup",
                 document_id: str, epoch: int):
        self._group = group
        self._document_id = document_id
        self._epoch = epoch
        super().__init__(root)

    def _make_op_log(self, path: str) -> FileOpLog:
        return ReplicatedOpLog(path, self._group,
                               self._document_id, self._epoch)


class ReplicatedLocalServer(LocalServer):
    """The LocalServer surface over the replicated plane: per-document
    orderers write through :class:`ReplicatedOpLog`, submits are
    fence-checked BEFORE ticketing (a deposed leader must not even
    consume sequence numbers), and the read path serves only
    quorum-COMMITTED ops — the window where an op is leader-durable
    but not yet quorum-durable is never client-visible."""

    def __init__(self, group: "ReplicatedSequencerGroup",
                 durable_dir: str, **kwargs):
        super().__init__(durable_dir=durable_dir, **kwargs)
        self.group = group
        self.epoch = group.fence.epoch

    def _make_storage(self, document_id: str):
        return ReplicatedDocumentStorage(
            os.path.join(self.durable_dir, document_id),
            self.group, document_id, self.epoch)

    def _make_orderer(self, document_id: str) -> LocalOrderer:
        return LocalOrderer(
            document_id, storage=self._make_storage(document_id),
            storage_breaker=self.storage_breaker,
            checkpoint_every=self.checkpoint_every,
            write_fence=self._fence_check_for(document_id),
            clock=self.clock,
        )

    def _fence_check_for(self, document_id: str):
        def check(op: str = "write") -> None:
            self.group.fence.check(self.epoch, doc=document_id,
                                   op=op)
        return check

    def read_ops(self, document_id: str, from_seq: int,
                 to_seq: Optional[int] = None):
        # a deposed server must not serve reads either: its in-memory
        # state may disagree with the order the new leader is minting
        self.group.fence.check(self.epoch, doc=document_id, op="read")
        committed = self.group.committed(document_id)
        to = committed if to_seq is None else min(to_seq, committed)
        return super().read_ops(document_id, from_seq, to)


class ReplicatedSequencerGroup:
    """Leader + N follower sequencer nodes for one ordering scope.

    The group owns the lease, the epoch fence, the follower set and
    the committed watermark; the current leader's
    :class:`ReplicatedLocalServer` is ``group.server`` (callers front
    it with an AlfredServer exactly like a plain LocalServer — after
    a failover they front the NEW ``group.server`` and clients ride
    the PR9 reconnect/resubmit path through the handoff)."""

    def __init__(self, root: str, n_followers: int = 2,
                 quorum: Optional[int] = None, clock=None,
                 lease_ttl: float = 0.3, scope: str = "docs",
                 server_kwargs: Optional[dict] = None,
                 registry=None, follower_registries=None,
                 timeline=None):
        if n_followers < 1:
            raise ValueError(
                "a replicated sequencer needs at least one follower "
                "(n_followers >= 1), or host loss loses acked ops")
        if follower_registries is not None and \
                len(follower_registries) != n_followers:
            raise ValueError(
                f"{len(follower_registries)} follower registries for "
                f"{n_followers} followers")
        self.root = root
        self.scope = scope
        # timestamps for the repl hop stamps follow the clock ONLY
        # when one was injected: the default group clock is
        # time.monotonic (lease arithmetic), and monotonic stamps
        # must never mix into wall-clock hop tables
        self._injected_clock = clock is not None
        self.clock = clock or time.monotonic
        self.registry = registry or obs_metrics.REGISTRY
        self.timeline = timeline
        self.metrics = _group_metrics(self.registry)
        self.fence = EpochFence(registry=self.registry,
                                timeline=timeline)
        self.lease = SequencerLease(self.fence, ttl=lease_ttl,
                                    clock=self.clock,
                                    timeline=timeline)
        self.followers = [
            FollowerReplica(
                os.path.join(root, f"node-{i}"), f"node-{i}",
                registry=(follower_registries[i - 1]
                          if follower_registries else None),
                timeline=timeline, stamp_ts=self._trace_ts,
            )
            for i in range(1, n_followers + 1)
        ]
        # quorum over ALL nodes (leader included); default = a strict
        # majority of the initial group ((total // 2) + 1 — for even
        # group sizes too: 4 nodes need 3, or losing a minority could
        # lose a client-acked op), floored at 2 so at least one
        # follower always holds every fanned-out op
        self.quorum = quorum if quorum is not None else max(
            2, (n_followers + 1) // 2 + 1)
        if self.quorum > 1 + n_followers:
            raise ValueError(
                f"quorum {self.quorum} unsatisfiable with "
                f"{n_followers} followers")
        self.server_kwargs = dict(server_kwargs or {})
        self._committed: dict[str, int] = {}
        self.max_lag_observed = 0
        self.leader_id = "node-0"
        self.epoch = self.lease.acquire(self.leader_id)
        self.server = self._build_server(
            os.path.join(root, "node-0"))
        self.metrics["followers"].labels(partition=self.scope).set(
            len(self.followers))

    def _build_server(self, durable_dir: str) -> ReplicatedLocalServer:
        return ReplicatedLocalServer(self, durable_dir,
                                     **self.server_kwargs)

    def _trace_ts(self) -> Optional[float]:
        """Timestamp for repl hop stamps: the injected clock when one
        exists (byte-stable recorded corpora per seed), else None —
        stamp()'s wall default."""
        return self.clock() if self._injected_clock else None

    # -- committed watermark -------------------------------------------

    def committed(self, doc: str) -> int:
        """Highest quorum-durable seq for ``doc`` — the only ops the
        read path may serve (Raft's commitIndex shape)."""
        return self._committed.get(doc, 0)

    def replication_floor(self, doc: str) -> int:
        """Lowest follower head: truncation must stay below nothing a
        laggard still needs from the leader's log."""
        return min(f.head(doc) for f in self.followers) \
            if self.followers else self.committed(doc)

    # -- the ack barrier ------------------------------------------------

    def replicate_before_fanout(self, doc: str, epoch: int,
                                msg: SequencedMessage,
                                source_log) -> None:
        """Block until ``msg`` is durable on a quorum. Callers check
        the epoch fence FIRST (qoscheck:fence-before-fanout pins the
        ordering statically). Follower faults are absorbed — the
        quorum is the contract, not any single ack: a lagging or
        unreachable follower simply doesn't count, and when the
        prompt acks fall short the barrier force-syncs laggards in
        deterministic order (the leader genuinely WAITS on its
        quorum, exactly what an ack barrier means)."""
        seq = msg.sequence_number
        # the hop pair around the quorum barrier: forward marks the
        # leader offering the op to its followers, quorum_ack marks
        # the barrier satisfied — so the quorum wait is its OWN hop
        # in op_breakdown()/OTLP instead of silently inflating the
        # sequencer-ticket hop (the ledger bridge feeds
        # repl_quorum_wait_ms from exactly this pair)
        _stamp(msg.traces, "repl", "forward",
               timestamp=self._trace_ts())
        acked = 1  # the leader's own fsynced append
        for f in self.followers:
            if self._offer(f, doc, epoch, msg, source_log):
                acked += 1
        # leadership heartbeat piggybacks on replication traffic
        self.lease.renew(self.leader_id, epoch)
        if acked < self.quorum:
            for f in self.followers:
                if acked >= self.quorum:
                    break
                if f.head(doc) >= seq:
                    continue
                self._force_sync(f, doc, epoch, msg, source_log)
                acked += 1
        heads = sorted([seq] + [f.head(doc) for f in self.followers],
                       reverse=True)
        self._committed[doc] = max(self.committed(doc),
                                   heads[self.quorum - 1])
        _stamp(msg.traces, "repl", "quorum_ack",
               timestamp=self._trace_ts())
        lag = max((seq - f.head(doc) for f in self.followers),
                  default=0)
        self.metrics["lag"].set(lag)
        self.max_lag_observed = max(self.max_lag_observed, lag)

    def _offer(self, f: FollowerReplica, doc: str, epoch: int,
               msg: SequencedMessage, source_log) -> bool:
        """One replication attempt to one follower; True = durable
        ack. ``defer`` buffers (replication lag); a dropped/erroring
        ack is retried once (the broker-append idiom), then the
        follower just misses this round — catch-up repairs it on the
        next offer or at promotion."""
        seq = msg.sequence_number
        if _SITE_LAG.fire(follower=f.node_id, doc=doc,
                          seq=seq) == KIND_DEFER:
            f.buffer_lag(doc, epoch, msg)
            return False
        fault = _SITE_ACK.fire(follower=f.node_id, doc=doc, seq=seq)
        if fault is not None:
            fault = _SITE_ACK.fire(follower=f.node_id, doc=doc,
                                   seq=seq, retry=True)
            if fault is not None:
                return False
        self._catch_up(f, doc, seq - 1, source_log)
        f.append_durable(doc, epoch, msg)
        return True

    def _catch_up(self, f: FollowerReplica, doc: str, upto: int,
                  source_log) -> None:
        f.flush_lag(doc)
        if f.head(doc) < upto:
            applied = f.sync_from(
                doc, source_log.read(f.head(doc), upto))
            if applied:
                self.metrics["anti_entropy"].inc(applied)

    def _force_sync(self, f: FollowerReplica, doc: str, epoch: int,
                    msg: SequencedMessage, source_log) -> None:
        """The blocking path: quorum shortfall makes the leader WAIT
        on this follower — flush its buffer, supply any missing
        middle from the leader's log, land the op. No chaos sites
        fire here: the faults already fired (and were recorded) on
        the offer; this is the barrier waiting them out."""
        self._catch_up(f, doc, msg.sequence_number - 1, source_log)
        if f.head(doc) >= msg.sequence_number:
            return  # the flushed buffer already contained it
        f.append_durable(doc, epoch, msg)

    # -- failover -------------------------------------------------------

    def kill_leader(self):
        """Host loss: the leader process is simply gone — nothing
        graceful happens; the lease stops being renewed and lapses on
        its TTL. Returns the dead server object (harnesses keep it to
        model the deposed-leader race)."""
        dead = self.server
        self.server = None
        return dead

    def laggiest_follower(self) -> FollowerReplica:
        return min(self.followers, key=lambda f: f.total_head())

    def failover(self, candidate: Optional[FollowerReplica] = None
                 ) -> ReplicatedLocalServer:
        """Elect ``candidate`` (default: the best-replicated
        follower) into the leader role. Refuses while a live lease is
        held — failover is lease-driven, never a second writer."""
        if not self.lease.expired():
            raise LeaseHeldError(
                f"lease held by {self.lease.holder!r}; failover "
                "requires the lease to lapse first")
        # the election OBSERVES the lapse — the failover timeline's
        # detection-phase boundary (obs/timeline.py failover_phases)
        _note(self.timeline, "lease_expire",
              node=self.lease.holder or "", origin="observed")
        if not self.followers:
            raise RuntimeError("no followers left to promote")
        if candidate is None:
            # max() keeps the FIRST maximum: deterministic low-index
            # tie-break
            candidate = max(self.followers,
                            key=lambda f: f.total_head())
        fault = _SITE_PROMOTE.fire(node=candidate.node_id)
        if fault == KIND_ERROR:
            # transient election failure: the retry is exact (nothing
            # was promoted); a second injected fault is absorbed the
            # same way — promotion is idempotent until acquire()
            _SITE_PROMOTE.fire(node=candidate.node_id, retry=True)
        return self._promote(candidate)

    def _promote(self, candidate: FollowerReplica
                 ) -> ReplicatedLocalServer:
        # 1) the candidate's own received-but-buffered tail
        candidate.flush_lag()
        # 2) anti-entropy from every surviving peer: any fanned-out op
        # is durable on >= quorum-1 followers, so at least one
        # surviving peer holds it in its contiguous prefix
        for peer in self.followers:
            if peer is candidate:
                continue
            for doc in peer.documents():
                if peer.head(doc) > candidate.head(doc):
                    applied = candidate.sync_from(
                        doc, peer.read_log(doc, candidate.head(doc)))
                    if applied:
                        self.metrics["anti_entropy"].inc(applied)
                        _note(self.timeline, "anti_entropy",
                              node=candidate.node_id,
                              source=peer.node_id, doc=doc,
                              ops=applied)
        candidate.flush_lag()
        candidate.drop_lag()
        # 3) mint the new epoch and fence everyone else out
        self.epoch = self.lease.acquire(candidate.node_id)
        self.leader_id = candidate.node_id
        self.followers = [f for f in self.followers
                          if f is not candidate]
        for f in self.followers:
            f.note_epoch(self.epoch)
        self.quorum = min(self.quorum, 1 + len(self.followers))
        self._committed = {doc: candidate.head(doc)
                           for doc in candidate.documents()}
        # 4) the follower's dir BECOMES the leader's durable dir: the
        # orderer boot path fast-forwards each sequencer to its log
        # head, so ticketing resumes at exactly the replicated head
        candidate.close()
        self.server = self._build_server(candidate.root)
        self.metrics["failovers"].inc()
        self.metrics["followers"].labels(partition=self.scope).set(
            len(self.followers))
        _note(self.timeline, "promotion", node=self.leader_id,
              epoch=self.epoch,
              followers_left=len(self.followers))
        return self.server
