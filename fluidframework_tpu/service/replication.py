"""Replicated sequencer: op-log replication + leader failover — the
ordering plane survives host loss with zero client-visible downtime.

PR9 proved single-node crash-restart converges bit-identically, but a
dead host still blacked out every document it ordered until an
operator restarted it. This module replicates the sequencer's durable
op log to N follower nodes behind an explicit ack barrier and elects
a follower into the leader role when the leader's lease lapses — the
contract "On Coordinating Collaborative Objects" (arXiv 1007.5093)
frames: ONE total order per document, never re-issued, never forked,
across the handoff.

The three load-bearing pieces:

- **The ack barrier** — PR9's fsync-before-fanout extends to
  *fsync-AND-replicate-before-fanout*: ``ReplicatedOpLog`` makes the
  local fsynced append, then blocks in
  ``ReplicatedSequencerGroup.replicate_before_fanout`` until a QUORUM
  of nodes holds the op durably, and only then does the pipeline fan
  it out (scriptorium runs before the broadcaster, so the barrier
  sits exactly where PR9's fsync sat). An op any client was ever told
  about therefore survives the loss of any non-quorum subset of
  nodes; an op the quorum never accepted was never fanned out, and
  the submitting client still holds it pending (the PR9
  reconnect/resubmit path replays it — no new client machinery).

- **The epoch fence** — every leader writes under the epoch its lease
  acquisition minted (``EpochFence.advance``). A deposed leader that
  still *thinks* it holds the lease (the split-brain candidate: its
  renewal was lost, or the lease service hiccuped) is refused at the
  write seam: ``EpochFence.check`` raises ``FencedWriteError`` and
  counts ``sequencer_fenced_writes_total`` BEFORE anything could fan
  out, and every follower independently refuses stale epochs as the
  backstop (fencing tokens: the RESOURCE checks the token, not the
  leader's belief). The fluidlint rule ``qoscheck:fence-before-fanout``
  pins the ordering statically.

- **Promotion at exactly the replicated head** — failover flushes the
  candidate's buffered (lagging) tail, anti-entropies any missing
  suffix from every surviving peer (any fanned-out op is on at least
  one surviving follower's contiguous prefix, because quorum heads
  imply contiguous prefixes), then boots a fresh
  ``ReplicatedLocalServer`` over the candidate's directory: the
  orderer fast-forwards the sequencer to the log head and ticketing
  resumes at exactly seq+1. Buffered ops still gapped after
  anti-entropy were never quorum-durable — dropped; their submitters
  resubmit.

Layout: ``<root>/node-0`` is the initial leader's durable dir (a
normal ``DocumentStorage`` tree per document); each follower keeps
the SAME ``<node>/<doc>/ops.jsonl`` layout, which is what makes
promotion "build a LocalServer over the follower's dir" instead of a
data migration.

Chaos seams (docs/ROBUSTNESS.md): ``repl.lag`` (a follower defers
durability — replication lag), ``repl.append_ack`` (a follower's ack
is lost / errors), ``repl.lease_expire`` (renewal dropped, or the
lease service lapses the grant NOW — the split-brain trigger),
``repl.promote`` (a transient election failure, retried).
"""
from __future__ import annotations

import os
import time
from typing import Any, Optional

from ..obs import metrics as obs_metrics
from ..obs.trace import stamp as _stamp
from ..protocol.messages import SequencedMessage
from ..protocol.serialization import message_from_json, message_to_json
from ..qos.faults import (
    KIND_DEFER,
    KIND_DROP,
    KIND_ERROR,
    KIND_HEAL,
    KIND_PARTITION,
    PLANE,
)
from .local_orderer import LocalOrderer
from .local_server import LocalServer
from .storage import DocumentStorage, FileOpLog, atomic_write, \
    jsonl_record, read_jsonl_tolerant, scrub_repair_jsonl

# chaos seams (one schedule drives the document plane and the
# partitioned-queue counterpart in partitioning.py — shared names,
# exactly like socket.frame_in/out across harnesses)
_SITE_LAG = PLANE.site("repl.lag", (KIND_DEFER,))
_SITE_ACK = PLANE.site("repl.append_ack", (KIND_DROP, KIND_ERROR))
_SITE_LEASE = PLANE.site("repl.lease_expire", (KIND_DROP, KIND_ERROR))
# error only: a deferred election would be indistinguishable from a
# slightly-later failover call on the step clock — a kind the code
# never acts on is exactly the vacuous vocabulary the sweep guard
# exists to forbid
_SITE_PROMOTE = PLANE.site("repl.promote", (KIND_ERROR,))
# netsplit topology transitions: force()d by NetworkTopology when the
# harness applies/heals a partition, so PLANE.fired stays the one
# replayable log of everything that happened to the run (the torn-
# state idiom: a topology change is a harness decision, not a draw)
_SITE_PARTITION = PLANE.site("repl.partition", (KIND_PARTITION,))
_SITE_HEAL = PLANE.site("repl.heal", (KIND_HEAL,))

def _group_metrics(registry: obs_metrics.MetricsRegistry) -> dict:
    """Register (or fetch) the replication families on ``registry``.

    PR13 made every replication metric holder INJECTABLE: in-process
    multi-node harnesses (chaos, test_replication) give the leader
    and each follower their own registry so per-node series never
    double-count into one process aggregate, and
    ``obs.federation.FederatedView`` merges them back into the fleet
    view. Default (registry=None at every ctor) stays the
    process-wide REGISTRY — production topology is one node per
    process, unchanged. Names stay literals HERE so fluidlint's
    slo-unbound-objective collection sees them statically."""
    return {
        "followers": registry.gauge(
            "repl_followers", "follower replicas behind the leader",
            labelnames=("partition",)),
        "lag": registry.gauge(
            "repl_lag_ops",
            "worst follower replication lag at the last append (ops)"),
        "failovers": registry.counter(
            "sequencer_failovers_total",
            "follower promotions into the leader role"),
        "anti_entropy": registry.counter(
            "repl_anti_entropy_ops_total",
            "ops applied via anti-entropy catch-up and promotion "
            "suffix pulls"),
        # partition-tolerance plane (quorum-loss degraded mode,
        # follower lifecycle) — docs/OBSERVABILITY.md
        "degraded": registry.gauge(
            "repl_degraded",
            "1 while the leader is in quorum-loss degraded mode "
            "(writes nack retriable-unavailable; reads clamp at the "
            "committed watermark)"),
        "degraded_s": registry.counter(
            "repl_degraded_seconds_total",
            "cumulative seconds spent in degraded mode (accumulated "
            "at degraded_exit, on the group clock)"),
        "unavailable": registry.counter(
            "repl_unavailable_nacks_total",
            "writes refused with the retriable unavailable nack "
            "while quorum/lease was unprovable"),
        "rejoins": registry.counter(
            "repl_rejoin_total",
            "followers rejoined via full anti-entropy resync behind "
            "the epoch fence"),
        # fault-to-signal plane: the two transient-fault absorb
        # points in _offer_one each leave a visible mark, so a chaos
        # injection at repl.lag / repl.append_ack is never silent
        "lag_deferrals": registry.counter(
            "repl_lag_deferrals_total",
            "offers absorbed into the follower lag buffer instead "
            "of acking durably (replication lag deferral)"),
        "ack_retries": registry.counter(
            "repl_ack_retries_total",
            "transiently-failed follower ack offers retried once "
            "(second failure skips the round; anti-entropy repairs)"),
    }


def _fence_metrics(registry: obs_metrics.MetricsRegistry) -> dict:
    return {
        "epoch": registry.gauge(
            "repl_epoch", "current sequencer leadership epoch"),
        "fenced": registry.counter(
            "sequencer_fenced_writes_total",
            "writes refused by the epoch fence (deposed-leader "
            "attempts)"),
    }


def _note(timeline, kind: str, node: str = "", **fields) -> None:
    """Record a fleet-timeline event when a timeline is attached
    (obs/timeline.py); replication runs timeline-less by default."""
    if timeline is not None:
        timeline.record(kind, node=node, **fields)


class FencedWriteError(RuntimeError):
    """A write carried a stale leadership epoch: the writer was
    deposed. Refusing it here (BEFORE fan-out) is what makes a
    split-brain candidate harmless — the op was never sequenced as
    far as any client can observe, so the submitter resubmits it to
    the real leader."""


class LeaseHeldError(RuntimeError):
    """Acquisition attempted while a live (unexpired) lease is held
    by another node."""


class LeaseUnreachableError(RuntimeError):
    """The lease service is in another reachability island: no grant
    can be acquired or proven until the partition heals — elections
    are impossible, which is exactly what keeps a split from minting
    two leaders."""


class QuorumUnavailableError(RuntimeError):
    """The leader cannot prove a write durable (quorum unreachable
    within the deadline) or cannot prove its own leadership (lease
    lapsed with the lease service unreachable). RETRIABLE by
    construction: nothing was sequenced as far as any client can
    observe — the op stays with its submitter, rides a throttle nack
    with ``shed_class="unavailable"``, and the PR9 reconnect/resubmit
    path replays it after the heal."""

    def __init__(self, msg: str, retry_after_seconds: float = 0.25):
        super().__init__(msg)
        self.retry_after_seconds = retry_after_seconds


class NetworkTopology:
    """Reachability islands for the in-process multi-node harnesses —
    the netsplit fault vocabulary's state. Production deployments
    never construct one (``group.network`` stays None = fully
    connected, zero overhead); the chaos harness installs one and
    drives ``partition()``/``heal()`` on the seeded schedule.

    ``islands`` maps node id -> island index; nodes reach each other
    iff they share an island, and the LEASE SERVICE occupies an
    island of its own choosing (``lease_island``) so lease isolation
    — everyone replicating fine but nobody able to renew or elect —
    is expressible as its own split mode. Unknown nodes default to
    island 0 (a node the schedule never mentioned is reachable from
    the majority side)."""

    def __init__(self, timeline=None):
        self.islands: dict[str, int] = {}
        self.lease_island = 0
        self.timeline = timeline
        self.split = False
        self.flaps = 0

    def island_of(self, node: str) -> int:
        return self.islands.get(node, 0)

    def reachable(self, a: str, b: str) -> bool:
        return self.island_of(a) == self.island_of(b)

    def lease_reachable(self, node: str) -> bool:
        return self.island_of(node) == self.lease_island

    def partition(self, groups: list[list[str]],
                  lease_island: int = 0) -> None:
        """Apply a split: ``groups[i]`` lands in island ``i``; the
        lease service sits in ``groups[lease_island]``. Recorded
        through the ``repl.partition`` site (PLANE.fired stays the
        replayable log) and on the fleet timeline."""
        self.islands = {node: i
                        for i, group in enumerate(groups)
                        for node in group}
        self.lease_island = lease_island
        if self.split:
            self.flaps += 1
        self.split = True
        desc = "|".join(",".join(g) for g in groups)
        _SITE_PARTITION.force(KIND_PARTITION, islands=desc,
                              lease_island=lease_island)
        _note(self.timeline, "partition", islands=desc,
              lease_island=lease_island)

    def heal(self) -> None:
        if not self.split:
            return
        self.islands = {}
        self.lease_island = 0
        self.split = False
        _SITE_HEAL.force(KIND_HEAL)
        _note(self.timeline, "heal")


class EpochFence:
    """The monotone leadership epoch and THE check every replicated
    write makes before anything can fan out. ``advance()`` is called
    only by lease acquisition — one epoch per leadership term."""

    def __init__(self, epoch: int = 0, registry=None, timeline=None):
        self.epoch = epoch
        self.timeline = timeline
        m = _fence_metrics(registry or obs_metrics.REGISTRY)
        self._g_epoch = m["epoch"]
        self._c_fenced = m["fenced"]

    def advance(self) -> int:
        self.epoch += 1
        self._g_epoch.set(self.epoch)
        _note(self.timeline, "epoch_advance", epoch=self.epoch)
        return self.epoch

    def check(self, epoch: int, **context) -> None:
        if epoch != self.epoch:
            self._c_fenced.inc()
            _note(self.timeline, "fenced_write", epoch=epoch,
                  current=self.epoch,
                  **{k: v for k, v in context.items()
                     if isinstance(v, (int, float, str, bool))})
            raise FencedWriteError(
                f"epoch fence: write under epoch {epoch} refused, "
                f"current epoch is {self.epoch} ({context}) — the "
                "writer was deposed; the op stays with its submitter "
                "and resubmits to the current leader")


class SequencerLease:
    """The lease seam: leadership is a TTL'd grant renewed on the
    replication heartbeat. Clock-injectable (the chaos harness drives
    it on the step clock), so lease expiry — and therefore failover
    timing — is deterministic. Acquisition advances the epoch fence;
    renewal consults the ``repl.lease_expire`` chaos site, whose
    faults model the two real-world lease failure shapes: a renewal
    lost in transit (``drop`` — the TTL keeps running) and the lease
    service lapsing the grant NOW without telling the holder
    (``error`` — the split-brain trigger)."""

    def __init__(self, fence: EpochFence, ttl: float = 0.3,
                 clock=None, timeline=None, network=None):
        self.fence = fence
        self.ttl = ttl
        self.clock = clock or time.monotonic
        self.timeline = timeline
        # reachability to the lease SERVICE (netsplit plane): None =
        # fully connected. An unreachable caller's renewal is lost in
        # transit (the TTL keeps running) and its acquire refuses —
        # a minority island can never mint an epoch
        self.network: Optional[NetworkTopology] = network
        self.holder: Optional[str] = None
        self.expires_at = float("-inf")

    @property
    def epoch(self) -> int:
        return self.fence.epoch

    def expired(self) -> bool:
        return self.clock() >= self.expires_at

    def acquire(self, node_id: str) -> int:
        if self.network is not None and \
                not self.network.lease_reachable(node_id):
            raise LeaseUnreachableError(
                f"{node_id} cannot reach the lease service across "
                "the partition: no election from a minority island")
        if self.holder not in (None, node_id) and not self.expired():
            raise LeaseHeldError(
                f"lease held by {self.holder!r} for another "
                f"{self.expires_at - self.clock():.3f}s")
        self.holder = node_id
        self.expires_at = self.clock() + self.ttl
        _note(self.timeline, "lease_grant", node=node_id,
              ttl=self.ttl)
        return self.fence.advance()

    def renew(self, node_id: str, epoch: int) -> bool:
        if node_id != self.holder or epoch != self.fence.epoch:
            return False  # deposed caller: the grant moved on
        if self.network is not None and \
                not self.network.lease_reachable(node_id):
            # the renewal is lost in transit across the split: the
            # TTL keeps running toward the lapse — topology-driven
            # and deterministic, so it consumes NO chaos-site draw
            return False
        fault = _SITE_LEASE.fire(holder=node_id)
        if fault == KIND_DROP:
            return False  # renewal lost in transit; TTL keeps running
        if fault == KIND_ERROR:
            # lease-service hiccup: the grant lapses NOW and the
            # holder is NOT told — it keeps writing until the epoch
            # fence refuses it (the split-brain candidate the
            # deposed-race chaos mode exercises)
            self.expires_at = self.clock()
            _note(self.timeline, "lease_expire", node=node_id,
                  origin="fault")
            return False
        self.expires_at = self.clock() + self.ttl
        _note(self.timeline, "lease_renew", node=node_id)
        return True

    def force_expire(self, reason: str = "forced") -> None:
        """Harness-driven lapse (the deposed-race schedule), recorded
        through the plane like any crash-time forced state."""
        _SITE_LEASE.force(KIND_ERROR, reason=reason)
        self.expires_at = self.clock()
        _note(self.timeline, "lease_expire",
              node=self.holder or "", origin="forced", reason=reason)


class FollowerReplica:
    """One follower sequencer node: a durable, per-document,
    contiguous copy of the leader's op log, in EXACTLY the layout a
    ``LocalServer`` durable dir uses (``<root>/<doc>/ops.jsonl``) —
    so promotion is "boot a server over this directory", not a data
    migration. Appends fsync before acking (the follower's half of
    the ack barrier); a deferred (lagging) append is buffered
    in-memory and acked only once durable."""

    def __init__(self, root: str, node_id: str, registry=None,
                 timeline=None, stamp_ts=None):
        self.root = root
        self.node_id = node_id
        # the follower's OWN registry (satellite fix: follower series
        # used to alias the process-wide REGISTRY, double-counting
        # leader + follower into one registry in in-process multi-node
        # tests); default None keeps the process-wide aggregate —
        # production runs one node per process
        self._c_fenced = _fence_metrics(
            registry or obs_metrics.REGISTRY)["fenced"]
        self.timeline = timeline
        # timestamp source for the repl:follower_append hop stamp:
        # None = stamp()'s wall default; the group passes its injected
        # clock through so recorded corpora stay byte-stable per seed
        self._stamp_ts = stamp_ts
        os.makedirs(root, exist_ok=True)
        self.max_epoch_seen = 0
        self._heads: dict[str, int] = {}
        self._fhs: dict[str, Any] = {}
        self._lag: dict[str, list[SequencedMessage]] = {}
        # resume replicated heads from disk (a follower surviving its
        # own restart) — torn tails tolerated exactly like the
        # leader's log: the torn op never acked, so discarding it is
        # exact
        for doc in sorted(os.listdir(root)):
            path = self._log_path(doc)
            if not os.path.isfile(path):
                continue
            rows, torn = read_jsonl_tolerant(path, "repl")
            if torn:
                atomic_write(path, "".join(
                    jsonl_record(r) for r in rows))
            if rows:
                self._heads[doc] = rows[-1]["sequenceNumber"]

    def _log_path(self, doc: str) -> str:
        return os.path.join(self.root, doc, "ops.jsonl")

    def _fh(self, doc: str):
        fh = self._fhs.get(doc)
        if fh is None:
            os.makedirs(os.path.join(self.root, doc), exist_ok=True)
            fh = open(self._log_path(doc), "a")
            self._fhs[doc] = fh
        return fh

    # -- state ----------------------------------------------------------

    def documents(self) -> list[str]:
        return sorted(set(self._heads) | set(self._lag))

    def head(self, doc: str) -> int:
        """Last DURABLY replicated seq for ``doc`` (0 = none)."""
        return self._heads.get(doc, 0)

    def total_head(self) -> int:
        return sum(self._heads.values())

    def lag_depth(self) -> int:
        return sum(len(v) for v in self._lag.values())

    # -- the replication stream ----------------------------------------

    def _check_epoch(self, epoch: int, doc: str) -> None:
        if epoch < self.max_epoch_seen:
            self._c_fenced.inc()
            _note(self.timeline, "fenced_write", node=self.node_id,
                  epoch=epoch, current=self.max_epoch_seen, doc=doc)
            raise FencedWriteError(
                f"follower {self.node_id}: append under epoch "
                f"{epoch} refused (seen {self.max_epoch_seen}, "
                f"doc {doc!r}) — fencing-token backstop")
        self.max_epoch_seen = epoch

    def note_epoch(self, epoch: int) -> None:
        """A new leader's first contact: stale-epoch writes from the
        deposed leader are refused from here on."""
        self.max_epoch_seen = max(self.max_epoch_seen, epoch)

    def buffer_lag(self, doc: str, epoch: int,
                   msg: SequencedMessage) -> None:
        """Replication lag: the op arrived but is NOT yet durable —
        no ack. ``flush_lag`` makes the contiguous prefix durable."""
        self._check_epoch(epoch, doc)
        self._lag.setdefault(doc, []).append(msg)

    def append_durable(self, doc: str, epoch: int,
                       msg: SequencedMessage) -> None:
        self._check_epoch(epoch, doc)
        self._append_raw(doc, msg)

    def _append_raw(self, doc: str, msg: SequencedMessage) -> None:
        assert msg.sequence_number == self.head(doc) + 1, (
            f"follower {self.node_id} log must stay contiguous: "
            f"append seq {msg.sequence_number} onto head "
            f"{self.head(doc)} (doc {doc!r})")
        # the cross-node hop: this follower holds the op durably (one
        # stamp per follower that appends — catch-up/anti-entropy
        # appends stamp too, honestly dating when the copy landed)
        _stamp(msg.traces, "repl", "follower_append",
               timestamp=self._stamp_ts() if self._stamp_ts else None)
        fh = self._fh(doc)
        # crc-stamped (storage.jsonl_record): the scrubber's bit-rot
        # detection is only as good as the records carrying checksums
        fh.write(jsonl_record(message_to_json(msg)))
        fh.flush()
        os.fsync(fh.fileno())  # durable BEFORE the ack counts
        self._heads[doc] = msg.sequence_number

    def flush_lag(self, doc: Optional[str] = None) -> int:
        """Durably apply the buffered tail's CONTIGUOUS prefix;
        anything gapped (an earlier op was dropped in transit) stays
        buffered until catch-up supplies the middle. Returns ops
        applied."""
        applied = 0
        for d in ([doc] if doc is not None else list(self._lag)):
            pending = sorted(self._lag.get(d, []),
                             key=lambda m: m.sequence_number)
            keep: list[SequencedMessage] = []
            for msg in pending:
                if msg.sequence_number <= self.head(d):
                    continue  # catch-up already supplied it
                if msg.sequence_number == self.head(d) + 1:
                    self._append_raw(d, msg)
                    applied += 1
                else:
                    keep.append(msg)
            if keep:
                self._lag[d] = keep
            else:
                self._lag.pop(d, None)
        return applied

    def drop_lag(self) -> int:
        """Discard buffered ops still gapped after anti-entropy: no
        surviving node holds the middle, so they were never
        quorum-durable — never fanned out — and their submitters
        still hold them pending. Returns ops dropped."""
        dropped = self.lag_depth()
        self._lag.clear()
        return dropped

    def sync_from(self, doc: str, msgs: list[SequencedMessage]) -> int:
        """Anti-entropy: apply a peer/leader-supplied range (ops at or
        below our head are skipped — at-least-once safe)."""
        applied = 0
        for msg in msgs:
            if msg.sequence_number <= self.head(doc):
                continue
            self._append_raw(doc, msg)
            applied += 1
        return applied

    def read_log(self, doc: str,
                 from_seq: int = 0) -> list[SequencedMessage]:
        """Ops with seq > from_seq from the durable replica log."""
        path = self._log_path(doc)
        if not os.path.isfile(path):
            return []
        rows, _ = read_jsonl_tolerant(path, "repl")
        return [message_from_json(r) for r in rows
                if r["sequenceNumber"] > from_seq]

    def close(self) -> None:
        for fh in self._fhs.values():
            fh.close()
        self._fhs.clear()


class ReplicatedOpLog(FileOpLog):
    """The leader's per-document op log under the extended ack
    barrier: fence check, local fsynced append (PR9's barrier), then
    BLOCK until a quorum of followers holds the op durably — all
    before ``OpLog.append`` returns to scriptorium, which runs before
    the broadcaster, so nothing fans out un-replicated."""

    def __init__(self, path: str, group: "ReplicatedSequencerGroup",
                 document_id: str, epoch: int):
        self._group = group
        self._doc = document_id
        self._epoch = epoch
        super().__init__(path)

    def _persist_append(self, msg: SequencedMessage) -> None:
        try:
            self._group.fence.check(self._epoch, doc=self._doc,
                                    op="append")
        except FencedWriteError:
            # OpLog.append adds to the in-memory list BEFORE
            # persisting: the refused op must not linger there either,
            # or a deposed leader's read path would serve an op the
            # quorum never accepted
            self._ops.pop()
            raise
        _stamp(msg.traces, "repl", "fence_check",
               timestamp=self._group._trace_ts())
        super()._persist_append(msg)  # local fsync (the PR9 barrier)
        try:
            self._group.replicate_before_fanout(
                self._doc, self._epoch, msg, self)
        except QuorumUnavailableError:
            # quorum deadline lapsed: UNWIND the local append — in
            # memory AND on disk — so the refused op can never be
            # served, replicated later under a stale epoch, or leave
            # the durable log ahead of the sequencer the submit path
            # rolls back. The op was never quorum-durable, never
            # fanned out; its submitter still holds it pending.
            # Cycle the append handle around the rewrite (the
            # _persist_truncate discipline): atomic_write replaces
            # the inode, and a post-heal append through the stale
            # handle would land on the unlinked file.
            self._ops.pop()
            self._fh.close()
            self._rewrite()
            self._fh = open(self.path, "a")
            raise

    def truncate_below(self, seq: int) -> int:
        # summary truncation must never outrun a laggard: this log is
        # every follower's catch-up source, and dropping records a
        # follower still needs would turn its next catch-up into an
        # unfillable gap
        return super().truncate_below(
            min(seq, self._group.replication_floor(self._doc)))


class ReplicatedDocumentStorage(DocumentStorage):
    """DocumentStorage whose op log is a :class:`ReplicatedOpLog`
    (summaries and checkpoints stay node-local: the replicated log is
    the recovery truth, and a promoted follower rebuilds everything
    else from it)."""

    def __init__(self, root: str, group: "ReplicatedSequencerGroup",
                 document_id: str, epoch: int):
        self._group = group
        self._document_id = document_id
        self._epoch = epoch
        super().__init__(root)

    def _make_op_log(self, path: str) -> FileOpLog:
        return ReplicatedOpLog(path, self._group,
                               self._document_id, self._epoch)


class ReplicatedLocalServer(LocalServer):
    """The LocalServer surface over the replicated plane: per-document
    orderers write through :class:`ReplicatedOpLog`, submits are
    fence-checked BEFORE ticketing (a deposed leader must not even
    consume sequence numbers), and the read path serves only
    quorum-COMMITTED ops — the window where an op is leader-durable
    but not yet quorum-durable is never client-visible."""

    def __init__(self, group: "ReplicatedSequencerGroup",
                 durable_dir: str, **kwargs):
        super().__init__(durable_dir=durable_dir, **kwargs)
        self.group = group
        self.epoch = group.fence.epoch

    def _make_storage(self, document_id: str):
        return ReplicatedDocumentStorage(
            os.path.join(self.durable_dir, document_id),
            self.group, document_id, self.epoch)

    def _make_orderer(self, document_id: str) -> LocalOrderer:
        return LocalOrderer(
            document_id, storage=self._make_storage(document_id),
            storage_breaker=self.storage_breaker,
            checkpoint_every=self.checkpoint_every,
            write_fence=self._fence_check_for(document_id),
            clock=self.clock,
        )

    def _fence_check_for(self, document_id: str):
        def check(op: str = "write") -> None:
            self.group.fence.check(self.epoch, doc=document_id,
                                   op=op)
            if op in ("submit", "connect", "disconnect"):
                # the availability gate (quorum-loss degraded mode):
                # AFTER the fence — a deposed leader refuses as
                # deposed, never as "retry later". A refused
                # disconnect is absorbed by the orderer as an OWED
                # leave (settled at the client's next join), so
                # teardown never detonates.
                self.group.ensure_available(document_id, op=op)
        return check

    def read_ops(self, document_id: str, from_seq: int,
                 to_seq: Optional[int] = None):
        # a deposed server must not serve reads either: its in-memory
        # state may disagree with the order the new leader is minting
        self.group.fence.check(self.epoch, doc=document_id, op="read")
        committed = self.group.committed(document_id)
        to = committed if to_seq is None else min(to_seq, committed)
        return super().read_ops(document_id, from_seq, to)


class ReplicatedSequencerGroup:
    """Leader + N follower sequencer nodes for one ordering scope.

    The group owns the lease, the epoch fence, the follower set and
    the committed watermark; the current leader's
    :class:`ReplicatedLocalServer` is ``group.server`` (callers front
    it with an AlfredServer exactly like a plain LocalServer — after
    a failover they front the NEW ``group.server`` and clients ride
    the PR9 reconnect/resubmit path through the handoff)."""

    def __init__(self, root: str, n_followers: int = 2,
                 quorum: Optional[int] = None, clock=None,
                 lease_ttl: float = 0.3, scope: str = "docs",
                 server_kwargs: Optional[dict] = None,
                 registry=None, follower_registries=None,
                 timeline=None, network: Optional[NetworkTopology] = None,
                 quorum_timeout_s: float = 0.5,
                 retry_interval_s: float = 0.05,
                 membership_grace_s: Optional[float] = None,
                 sleep=None):
        if n_followers < 1:
            raise ValueError(
                "a replicated sequencer needs at least one follower "
                "(n_followers >= 1), or host loss loses acked ops")
        if follower_registries is not None and \
                len(follower_registries) != n_followers:
            raise ValueError(
                f"{len(follower_registries)} follower registries for "
                f"{n_followers} followers")
        self.root = root
        self.scope = scope
        # timestamps for the repl hop stamps follow the clock ONLY
        # when one was injected: the default group clock is
        # time.monotonic (lease arithmetic), and monotonic stamps
        # must never mix into wall-clock hop tables
        self._injected_clock = clock is not None
        self.clock = clock or time.monotonic
        # the quorum barrier's wait primitive: deadline-bounded and
        # INJECTABLE (qoscheck:unbounded-blocking-wait pins the
        # deadline statically). Harnesses on the step clock inject a
        # sleep that ADVANCES it, so the wait-out is deterministic;
        # production defaults to the wall sleep.
        self._sleep = sleep if sleep is not None else time.sleep
        self.quorum_timeout_s = quorum_timeout_s
        self.retry_interval_s = retry_interval_s
        # follower unseen past the grace TTL -> membership shrinks
        # (and grows back on rejoin); default: a few lease TTLs
        self.membership_grace_s = membership_grace_s \
            if membership_grace_s is not None else 4 * lease_ttl
        # netsplit plane: None = fully connected (production; zero
        # overhead). The chaos harness installs a NetworkTopology and
        # drives partition()/heal() on the seeded schedule.
        self.network = network
        self.registry = registry or obs_metrics.REGISTRY
        self.timeline = timeline
        self.metrics = _group_metrics(self.registry)
        self.fence = EpochFence(registry=self.registry,
                                timeline=timeline)
        self.lease = SequencerLease(self.fence, ttl=lease_ttl,
                                    clock=self.clock,
                                    timeline=timeline, network=network)
        self.followers = [
            FollowerReplica(
                os.path.join(root, f"node-{i}"), f"node-{i}",
                registry=(follower_registries[i - 1]
                          if follower_registries else None),
                timeline=timeline, stamp_ts=self._trace_ts,
            )
            for i in range(1, n_followers + 1)
        ]
        # quorum-loss degraded mode (read-only brownout) + follower
        # lifecycle state. _degraded_probe_at paces rediscovery when
        # NO topology is installed (production): one write per
        # timeout window runs the barrier as the probe, the rest
        # fast-nack — without it every post-loss write would re-pay
        # the full discovery deadline.
        self.degraded = False
        self.degraded_reason = ""
        self._degraded_since = 0.0
        self._degraded_probe_at = 0.0
        self._last_seen: dict[str, float] = {
            f.node_id: self.clock() for f in self.followers}
        #: detached (grace-lapsed / wiped) followers by node id —
        #: rejoin() re-admits them behind the epoch fence
        self.detached: dict[str, str] = {}
        # quorum over ALL nodes (leader included); default = a strict
        # majority of the initial group ((total // 2) + 1 — for even
        # group sizes too: 4 nodes need 3, or losing a minority could
        # lose a client-acked op), floored at 2 so at least one
        # follower always holds every fanned-out op
        self.quorum = quorum if quorum is not None else max(
            2, (n_followers + 1) // 2 + 1)
        if self.quorum > 1 + n_followers:
            raise ValueError(
                f"quorum {self.quorum} unsatisfiable with "
                f"{n_followers} followers")
        self.server_kwargs = dict(server_kwargs or {})
        self._committed: dict[str, int] = {}
        self.max_lag_observed = 0
        self.leader_id = "node-0"
        self.epoch = self.lease.acquire(self.leader_id)
        self.server = self._build_server(
            os.path.join(root, "node-0"))
        self.metrics["followers"].labels(partition=self.scope).set(
            len(self.followers))

    def _build_server(self, durable_dir: str) -> ReplicatedLocalServer:
        return ReplicatedLocalServer(self, durable_dir,
                                     **self.server_kwargs)

    def _trace_ts(self) -> Optional[float]:
        """Timestamp for repl hop stamps: the injected clock when one
        exists (byte-stable recorded corpora per seed), else None —
        stamp()'s wall default."""
        return self.clock() if self._injected_clock else None

    # -- committed watermark -------------------------------------------

    def committed(self, doc: str) -> int:
        """Highest quorum-durable seq for ``doc`` — the only ops the
        read path may serve (Raft's commitIndex shape)."""
        return self._committed.get(doc, 0)

    def replication_floor(self, doc: str) -> int:
        """Lowest follower head: truncation must stay below nothing a
        laggard still needs from the leader's log."""
        return min(f.head(doc) for f in self.followers) \
            if self.followers else self.committed(doc)

    # -- quorum-loss degraded mode (read-only brownout) -----------------

    def _reachable(self, f: FollowerReplica) -> bool:
        return self.network is None or \
            self.network.reachable(self.leader_id, f.node_id)

    def _quorum_reachable(self) -> bool:
        """ONE owner for the reachable-quorum verdict (leader + the
        followers the topology can currently offer to), shared by the
        pre-ticket gate and the barrier's cached-verdict fast path so
        the two can never drift."""
        return 1 + sum(
            1 for f in self.followers if self._reachable(f)
        ) >= self.quorum

    def _lease_unprovable(self) -> bool:
        """The leader's lease lapsed AND the lease service is across
        the split: leadership cannot be proven, so writes must stop
        (a write the node cannot prove it is entitled to sequence is
        a fork candidate). A lapse with the lease service REACHABLE
        is different — the next heartbeat renews it (same holder,
        same epoch: the grant never moved)."""
        return (self.lease.expired()
                and self.network is not None
                and not self.network.lease_reachable(self.leader_id))

    def _enter_degraded(self, reason: str) -> None:
        if self.degraded:
            return
        self.degraded = True
        self.degraded_reason = reason
        self._degraded_since = self.clock()
        self._degraded_probe_at = self.clock() + self.quorum_timeout_s
        self.metrics["degraded"].set(1)
        _note(self.timeline, "degraded_enter", node=self.leader_id,
              reason=reason)

    def _exit_degraded(self) -> None:
        if not self.degraded:
            return
        self.degraded = False
        self.metrics["degraded"].set(0)
        self.metrics["degraded_s"].inc(
            max(0.0, self.clock() - self._degraded_since))
        _note(self.timeline, "degraded_exit", node=self.leader_id,
              reason=self.degraded_reason)
        self.degraded_reason = ""
        # the heal is also every unreachable follower's comeback:
        # refresh liveness so the grace TTL restarts from the heal,
        # not from the split
        for f in self.followers:
            if self._reachable(f):
                self._last_seen[f.node_id] = self.clock()

    def _refuse_unavailable(self, doc: str, op: str
                            ) -> QuorumUnavailableError:
        self.metrics["unavailable"].inc()
        return QuorumUnavailableError(
            f"quorum unavailable ({self.degraded_reason or 'quorum'}"
            f"): {op} on {doc!r} refused — retriable; resubmit after "
            "the partition heals (read-only brownout at the "
            "committed watermark)",
            retry_after_seconds=self.quorum_timeout_s)

    def ensure_available(self, doc: str, op: str = "submit") -> None:
        """The write path's pre-ticket availability gate (consulted by
        the same write_fence hook as the epoch fence, AFTER it).
        Degraded is a CACHED verdict: entered when the barrier timed
        out (or the lease became unprovable), so exactly one submit
        pays the discovery deadline and later ones fast-nack. Exit:
        with a topology installed, the moment a probe shows quorum
        reachable (and leadership provable) again; with NO topology
        (production — reachability is only discoverable by trying),
        one PACED probe write per timeout window runs the barrier as
        the arbiter and a quorum success there exits degraded."""
        if self._lease_unprovable():
            self._enter_degraded("lease_unreachable")
            raise self._refuse_unavailable(doc, op)
        if not self.degraded:
            return
        if self.network is not None:
            if self._quorum_reachable():
                self._exit_degraded()
                return
            raise self._refuse_unavailable(doc, op)
        if self.clock() >= self._degraded_probe_at:
            self._degraded_probe_at = \
                self.clock() + self.quorum_timeout_s
            return  # the probe write: the barrier decides
        raise self._refuse_unavailable(doc, op)

    # -- follower lifecycle (grace shrink, rejoin) ----------------------

    def detach(self, node_id: str, origin: str) -> Optional[str]:
        """THE membership-shrink path (grace lapse, or a crash-and-
        wipe observed as a dead host being replaced — both callers
        share it so the quorum rule can never drift between them):
        the follower leaves the membership, the quorum recomputes as
        a strict majority of the REMAINING set (floored at 2 — at
        least one follower must hold every fanned-out op — and
        clamped to what the remaining set can satisfy). The data dir
        stays on disk; ``rejoin()`` re-admits the node. Returns the
        detached root, or None when the node is unknown or the last
        follower (never shrink below one)."""
        f = next((x for x in self.followers
                  if x.node_id == node_id), None)
        if f is None or len(self.followers) <= 1:
            return None
        self.followers.remove(f)
        self.detached[node_id] = f.root
        f.close()
        self.quorum = min(
            self.quorum,
            max(2, (1 + len(self.followers)) // 2 + 1))
        self.quorum = min(self.quorum, 1 + len(self.followers))
        self.metrics["followers"].labels(
            partition=self.scope).set(len(self.followers))
        _note(self.timeline, "membership", node=node_id,
              action="shrink", origin=origin, quorum=self.quorum,
              followers=len(self.followers))
        return f.root

    def _check_membership_grace(self) -> None:
        """Followers unseen past the grace TTL detach (see
        :meth:`detach`)."""
        cutoff = self.clock() - self.membership_grace_s
        for f in list(self.followers):
            if self._last_seen.get(f.node_id, cutoff) >= cutoff:
                continue
            self.detach(f.node_id, origin="grace")

    def _leader_log(self, doc: str):
        """The leader's op log for ``doc``, booting the orderer from
        its durable dir when it has not been touched since a
        promotion (``server.documents`` is lazy; booting from the dir
        IS the crash-restore path). None when the leader holds
        nothing for the doc."""
        if self.server is None:
            return None
        if doc not in self.server.documents and not os.path.isdir(
                os.path.join(self.server.durable_dir, doc)):
            return None
        return self.server.get_orderer(doc).op_log

    def rejoin(self, node_id: str, registry=None) -> FollowerReplica:
        """Re-admit a crashed (possibly WIPED) follower: a fresh
        replica over its dir, fenced at the current epoch, fully
        resynced by anti-entropy from every peer's contiguous log
        (follower logs are never truncated, so one surviving peer
        covers a wiped node's whole history) plus the leader's log
        tail. Membership grows back and the quorum recomputes."""
        root = self.detached.pop(node_id, None) or \
            os.path.join(self.root, node_id)
        f = FollowerReplica(root, node_id, registry=registry,
                            timeline=self.timeline,
                            stamp_ts=self._trace_ts)
        f.note_epoch(self.fence.epoch)  # the fence: stale writers out
        docs = set()
        for peer in self.followers:
            docs.update(peer.documents())
        if self.server is not None:
            docs.update(self.server.documents)
            docs.update(
                d for d in os.listdir(self.server.durable_dir)
                if os.path.isdir(
                    os.path.join(self.server.durable_dir, d)))
        applied = 0
        for doc in sorted(docs):
            for peer in self.followers:
                if peer.head(doc) > f.head(doc):
                    applied += f.sync_from(
                        doc, peer.read_log(doc, f.head(doc)))
            log = self._leader_log(doc)
            if log is not None:
                behind = [m for m in log.read(f.head(doc))
                          if m.sequence_number <= self.committed(doc)]
                applied += f.sync_from(doc, behind)
        if applied:
            self.metrics["anti_entropy"].inc(applied)
        self.followers.append(f)
        self._last_seen[node_id] = self.clock()
        self.quorum = max(self.quorum,
                          (1 + len(self.followers)) // 2 + 1)
        self.metrics["followers"].labels(partition=self.scope).set(
            len(self.followers))
        self.metrics["rejoins"].inc()
        _note(self.timeline, "rejoin", node=node_id,
              ops_resynced=applied, quorum=self.quorum,
              followers=len(self.followers))
        return f

    # -- bit-rot scrubbing ---------------------------------------------

    def scrub(self) -> int:
        """Scrub every follower's replica logs: a record that fails
        its crc is read-repaired from any peer (other followers, then
        the leader's op log) whose copy is intact — quorum
        replication is what makes the repair possible. Returns
        records repaired (storage_scrub_repairs_total counts them
        per log); raises ``CorruptRecordError`` when NO intact copy
        survives anywhere."""
        repaired = 0
        for f in self.followers:
            for doc in f.documents():
                path = f._log_path(doc)
                if not os.path.isfile(path):
                    continue

                def fetch(index: int, rows: list, _doc=doc,
                          _f=f) -> Optional[dict]:
                    from .storage import CorruptRecordError

                    # contiguous follower logs start at seq 1, so an
                    # intact neighbour anchors the corrupt slot's seq
                    seq = None
                    for j, row in enumerate(rows):
                        if row is not None and "sequenceNumber" in row:
                            seq = row["sequenceNumber"] + (index - j)
                            break
                    if seq is None:
                        seq = index + 1
                    for peer in self.followers:
                        if peer is _f:
                            continue
                        try:
                            for m in peer.read_log(_doc, seq - 1):
                                if m.sequence_number == seq:
                                    return message_to_json(m)
                                break
                        except CorruptRecordError:
                            continue  # this peer rotted too: next
                    log = self._leader_log(_doc)
                    if log is not None:
                        for m in log.read(seq - 1, seq):
                            if m.sequence_number == seq:
                                return message_to_json(m)
                    return None

                report = scrub_repair_jsonl(path, "repl", fetch)
                if report.repaired:
                    # the rewrite replaced the inode: reopen the
                    # append handle or later appends land on the
                    # unlinked file
                    fh = f._fhs.pop(doc, None)
                    if fh is not None:
                        fh.close()
                    repaired += report.repaired
                    _note(self.timeline, "scrub_repair",
                          node=f.node_id, doc=doc,
                          records=report.repaired)
        return repaired

    # -- the ack barrier ------------------------------------------------

    def replicate_before_fanout(self, doc: str, epoch: int,
                                msg: SequencedMessage,
                                source_log) -> None:
        """Block until ``msg`` is durable on a quorum — but never
        forever: the wait is DEADLINE-BOUNDED on the injectable
        clock. Callers check the epoch fence FIRST
        (qoscheck:fence-before-fanout pins the ordering statically).
        Follower faults are absorbed — the quorum is the contract,
        not any single ack: a lagging follower is force-synced in
        deterministic order; an UNREACHABLE one (netsplit) simply
        cannot ack, and when the deadline lapses with the quorum
        still short the append is UNWOUND (the op was never
        client-visible) and the leader enters degraded mode,
        refusing the write with a retriable unavailable nack — a
        minority-side leader nacks its submitters instead of hanging
        them (qoscheck:unbounded-blocking-wait pins the deadline
        statically)."""
        seq = msg.sequence_number
        # the hop pair around the quorum barrier: forward marks the
        # leader offering the op to its followers, quorum_ack marks
        # the barrier satisfied — so the quorum wait is its OWN hop
        # in op_breakdown()/OTLP instead of silently inflating the
        # sequencer-ticket hop (the ledger bridge feeds
        # repl_quorum_wait_ms from exactly this pair)
        _stamp(msg.traces, "repl", "forward",
               timestamp=self._trace_ts())
        if self.degraded:
            # the verdict is CACHED: while a topology says the
            # partition stands, a write that slipped past the gate
            # (a leave, a mid-batch op) must not pay the discovery
            # deadline again — refuse immediately. Otherwise this is
            # the PACED probe write (or a topology-observed heal):
            # run the barrier, and a quorum success below is what
            # exits degraded.
            if self.network is not None:
                if not self._quorum_reachable():
                    raise self._refuse_unavailable(doc, "append")
                self._exit_degraded()
        acked = 1  # the leader's own fsynced append
        for f in self.followers:
            if self._offer(f, doc, epoch, msg, source_log):
                acked += 1
        # leadership heartbeat piggybacks on replication traffic
        self.lease.renew(self.leader_id, epoch)
        deadline = self.clock() + self.quorum_timeout_s
        # attempts bound the wait even under a mis-injected clock (a
        # harness whose sleep forgets to advance it): the barrier
        # degrades to a bounded retry count, never a busy spin
        attempts = 0
        max_attempts = max(1, int(
            self.quorum_timeout_s / max(self.retry_interval_s, 1e-9)
        ) + 1)
        while acked < self.quorum:
            for f in self.followers:
                if acked >= self.quorum:
                    break
                if f.head(doc) >= seq or not self._reachable(f):
                    continue
                self._force_sync(f, doc, epoch, msg, source_log)
                acked += 1
            if acked >= self.quorum:
                break
            if self.clock() >= deadline or attempts >= max_attempts:
                # quorum shortfall past the deadline: unwind + refuse
                # (the fix for the unbounded `while acked < quorum`
                # wait — a vanished follower set cannot hang a
                # submitter). The local append rolls back (the op was
                # never quorum-durable, never fanned out; its
                # submitter still holds it pending), degraded mode
                # latches so later submits fast-nack, and the nack is
                # retriable — the PR9 resubmit path converges after
                # the heal.
                self._enter_degraded("quorum_timeout")
                raise self._refuse_unavailable(doc, "append")
            self._sleep(self.retry_interval_s)
            attempts += 1
        if self.degraded:
            # the paced probe write reached quorum: the loss healed
            self._exit_degraded()
        self._last_seen.update(
            (f.node_id, self.clock())
            for f in self.followers if f.head(doc) >= seq)
        self._check_membership_grace()
        heads = sorted([seq] + [f.head(doc) for f in self.followers],
                       reverse=True)
        self._committed[doc] = max(self.committed(doc),
                                   heads[self.quorum - 1])
        _stamp(msg.traces, "repl", "quorum_ack",
               timestamp=self._trace_ts())
        lag = max((seq - f.head(doc) for f in self.followers),
                  default=0)
        self.metrics["lag"].set(lag)
        self.max_lag_observed = max(self.max_lag_observed, lag)

    def _offer(self, f: FollowerReplica, doc: str, epoch: int,
               msg: SequencedMessage, source_log) -> bool:
        """One replication attempt to one follower; True = durable
        ack. An unreachable follower (netsplit) cannot be offered
        anything — and consumes NO chaos-site draw, so the injection
        stream stays a pure function of the reachable event order.
        ``defer`` buffers (replication lag); a dropped/erroring ack
        is retried once (the broker-append idiom), then the follower
        just misses this round — catch-up repairs it on the next
        offer or at promotion."""
        if not self._reachable(f):
            return False
        seq = msg.sequence_number
        if _SITE_LAG.fire(follower=f.node_id, doc=doc,
                          seq=seq) == KIND_DEFER:
            self.metrics["lag_deferrals"].inc()
            f.buffer_lag(doc, epoch, msg)
            return False
        fault = _SITE_ACK.fire(follower=f.node_id, doc=doc, seq=seq)
        if fault is not None:
            self.metrics["ack_retries"].inc()
            fault = _SITE_ACK.fire(follower=f.node_id, doc=doc,
                                   seq=seq, retry=True)
            if fault is not None:
                return False
        self._catch_up(f, doc, seq - 1, source_log)
        f.append_durable(doc, epoch, msg)
        return True

    def _catch_up(self, f: FollowerReplica, doc: str, upto: int,
                  source_log) -> None:
        f.flush_lag(doc)
        if f.head(doc) < upto:
            applied = f.sync_from(
                doc, source_log.read(f.head(doc), upto))
            if applied:
                self.metrics["anti_entropy"].inc(applied)

    def _force_sync(self, f: FollowerReplica, doc: str, epoch: int,
                    msg: SequencedMessage, source_log) -> None:
        """The blocking path: quorum shortfall makes the leader WAIT
        on this follower — flush its buffer, supply any missing
        middle from the leader's log, land the op. No chaos sites
        fire here: the faults already fired (and were recorded) on
        the offer; this is the barrier waiting them out."""
        self._catch_up(f, doc, msg.sequence_number - 1, source_log)
        if f.head(doc) >= msg.sequence_number:
            return  # the flushed buffer already contained it
        f.append_durable(doc, epoch, msg)

    # -- failover -------------------------------------------------------

    def kill_leader(self):
        """Host loss: the leader process is simply gone — nothing
        graceful happens; the lease stops being renewed and lapses on
        its TTL. Returns the dead server object (harnesses keep it to
        model the deposed-leader race)."""
        dead = self.server
        self.server = None
        return dead

    def laggiest_follower(self) -> FollowerReplica:
        return min(self.followers, key=lambda f: f.total_head())

    def failover(self, candidate: Optional[FollowerReplica] = None
                 ) -> ReplicatedLocalServer:
        """Elect ``candidate`` (default: the best-replicated
        follower) into the leader role. Refuses while a live lease is
        held — failover is lease-driven, never a second writer."""
        if not self.lease.expired():
            raise LeaseHeldError(
                f"lease held by {self.lease.holder!r}; failover "
                "requires the lease to lapse first")
        # the election OBSERVES the lapse — the failover timeline's
        # detection-phase boundary (obs/timeline.py failover_phases)
        _note(self.timeline, "lease_expire",
              node=self.lease.holder or "", origin="observed")
        if not self.followers:
            raise RuntimeError("no followers left to promote")
        if candidate is None:
            # only a candidate that can reach the lease service can
            # be elected (acquire() enforces it; a minority island
            # never mints an epoch). max() keeps the FIRST maximum:
            # deterministic low-index tie-break.
            eligible = [f for f in self.followers
                        if self.network is None
                        or self.network.lease_reachable(f.node_id)]
            if not eligible:
                raise LeaseUnreachableError(
                    "no follower can reach the lease service across "
                    "the partition: no election until the heal")
            candidate = max(eligible, key=lambda f: f.total_head())
        fault = _SITE_PROMOTE.fire(node=candidate.node_id)
        if fault == KIND_ERROR:
            # transient election failure: the retry is exact (nothing
            # was promoted); a second injected fault is absorbed the
            # same way — promotion is idempotent until acquire()
            _SITE_PROMOTE.fire(node=candidate.node_id, retry=True)
        return self._promote(candidate)

    def _promote(self, candidate: FollowerReplica
                 ) -> ReplicatedLocalServer:
        # 1) the candidate's own received-but-buffered tail
        candidate.flush_lag()
        # 2) anti-entropy from every surviving REACHABLE peer: any
        # fanned-out op is durable on >= quorum-1 followers, so at
        # least one surviving peer holds it in its contiguous prefix
        # (a peer across a netsplit cannot be read — the in-process
        # object is right there, but pulling from it would model an
        # impossible cross-split transfer; the heal-time catch-up
        # repairs whatever it holds)
        for peer in self.followers:
            if peer is candidate:
                continue
            if self.network is not None and not \
                    self.network.reachable(candidate.node_id,
                                           peer.node_id):
                continue
            for doc in peer.documents():
                if peer.head(doc) > candidate.head(doc):
                    applied = candidate.sync_from(
                        doc, peer.read_log(doc, candidate.head(doc)))
                    if applied:
                        self.metrics["anti_entropy"].inc(applied)
                        _note(self.timeline, "anti_entropy",
                              node=candidate.node_id,
                              source=peer.node_id, doc=doc,
                              ops=applied)
        candidate.flush_lag()
        candidate.drop_lag()
        # 3) mint the new epoch and fence everyone else out
        self.epoch = self.lease.acquire(candidate.node_id)
        self.leader_id = candidate.node_id
        self.followers = [f for f in self.followers
                          if f is not candidate]
        for f in self.followers:
            f.note_epoch(self.epoch)
        self.quorum = min(self.quorum, 1 + len(self.followers))
        self._committed = {doc: candidate.head(doc)
                           for doc in candidate.documents()}
        # 4) the follower's dir BECOMES the leader's durable dir: the
        # orderer boot path fast-forwards each sequencer to its log
        # head, so ticketing resumes at exactly the replicated head
        candidate.close()
        self.server = self._build_server(candidate.root)
        self.metrics["failovers"].inc()
        self.metrics["followers"].labels(partition=self.scope).set(
            len(self.followers))
        _note(self.timeline, "promotion", node=self.leader_id,
              epoch=self.epoch,
              followers_left=len(self.followers))
        return self.server
