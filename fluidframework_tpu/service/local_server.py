"""Local delta-connection server: the whole multi-document service
in-proc.

Reference: server/routerlicious/packages/local-server/src/
localDeltaConnectionServer.ts (:61) + localWebSocketServer.ts (:77) —
the integration-test backbone (SURVEY §4 pillar (c)): real sequencing,
msn, nacks and summaries with zero deployment. Our connection objects
stand in for sockets.
"""
from __future__ import annotations

import itertools
from typing import Callable, Optional

from ..protocol.messages import (
    ClientDetail,
    DocumentMessage,
    Nack,
    SequencedMessage,
)
from .local_orderer import LocalOrderer


class DeltaConnection:
    """One client's live connection to a document (the socket
    analogue: driver-base/src/documentDeltaConnection.ts:41)."""

    def __init__(self, server: "LocalServer", orderer: LocalOrderer,
                 client_id: str, connection_id: str,
                 read_only: bool = False):
        self._server = server
        self._orderer = orderer
        self.client_id = client_id
        self.connection_id = connection_id
        self.read_only = read_only
        self.open = True
        self.on_message: Optional[Callable[[SequencedMessage], None]] = None
        self.on_nack: Optional[Callable[[Nack], None]] = None

    def submit(self, op: DocumentMessage) -> None:
        assert self.open, "submit on closed connection"
        if self.read_only:
            raise PermissionError(
                "submit on a read-mode connection (doc:read scope)")
        nack = self._orderer.submit(self.client_id, op)
        if nack is not None and self.on_nack is not None:
            self.on_nack(nack)

    def disconnect(self) -> None:
        if not self.open:
            return
        self.open = False
        self._orderer.broadcaster.unsubscribe(self.connection_id)
        if not self.read_only:
            self._orderer.disconnect(self.client_id)


class LocalServer:
    """Multi-document service: one LocalOrderer per document
    (document-parallelism — SURVEY §2.9 axis 1)."""

    def __init__(self, durable_dir: Optional[str] = None,
                 storage_breaker=None,
                 checkpoint_every: int = 1,
                 clock=None) -> None:
        self.documents: dict[str, LocalOrderer] = {}
        self.durable_dir = durable_dir
        # injectable wall clock threaded into every orderer's
        # sequencer (wire timestamps); None = real wall time
        self.clock = clock
        # ONE shared qos.CircuitBreaker across every document's
        # checkpoint writes (they share the disk, so they share the
        # failure domain); None = unguarded, as before
        self.storage_breaker = storage_breaker
        # checkpoint cadence (deli checkpoints every N dispatches): >1
        # leaves a restart a real op-log gap to fast-forward across —
        # the crash-recovery path tests/test_chaos.py exercises
        self.checkpoint_every = checkpoint_every
        self._conn_counter = itertools.count()

    def get_orderer(self, document_id: str) -> LocalOrderer:
        if document_id not in self.documents:
            self.documents[document_id] = self._make_orderer(
                document_id)
        return self.documents[document_id]

    # factory hooks: the replicated sequencer
    # (service/replication.py) swaps in a ReplicatedDocumentStorage
    # (op log behind the replication quorum) and an epoch-fenced
    # orderer without re-stating the construction logic
    def _make_storage(self, document_id: str):
        if self.durable_dir is None:
            return None
        import os

        from .storage import DocumentStorage

        return DocumentStorage(
            os.path.join(self.durable_dir, document_id))

    def _make_orderer(self, document_id: str) -> LocalOrderer:
        return LocalOrderer(
            document_id, storage=self._make_storage(document_id),
            storage_breaker=self.storage_breaker,
            checkpoint_every=self.checkpoint_every,
            clock=self.clock,
        )

    # ------------------------------------------------------------------
    # connection lifecycle (connect_document handshake,
    # lambdas/src/alfred/index.ts:465)

    def connect(self, document_id: str, client_id: str,
                on_message: Callable[[SequencedMessage], None],
                on_nack: Optional[Callable[[Nack], None]] = None,
                detail: Optional[ClientDetail] = None,
                read_only: bool = False,
                ) -> DeltaConnection:
        """``read_only`` = the reference's "read" connection mode:
        broadcast subscription only — no quorum join (the client's
        refSeq never pins the msn) and submit is rejected."""
        orderer = self.get_orderer(document_id)
        connection_id = f"conn-{next(self._conn_counter)}"
        conn = DeltaConnection(self, orderer, client_id, connection_id,
                               read_only=read_only)
        conn.on_message = on_message
        conn.on_nack = on_nack
        # subscribe BEFORE the join op so the client sees its own join
        orderer.broadcaster.subscribe(
            connection_id, lambda msg: conn.on_message and
            conn.on_message(msg)
        )
        if detail is None:
            # the join payload's ClientDetail rides the wire: stamp
            # it from the injected clock when one is set, so recorded
            # corpora stay byte-stable under a manual clock
            detail = ClientDetail(
                client_id, timestamp=self.clock(),
            ) if self.clock else ClientDetail(client_id)
        if not read_only:
            try:
                orderer.connect(detail)
            except Exception:
                # the client's own delivery callback refused the join
                # (e.g. the loader's unfillable-gap error): unwind the
                # half-made connection — a zombie subscription would
                # keep delivering into the dead client and raise its
                # error inside every UNRELATED submitter's dispatch
                conn.disconnect()
                raise
        return conn

    # ------------------------------------------------------------------
    # storage plane (delta storage + summaries)

    def read_ops(self, document_id: str, from_seq: int,
                 to_seq: Optional[int] = None) -> list[SequencedMessage]:
        return self.get_orderer(document_id).op_log.read(from_seq, to_seq)

    def latest_summary(self, document_id: str):
        return self.get_orderer(document_id).summary_store.latest()
