"""TPU merge sidecar: device-resident merge state for the service
plane.

The north star (BASELINE.json): the ordering service's op stream is
batched into padded tensors and merge resolution runs on-device across
thousands of documents per dispatch, while the per-client host path
stays untouched. The sidecar subscribes to sequenced channel streams
(deli out-topic / broadcaster fan-out), accumulates per-document
windows, applies them with ``ops.apply_window``, and serves
text/summary state — powering service-side summarization, replay
validation, and the batched benchmarks.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..ops import (
    DocStream,
    OpBatch,
    apply_window,
    compact,
    extract_signature,
    extract_text,
    fetch,
    make_table,
)
from ..ops.host_bridge import OP_FIELDS
from ..ops.segment_table import KIND_NOOP
from ..protocol.messages import MessageType, SequencedMessage


class TpuMergeSidecar:
    """Batched merge state for up to ``max_docs`` sequence channels.

    One tracked channel (doc slot) = one (document, datastore, channel)
    sequence stream. ``ingest`` consumes the document's sequenced
    envelope stream; ``apply`` flushes accumulated windows to the
    device in a single dispatch.
    """

    def __init__(self, max_docs: int = 1024, capacity: int = 1024,
                 compact_every: int = 8):
        self.max_docs = max_docs
        self.capacity = capacity
        self._table = make_table(max_docs, capacity)
        self._slots: dict[tuple[str, str, str], int] = {}
        self._streams: list[DocStream] = []
        self._queued: list[list[dict]] = []
        self._applies = 0
        self._compact_every = compact_every

    # ------------------------------------------------------------------
    # registration + ingest

    def track(self, document_id: str, datastore_id: str,
              channel_id: str) -> int:
        key = (document_id, datastore_id, channel_id)
        if key in self._slots:
            return self._slots[key]
        if len(self._streams) >= self.max_docs:
            raise RuntimeError("sidecar document capacity exhausted")
        slot = len(self._streams)
        self._slots[key] = slot
        self._streams.append(DocStream())
        self._queued.append([])
        return slot

    def subscribe(self, server, document_id: str, datastore_id: str,
                  channel_id: str) -> None:
        """Attach to a LocalServer document's broadcaster (the
        sidecar's place in the pipeline: after deli, beside
        scriptorium)."""
        self.track(document_id, datastore_id, channel_id)
        orderer = server.get_orderer(document_id)
        orderer.broadcaster.subscribe(
            f"tpu-sidecar/{document_id}/{datastore_id}/{channel_id}",
            lambda msg: self.ingest(document_id, msg),
        )

    def ingest(self, document_id: str, msg: SequencedMessage) -> None:
        """Consume one sequenced message of a document: channel ops for
        tracked channels encode as kernel ops; everything else becomes
        a NOOP that still advances the collab window."""
        for (doc, ds_id, ch_id), slot in self._slots.items():
            if doc != document_id:
                continue
            stream = self._streams[slot]
            before = len(stream.ops)
            envelope = msg.contents if isinstance(msg.contents, dict) else {}
            if (
                msg.type == MessageType.OPERATION
                and envelope.get("kind", "op") == "op"
                and envelope.get("address") == ds_id
                and envelope.get("channel") == ch_id
            ):
                inner = SequencedMessage(
                    client_id=msg.client_id,
                    sequence_number=msg.sequence_number,
                    minimum_sequence_number=msg.minimum_sequence_number,
                    client_sequence_number=msg.client_sequence_number,
                    reference_sequence_number=(
                        msg.reference_sequence_number
                    ),
                    type=msg.type,
                    contents=envelope["contents"],
                )
                stream.add_message(inner)
            else:
                stream.add_noop(msg.minimum_sequence_number)
            self._queued[slot].extend(stream.ops[before:])

    # ------------------------------------------------------------------
    # device application

    @property
    def queued_ops(self) -> int:
        return sum(len(q) for q in self._queued)

    def apply(self) -> int:
        """Flush all queued windows in one batched dispatch. Returns
        the number of real (non-noop) ops applied."""
        if not self._queued or self.queued_ops == 0:
            return 0
        docs = self.max_docs
        # Pad the window to a power-of-two bucket: ``apply_window`` is
        # compiled per (docs, window) shape, and an exact-fit window
        # would recompile on nearly every flush (20-40s each on the
        # real chip). Pow2 bucketing bounds the shape count to log(n).
        window = max(len(q) for q in self._queued)
        bucket = 16
        while bucket < window:
            bucket *= 2
        window = bucket
        arrays = {f: np.zeros((docs, window), np.int32)
                  for f in OP_FIELDS}
        arrays["kind"][:] = KIND_NOOP
        real = 0
        for slot, queue in enumerate(self._queued):
            for w, op in enumerate(queue):
                for f in OP_FIELDS:
                    arrays[f][slot, w] = op[f]
                if op["kind"] != KIND_NOOP:
                    real += 1
            queue.clear()
        self._table = apply_window(self._table, OpBatch(**arrays))
        self._applies += 1
        if self._applies % self._compact_every == 0:
            self._table = compact(self._table)
        return real

    # ------------------------------------------------------------------
    # reads (service-side summarization / validation)

    def _slot(self, document_id: str, datastore_id: str,
              channel_id: str) -> int:
        return self._slots[(document_id, datastore_id, channel_id)]

    def text(self, document_id: str, datastore_id: str,
             channel_id: str) -> str:
        slot = self._slot(document_id, datastore_id, channel_id)
        return extract_text(fetch(self._table), self._streams[slot], slot)

    def signature(self, document_id: str, datastore_id: str,
                  channel_id: str) -> tuple:
        slot = self._slot(document_id, datastore_id, channel_id)
        return extract_signature(
            fetch(self._table), self._streams[slot], slot
        )

    def overflowed(self) -> bool:
        return bool(np.asarray(self._table.overflow).any())
