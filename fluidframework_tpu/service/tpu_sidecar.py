"""TPU merge sidecar: device-resident merge state for the service
plane.

The north star (BASELINE.json): the ordering service's op stream is
batched into padded tensors and merge resolution runs on-device across
thousands of documents per dispatch, while the per-client host path
stays untouched. The sidecar subscribes to sequenced channel streams
(deli out-topic / broadcaster fan-out), accumulates per-document
windows, applies them with the chunked executor, and serves
text/summary state — powering service-side summarization, replay
validation, and the batched benchmarks.

DISPATCH PIPELINE (docs/PERF.md): the apply loop is a two-stage
pipeline. The host half (noop coalescing, vectorized ``_pack_rows``,
the chunk compile) runs for round N+1 while the device still computes
round N; the only host<->device sync is ``_settle`` — the designated
boundary where round N's overflow flag is read and recovery runs.
Dispatches ride the chunked executor (``ops/merge_chunk.py``,
launch/HBM-amortized, bit-identical to the scan for live state) by
default on launch-taxed backends (TPU); see ``default_executor`` for
the backend policy and ``FFTPU_SIDECAR_EXECUTOR`` / ``executor=`` for
the escape hatch either way. Donation is re-enabled through double
buffering: round N+1 donates the round N-1 table (provably idle —
round N's input depended on it), never the live input, so the
pre-dispatch snapshot regrow needs stays alive.

Overflow recovery (VERDICT r1 weak #4): a document that outgrows its
slab or exceeds the interned property channels is never silently
wrong. On overflow the sidecar REGROWS the slab (2x) by padding the
pre-dispatch table snapshot and re-applying just the failed window —
O(window), not O(history); JAX tables are immutable so the snapshot
is a free handle — or, past ``max_capacity``, admits the document to
the sequence-sharded pool / EVICTS it to a host-side scalar oracle
replica (the retained per-document encoded stream is the durable
source for those paths). The chunked executor PARKS an overflowed
document at its pre-chunk state instead of applying past the flag;
that difference is absorbed here at the policy layer — recovery
re-applies the whole failed window from the snapshot (or replays the
canonical stream), so both executors converge to the same served
state. ``prewarm`` walks the shared ``BucketLadder`` so neither
bucket jumps nor regrows ever hit an XLA compile mid-serve.
"""
from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from ..models.mergetree import MergeTreeClient
from ..obs import metrics as obs_metrics
from ..obs.flight_recorder import FlightRecorder
from ..obs.heat import HeatLedger, attribute_round
from ..obs.profiler import device_trace
from ..obs.trace import stamp as trace_stamp
from ..ops import (
    DocStream,
    OpBatch,
    apply_window,
    compact,
    extract_signature,
    extract_text,
    fetch,
    make_table,
)
from ..ops.bucket_ladder import BucketLadder
from ..ops.event_graph import (
    EG_K,
    EXECUTOR_ROUTES,
    apply_window_egwalker,
    apply_window_egwalker_pingpong,
    build_event_graph,
    validate_executor,
)
from ..ops.host_bridge import (
    OP_FIELDS,
    pack_rows as _pack_rows,
    replay_chunked as _replay_chunked,
)
from ..ops.merge_chunk import (
    CHUNK_K,
    apply_window_chunked,
    apply_window_chunked_pingpong,
    compile_chunks,
)
from ..ops.merge_kernel import apply_window_pingpong
from ..ops.segment_table import KIND_NOOP
from ..protocol.messages import MessageType, SequencedMessage
from ..qos.faults import (
    KIND_DEFER,
    KIND_ERROR,
    KIND_ERROR_BURST,
    PLANE as _CHAOS,
)

# CHUNK_K, _pack_rows and _replay_chunked live in ops/ since the
# mesh-pool PR (merge_chunk.CHUNK_K, host_bridge.pack_rows /
# replay_chunked — both pool tiers share them with this module); the
# old names are re-exported above because they are part of this
# module's de-facto surface (tests, bench's legacy-pack monkeypatch).

# Registry families (process aggregates across every sidecar/pool
# instance; exact per-instance counts stay on the owning object —
# tests read sidecar.grow_count etc.). IMPORTANT: everything bumped
# from inside the dispatch loop is host-side only — a registry inc and
# a flight-recorder record never touch the device; fluidlint's
# dispatch-loop-sync rule covers these call sites.
_M_ROUNDS = obs_metrics.REGISTRY.counter(
    "sidecar_rounds_total", "dispatch rounds flushed")
_M_OPS = obs_metrics.REGISTRY.counter(
    "sidecar_real_ops_total", "non-noop ops applied on device")
_M_GROW = obs_metrics.REGISTRY.counter(
    "sidecar_grow_total", "capacity-ladder regrows")
_M_EVICT = obs_metrics.REGISTRY.counter(
    "sidecar_evict_total", "documents evicted to host replicas")
_M_POOL_ADMIT = obs_metrics.REGISTRY.counter(
    "sidecar_pool_admit_total", "documents admitted to the seq pool")
_M_RECOVER = obs_metrics.REGISTRY.counter(
    "sidecar_overflow_recoveries_total",
    "settle boundaries that found the overflow flag set")
_M_PACK_MS = obs_metrics.REGISTRY.histogram(
    "sidecar_pack_ms", "host half of a round (pack + compile)")
_M_SETTLE_MS = obs_metrics.REGISTRY.histogram(
    "sidecar_settle_ms", "device-wait at the settle boundary")
_M_TRACKED = obs_metrics.REGISTRY.gauge(
    "sidecar_tracked_channels", "channels on the device batch path")
_M_POOLED = obs_metrics.REGISTRY.gauge(
    "sidecar_pooled_docs", "documents on the seq-sharded pool tier")
_M_HOSTED = obs_metrics.REGISTRY.gauge(
    "sidecar_host_docs", "documents evicted to host replicas")
_M_CAPACITY = obs_metrics.REGISTRY.gauge(
    "sidecar_capacity", "current primary slab capacity (slots/doc)")
_M_POOL_DISPATCH = obs_metrics.REGISTRY.counter(
    "pool_dispatches_total", "seq-pool incremental dispatches")
_M_POOL_DEPTH = obs_metrics.REGISTRY.gauge(
    "pool_dispatch_depth", "ops in the last pool dispatch")
_M_POOL_WATERMARK = obs_metrics.REGISTRY.gauge(
    "pool_watermark_ops", "sum of member stream watermarks")
_M_POOL_MEMBERS = obs_metrics.REGISTRY.gauge(
    "pool_members", "documents admitted to the pool")
_M_POOL_ROUTE_FALLBACK = obs_metrics.REGISTRY.counter(
    "pool_route_fallback_total",
    "SeqShardedPool chunked-route requests served by the "
    "scan-collective executor on a real seq mesh")
_M_DUP_DROPS = obs_metrics.REGISTRY.counter(
    "sidecar_duplicate_drops_total",
    "already-ingested sequenced messages dropped by the per-document "
    "sequence-number check (at-least-once delivery upstream)")
_M_SPAN_SPLITS = obs_metrics.REGISTRY.counter(
    "egwalker_span_splits_total",
    "would-be span breaks the egwalker compiler absorbed by event "
    "splitting (each one is a saved walker launch)")
_M_DISPATCH_FAULTS = obs_metrics.REGISTRY.counter(
    "sidecar_dispatch_faults_total",
    "device dispatch rounds that failed transiently before mutating "
    "anything (ops stay queued; the next apply retries exactly)")
_M_POOL_FAULTS = obs_metrics.REGISTRY.counter(
    "pool_faults_total",
    "pool operations deferred or retried under a transient fault "
    "(shared by NAME across the seq and mesh tiers, like the "
    "sidecar.pool_* chaos sites)", labelnames=("tier", "op"))

# chaos seams (docs/ROBUSTNESS.md): the dispatch site fires BEFORE the
# round mutates anything (queues intact, so a retry is exact); the
# pool sites model a lagging pool dispatch / a deferred migration / a
# transiently-failing admission — every one a recovery path the
# convergence differential must hold through
_SITE_DISPATCH = _CHAOS.site(
    "sidecar.dispatch", (KIND_ERROR, KIND_ERROR_BURST))
_SITE_POOL_DISPATCH = _CHAOS.site("sidecar.pool_dispatch", (KIND_DEFER,))
_SITE_POOL_ADMIT = _CHAOS.site("sidecar.pool_admit", (KIND_ERROR,))
_SITE_POOL_MIGRATE = _CHAOS.site("sidecar.pool_migrate", (KIND_DEFER,))


# --- the TPU default's launch arithmetic (reviewed, not hard-coded) --
#
# On the launch-taxed axon tunnel the serving cost model is
# launches/window x cost/launch: every kernel launch pays a ~0.3 ms
# tunnel round-trip (TPU_EVIDENCE round 3; the per-launch cost slots
# in from real-chip timings when the tunnel returns), so the route
# with the fewest launches per dispatch window wins regardless of
# per-step FLOPs. The launches/window column is RECORDED evidence —
# bench config14's sequential-heavy corpus at cpu scale (BENCH PR15:
# walker spans 14.5 vs chunked chunks 53.4 per doc window; the scan
# route pays one fused step per op = the padded 64-op window at that
# scale). Launch COUNTS are backend-portable (they are compiled-
# program dispatch counts, not timings), which is what lets CPU
# evidence drive the TPU default before real-chip numbers land.
# ``default_executor`` derives the TPU route from this table and
# bench config14 stamps the table + decision into its record, so a
# re-measure that changes the winner changes the default in review.
LAUNCH_COST_MS = 0.3
LAUNCHES_PER_WINDOW = {
    "scan": 64.0,       # one fused step per op in the padded window
    "chunked": 53.4,    # chunked_chunks_per_doc, config14 sequential
    "egwalker": 14.5,   # walker_spans_per_doc, config14 sequential
                        # (pre-event-splitting; splitting only shrinks
                        # it, so the flip is conservative)
}


def executor_flip() -> dict:
    """The launch-arithmetic decision behind the TPU default, with
    its inputs: per-route modeled launch cost per dispatch window and
    the winning route. Stamped into bench config14's record so the
    flip is reviewable data, not a constant."""
    cost = {
        route: round(n * LAUNCH_COST_MS, 2)
        for route, n in LAUNCHES_PER_WINDOW.items()
    }
    return {
        "launch_cost_ms": LAUNCH_COST_MS,
        "launches_per_window": dict(LAUNCHES_PER_WINDOW),
        "launch_ms_per_window": cost,
        "winner": min(cost, key=cost.get),
        "evidence": "config14 sequential-heavy graph stats (cpu "
                    "scale); ~0.3ms/launch tunnel model",
    }


def default_executor() -> str:
    """Service-side executor route. On a TPU backend the default is
    DERIVED from the launch-arithmetic table above (currently the
    egwalker route: 14.5 modeled launches/window vs chunked's 53.4 —
    the critical-version fast path composes whole spans per launch,
    and event splitting keeps spans open across min_seq-aging
    boundaries). On backends without a launch tax (CPU) the
    one-op-per-step scan stays the default — the macro-step routes'
    [D, ..., K] resolve + sort costs several x a fused scan step
    there and launches are ~free, so routing by the table would be a
    measured serving REGRESSION (bench config7/config14 record
    per-route numbers per backend).
    ``FFTPU_SIDECAR_EXECUTOR=scan|chunked|egwalker`` overrides either
    way (the operational escape hatch)."""
    env = os.environ.get("FFTPU_SIDECAR_EXECUTOR")
    if env:
        # the escape hatch must fail LOUDLY on a typo: silently
        # falling back to the backend default would mean an
        # emergency route change that never happened
        validate_executor(env, "FFTPU_SIDECAR_EXECUTOR")
        return env
    import jax

    try:
        backend = jax.default_backend()
    except RuntimeError as e:  # pragma: no cover - backend init failure
        import sys

        print(
            "default_executor: jax backend init failed "
            f"({e}); routing as cpu",
            file=sys.stderr,
        )
        backend = "cpu"
    return executor_flip()["winner"] if backend == "tpu" else "scan"


class SeqShardedPool:
    """Long-document tier (SURVEY §5.7 in the PRODUCT path): documents
    that outgrow the primary slab ladder move to a table whose SLOT
    axis is sharded across a device mesh — per-document capacity =
    n_seq_devices x the primary ladder top — instead of leaving the
    device path entirely (host eviction becomes the LAST resort, for
    documents that exceed even the pooled capacity or are
    tensor-inexpressible).

    Admissions are rare (a document must exhaust the primary ladder),
    so the pool keeps its machinery simple and correct: admitting
    rebuilds the pool table at the next power-of-two row count and
    re-replays every member's canonical encoded stream in chunked
    sequence-sharded dispatches (same recipe as the primary ladder's
    regrow)."""

    def __init__(self, mesh, per_doc_capacity: int,
                 executor: Optional[str] = None):
        from ..parallel.seq_shard import SEQ_AXIS

        if SEQ_AXIS not in mesh.axis_names:
            raise ValueError(
                f"seq pool needs a {SEQ_AXIS!r} mesh axis (got "
                f"{mesh.axis_names}); a docs-sharded mesh routes to "
                "MeshShardedPool (select_pool)"
            )
        n_seq = mesh.shape[SEQ_AXIS]
        if per_doc_capacity % n_seq or per_doc_capacity // n_seq < 2:
            raise ValueError(
                f"pool capacity {per_doc_capacity} invalid for "
                f"{n_seq}-way seq mesh"
            )
        doc_axes = [a for a in mesh.axis_names if a != SEQ_AXIS]
        if doc_axes and mesh.shape[doc_axes[0]] != 1:
            raise ValueError(
                "pool requires an unsharded doc axis (doc_shards=1): "
                "row admissions don't track a sharded row axis"
            )
        self.mesh = mesh
        self.n_seq = n_seq
        self.capacity = per_doc_capacity
        # the chunked/egwalker macro-steps' global multi-key sort does
        # not decompose over a slot-sharded axis, so those routes
        # apply only on a degenerate (n_seq == 1) mesh; a real seq
        # mesh keeps the scan-collective executor (docs/PERF.md) and
        # SAYS SO once (counter + stderr, _warn_route_once) — the
        # silent off-route fallback used to be invisible. On the
        # degenerate mesh an egwalker pool routes CHUNKED: the pool
        # only ever replays full histories (admission/rebuild), where
        # the critical-prefix fast path buys nothing by construction
        # (replay chunks carry arbitrary concurrency) and chunked owns
        # the launch-amortized replay recipe.
        validate_executor(executor, "executor")
        self.executor = executor or default_executor()
        self._route_warned = False
        self.members: list[int] = []      # sidecar slot per pool row
        self.row_of: dict[int, int] = {}  # sidecar slot -> row
        # per-member STREAM WATERMARK: how many of the slot's canonical
        # stream ops the pool table already reflects. This is what
        # makes incremental dispatch rebuild-proof: a full-stream
        # rebuild (_replay_all) advances every watermark to the stream
        # head, so ops it subsumed can never be dispatched again —
        # the review-confirmed double-apply of a deferred-op batch
        # racing a recovery rebuild is impossible by construction.
        self.applied_upto: dict[int, int] = {}
        self._table = None
        # per-instance observability counters (registry families hold
        # the process aggregates)
        self.dispatch_count = 0
        self.last_dispatch_depth = 0

    def _bucket(self) -> int:
        n = max(1, len(self.members))
        b = 1
        while b < n:
            b *= 2
        return b

    def _warn_route_once(self) -> None:
        if self._route_warned:
            return
        self._route_warned = True
        _M_POOL_ROUTE_FALLBACK.inc()
        import sys

        print(
            f"fftpu: SeqShardedPool: the {self.executor} macro-step "
            "does not decompose over a slot-sharded axis; using the "
            f"scan-collective route on this {self.n_seq}-way seq mesh "
            "(a docs-sharded MeshShardedPool follows the executor "
            "route — see select_pool)",
            file=sys.stderr, flush=True,
        )

    def _apply(self, table, arrays):
        from ..parallel import apply_window_seq_sharded

        if self.executor in ("chunked", "egwalker") and self.n_seq == 1:
            # egwalker routes chunked here on purpose: pool dispatches
            # are full-history replays, chunked's home turf (see the
            # executor-route comment in __init__)
            out = apply_window_chunked(
                table, compile_chunks(arrays, k_max=CHUNK_K), K=CHUNK_K
            )
        else:
            if self.executor in ("chunked", "egwalker"):
                self._warn_route_once()
            out = apply_window_seq_sharded(
                table, OpBatch(**arrays), self.mesh
            )
        # compact after every pool dispatch: remove-heavy histories
        # otherwise accumulate dead segments until they overflow a
        # pool that could easily hold the live text (the primary
        # ladder's _grow compacts per chunk for the same reason)
        return compact(out)

    def _replay_all(self, streams) -> None:
        """Rebuild the pool table and re-replay every member's stream
        (chunked sequence-sharded dispatches)."""
        if not self.members:
            self._table = None
            return
        table = make_table(self._bucket(), self.capacity)
        self._table = _replay_chunked(
            self._apply, table,
            {row: streams[slot].ops
             for row, slot in enumerate(self.members)},
            chunk=BucketLadder.replay_chunk(self.capacity),
        )
        self.applied_upto = {
            slot: len(streams[slot].ops) for slot in self.members
        }
        _M_POOL_MEMBERS.set(len(self.members))
        _M_POOL_WATERMARK.set(sum(self.applied_upto.values()))

    def admit(self, slots: list, streams) -> list:
        """Admit sidecar slots; returns the slots that FAILED (exceed
        even pooled capacity) and were rolled back out."""
        for slot in slots:
            if slot not in self.row_of:
                self.row_of[slot] = len(self.members)
                self.members.append(slot)
        self._replay_all(streams)
        failed = self.overflowed_slots()
        if failed:
            for slot in failed:
                self.remove(slot)
            self._replay_all(streams)
        return failed

    def remove(self, slot: int) -> None:
        """Bookkeeping only — the table still holds the removed row's
        data and flags at the OLD indices. Callers MUST follow with
        rebuild()/ _replay_all() before the next read or dispatch, or
        remaining members read the wrong rows and stale overflow flags
        evict innocent documents."""
        if slot not in self.row_of:
            return
        row = self.row_of.pop(slot)
        self.applied_upto.pop(slot, None)
        self.members.pop(row)
        for s2, r2 in self.row_of.items():
            if r2 > row:
                self.row_of[s2] = r2 - 1

    def rebuild(self, streams) -> None:
        self._replay_all(streams)

    def dispatch_pending(self, streams) -> list:
        """Apply every member's un-applied canonical-stream tail (past
        its watermark) in one dispatch; returns slots that overflowed
        the pool. Tails a rebuild already subsumed are empty here, so
        calling this at any point after any mix of rebuilds and
        incremental dispatches is exactly-once by construction."""
        if self._table is None:
            return []
        if _SITE_POOL_DISPATCH.fire(tier="seq") is not None:
            # deferred: tails stay past the watermark and apply whole
            # at the next settle — exactly-once by construction
            _M_POOL_FAULTS.labels(tier="seq", op="dispatch").inc()
            return []
        from ..ops.host_bridge import coalesce_noops

        pending = {}
        upto = {}
        for slot, row in self.row_of.items():
            tail = streams[slot].ops[self.applied_upto.get(slot, 0):]
            if tail:
                pending[row] = coalesce_noops(tail)
                upto[slot] = len(streams[slot].ops)
        if not pending:
            return []
        depth = sum(len(ops) for ops in pending.values())
        self.dispatch_count += 1
        self.last_dispatch_depth = depth
        _M_POOL_DISPATCH.inc()
        _M_POOL_DEPTH.set(depth)
        arrays = _pack_rows(self._table.docs, pending)
        self._table = self._apply(self._table, arrays)
        self.applied_upto.update(upto)
        _M_POOL_WATERMARK.set(sum(self.applied_upto.values()))
        return self.overflowed_slots()

    def prewarm(self) -> None:
        """Compile the pool's dispatch programs before any admission:
        the first-admission table (row bucket 1, pool capacity) at
        both window shapes the pool dispatches — the incremental
        ``dispatch_pending`` floor bucket and the ``_replay_all``
        chunk bucket — plus the compact that follows every pool
        dispatch. This covers the COMMON first overflow recovery (one
        slot overflows a settle, its tail stays under the floor
        bucket), which used to stall the settle boundary 20-40s on
        the real chip. Shapes beyond that still compile on admission
        and are unbounded by construction: a multi-slot same-settle
        admission builds a wider row bucket, a pending tail past the
        floor packs a higher window bucket, and later pow2
        member-growth rebuilds each have their own signature —
        admission is rare and already O(history), so those pay as
        they land (shapecheck's prewarm-coverage rule pins the
        ROOTS reachable, not every shape)."""
        noop = dict(
            kind=KIND_NOOP, pos1=0, pos2=0, seq=0, refseq=0,
            client=0, op_id=0, length=0, is_marker=0,
            prop_key=0, prop_val=0, min_seq=0,
        )
        chunk = BucketLadder.replay_chunk(self.capacity)
        for floor in sorted({16, chunk}):
            arrays = _pack_rows(1, {0: [noop]}, bucket_floor=floor)
            # each floor needs BOTH input signatures: a fresh
            # make_table (the first _replay_all chunk) and a table
            # that came out of a pool dispatch, which carries the
            # mesh's committed sharding — a distinct jit signature
            # the single-apply prewarm missed (every incremental
            # dispatch_pending after admission uses it)
            out = self._apply(make_table(1, self.capacity), arrays)
            self._apply(out, arrays)

    def overflowed_slots(self) -> list:
        if self._table is None:
            return []
        flags = np.asarray(self._table.overflow)
        return [self.members[r]
                for r in np.nonzero(flags)[0].tolist()
                if r < len(self.members)]

    def fetch(self):
        return fetch(self._table)


def select_pool(mesh, per_doc_capacity: Optional[int] = None,
                executor: Optional[str] = None,
                route: Optional[str] = None,
                max_capacity: int = 16384,
                plane: str = "merge"):
    """THE route-selection point between the pool tiers — every
    sidecar pool (merge AND tree plane) is constructed here, nowhere
    else. ``plane='tree'`` admits tree documents to the pooled tier
    (``TreeSeqPool``): the tree kernels' per-changeset sorts do not
    decompose over a slot-sharded axis, so that pool's capacity
    unlock is a larger chip-local slab and the merge-plane
    ``route`` knob does not apply.

    - a mesh with a real ``seq`` axis (size > 1) -> ``SeqShardedPool``
      (one long document's SLOT axis split across devices);
    - a mesh with a sharded ``docs`` axis -> ``MeshShardedPool``
      (many pooled documents spread across shards, live migration);
    - a single-shard mesh -> whichever tier matches its axis names
      (a degenerate ``seq`` mesh keeps the existing SeqShardedPool
      path; a ``docs`` mesh gets a 1-shard MeshShardedPool — both
      follow the executor route there).

    ``route='seq'|'mesh'`` (constructor arg) or
    ``FFTPU_SIDECAR_POOL=seq|mesh`` (env, arg wins) overrides; an
    unknown value fails LOUDLY, and an override that does not fit the
    mesh fails in the chosen pool's own validation — an emergency
    route change must never silently not happen.

    Default ``per_doc_capacity``: the seq pool multiplies the primary
    ladder top by its seq-shard count (per-doc capacity is the point
    of slot sharding); the mesh pool grants 4x the ladder top (its
    capacity unlock is MEMBER COUNT — per-doc stays chip-local)."""
    if plane not in ("merge", "tree"):
        raise ValueError(
            f"plane={plane!r}: expected 'merge' or 'tree'")
    if plane == "tree":
        from .tree_sidecar import TreeSeqPool

        # executor validation happens against the TREE route registry
        # inside TreeSeqPool (the merge routes don't apply here)
        return TreeSeqPool(
            mesh,
            per_doc_capacity if per_doc_capacity is not None
            else min(max_capacity * 4, 16384),
            executor=executor,
        )
    source = "pool_route"
    validate_executor(executor, "executor")
    if route is None:
        route = os.environ.get("FFTPU_SIDECAR_POOL") or None
        source = "FFTPU_SIDECAR_POOL"
    if route is not None and route not in ("seq", "mesh"):
        # BOTH spellings of the escape hatch fail loudly on a typo —
        # a constructor-arg route change must never silently not
        # happen any more than an env one
        raise ValueError(
            f"{source}={route!r}: expected 'seq' or 'mesh'"
        )
    from ..parallel.mesh import DOC_AXIS
    from ..parallel.seq_shard import SEQ_AXIS

    seq_n = mesh.shape.get(SEQ_AXIS, 1) \
        if SEQ_AXIS in mesh.axis_names else 1
    doc_n = mesh.shape.get(DOC_AXIS, 1) \
        if DOC_AXIS in mesh.axis_names else 1
    if route is None:
        if seq_n > 1:
            route = "seq"
        elif doc_n > 1:
            route = "mesh"
        else:
            route = "seq" if SEQ_AXIS in mesh.axis_names else "mesh"
    if route == "mesh":
        from ..parallel.mesh_pool import MeshShardedPool

        if per_doc_capacity is None:
            # capped: per-doc capacity is chip-local here, and the
            # merge step's op_off composite needs
            # capacity * OPOFF_BOUND < 2^31 (segment_table.py)
            per_doc_capacity = min(max_capacity * 4, 8192)
        # resolve the backend-default route HERE (the mesh pool lives
        # below service and cannot read it itself): a single-shard
        # docs mesh must follow the chunked fast path on TPU exactly
        # like the degenerate seq pool does
        return MeshShardedPool(
            mesh, per_doc_capacity,
            executor=executor or default_executor(),
        )
    if per_doc_capacity is None:
        per_doc_capacity = max_capacity * seq_n
    return SeqShardedPool(mesh, per_doc_capacity, executor=executor)


class TpuMergeSidecar:
    """Batched merge state for up to ``max_docs`` sequence channels.

    One tracked channel (doc slot) = one (document, datastore, channel)
    sequence stream. ``ingest`` consumes the document's sequenced
    envelope stream; ``apply`` flushes accumulated windows to the
    device in a single pipelined dispatch (see the module docstring
    for the pipeline/settle contract).
    """

    def __init__(self, max_docs: int = 1024, capacity: int = 1024,
                 compact_every: int = 8, max_capacity: int = 16384,
                 seq_mesh=None, pool_capacity: Optional[int] = None,
                 pool_route: Optional[str] = None,
                 executor: Optional[str] = None,
                 pipeline: Optional[bool] = None,
                 donate: Optional[bool] = None,
                 ladder: Optional[BucketLadder] = None,
                 trace_ops: Optional[bool] = None,
                 breaker=None,
                 heat: Optional[HeatLedger] = None,
                 usage: Optional[HeatLedger] = None,
                 tenant_of: Optional[Callable] = None,
                 attr_clock: Optional[Callable[[], float]] = None):
        self.max_docs = max_docs
        self.capacity = capacity
        self.max_capacity = max_capacity
        # per-op trace stamping (sidecar:pack / sidecar:settle hops on
        # the ingested messages' trace lists). OPT-IN: it costs one
        # Python append per op per round on the serving path, so the
        # default stays off; the op-trace example and tests enable it.
        if trace_ops is not None:
            self.trace_ops = trace_ops
        else:
            env_trace = os.environ.get("FFTPU_SIDECAR_TRACE")
            if env_trace and env_trace not in ("0", "1"):
                raise ValueError(
                    f"FFTPU_SIDECAR_TRACE={env_trace!r}: expected "
                    "'0' or '1'"
                )
            self.trace_ops = env_trace == "1"
        # messages ingested since the last dispatch / packed into the
        # in-flight round (trace_ops bookkeeping; cleared every round)
        self._round_msgs: list[SequencedMessage] = []
        self._inflight_msgs: list[SequencedMessage] = []
        self.last_settled_msgs: list[SequencedMessage] = []
        # dispatch-loop flight recorder: last N rounds' host-side
        # events, dumped automatically when _settle finds the overflow
        # flag set (the postmortem the PR-2 stall lacked)
        self.flight = FlightRecorder(256, name="sidecar")
        self.last_flight_dump: Optional[str] = None
        # optional qos.CircuitBreaker around device dispatch: repeated
        # dispatch faults open it (apply() then returns 0 and ops stay
        # queued — the growing queued_ops backlog is exactly what the
        # qos pressure signal samples, so ingress starts shedding),
        # and the reset timeout admits probe dispatches that close it
        # when the device recovers. Opening dumps THIS flight
        # recorder: the postmortem of what tripped it is captured at
        # trip time.
        self.breaker = breaker
        if breaker is not None and breaker.on_open is None:
            def _dump_on_open(b) -> None:
                self.last_flight_dump = self.flight.dump_to(
                    reason=f"circuit breaker {b.name!r} opened "
                           f"(last error: {b.last_error!r})")
            breaker.on_open = _dump_on_open
        # dispatch-route knobs (env-overridable escape hatches). The
        # CONSTRUCTOR-ARG spelling of a route typo must be exactly as
        # loud as the env one (the select_pool discipline): an
        # executor='egwalkr' silently serving the backend default is
        # an emergency route change that never happened.
        validate_executor(executor, "executor")
        self.executor = executor or default_executor()
        if pipeline is not None:
            self.pipeline = pipeline
        else:
            env_pipe = os.environ.get("FFTPU_SIDECAR_PIPELINE")
            if env_pipe and env_pipe not in ("0", "1"):
                raise ValueError(
                    f"FFTPU_SIDECAR_PIPELINE={env_pipe!r}: expected "
                    "'0' or '1'"
                )
            self.pipeline = env_pipe != "0"
        if donate is not None:
            self.donate = donate
        else:
            env_donate = os.environ.get("FFTPU_SIDECAR_DONATE")
            if env_donate:
                if env_donate not in ("0", "1"):
                    raise ValueError(
                        f"FFTPU_SIDECAR_DONATE={env_donate!r}: "
                        "expected '0' or '1'"
                    )
                self.donate = env_donate == "1"
            else:
                # backend-aware like the executor route: the ping-pong
                # wrappers fall back to the plain dispatch on CPU (no
                # donation support), so holding fodder there is pure
                # dead weight (an extra [max_docs, capacity] table)
                import jax

                try:
                    self.donate = jax.default_backend() == "tpu"
                except RuntimeError as e:  # pragma: no cover - init
                    import sys

                    print(
                        "sidecar: jax backend init failed "
                        f"({e}); disabling buffer donation",
                        file=sys.stderr,
                    )
                    self.donate = False
        self.ladder = ladder or BucketLadder()
        # pool tier: past the ladder top, docs move to a mesh pool —
        # slot-sharded (SeqShardedPool, SURVEY §5.7) or doc-sharded
        # (MeshShardedPool, SURVEY §2.9) per the mesh's axes — before
        # any host eviction. ``select_pool`` is the ONE routing point;
        # ``pool_route``/FFTPU_SIDECAR_POOL override it.
        self._pool = None
        if seq_mesh is not None:
            self._pool = select_pool(
                seq_mesh, pool_capacity, executor=self.executor,
                route=pool_route, max_capacity=max_capacity,
            )
        self.pool_admit_count = 0
        self._table = make_table(max_docs, capacity)
        self._slots: dict[tuple[str, str, str], int] = {}
        # per-document slot index: ingest is called once per sequenced
        # message per document — scanning every tracked channel there
        # was accidentally O(docs) per message (O(docs^2) per window)
        self._doc_slots: dict[str, list[tuple[int, str, str]]] = {}
        # per-document last ingested seq (the at-least-once dedupe
        # guard in ingest)
        self._last_ingested: dict[str, int] = {}
        # per-slot APPLIED-HEAD seq watermark (egwalker route): the
        # max sequence number of any op already dispatched for the
        # slot. build_event_graph judges the criticality of ops whose
        # refseq predates the window against it — conservative (a
        # stale-low head demotes ops to the exact scan suffix, never
        # the reverse), updated at each dispatch AFTER the window's
        # program is compiled against the pre-window value.
        self._slot_head = np.zeros(max_docs, np.int64)
        # the encoded stream is the single canonical per-doc history:
        # grow re-replays it on device, eviction decodes it back into
        # sequenced messages for the scalar replica (no duplicate raw
        # log — advisor r2)
        self._streams: list[DocStream] = []
        self._queued: list[list[dict]] = []
        # slot -> host oracle replica (evicted documents)
        self._host: dict[int, MergeTreeClient] = {}
        # pipeline state: pre-dispatch snapshot + the window it
        # predates (regrow re-applies it), the retired table offered
        # as donation fodder, and whether the in-flight round's
        # overflow flag has been read yet
        self._prev_table = None
        self._last_program = None
        self._dead = None
        self._unsettled = False
        self._applies = 0
        self._compact_every = compact_every
        self.grow_count = 0
        self.evict_count = 0
        # pipeline instrumentation (bench config7 reads these):
        # host-pack seconds vs settle (device-wait) seconds per round
        self.stats = {"pack_s": 0.0, "settle_s": 0.0, "rounds": 0}
        # device-time attribution plane (obs/heat.py, OPT-IN): when a
        # heat ledger is attached, each round's wall-ms (dispatch
        # start -> that round's settle; consecutive pipelined spans
        # overlap by the next round's pack on purpose) splits across
        # the documents active that round proportional to ops applied.
        # Counts are captured host-side at pack time and charged at
        # the _settle sync boundary — never a mid-loop device read.
        # attr_clock is injectable so differential runs (bench
        # config16) can pin bit-identical tables under a manual clock.
        self.heat = heat
        self.usage = usage
        self.tenant_of = tenant_of
        self._attr_clock = (attr_clock if attr_clock is not None
                            else time.perf_counter)
        self._attr_counts: dict[str, int] = {}
        self._attr_t0 = 0.0
        # slot -> document id (attribution reads counts per doc)
        self._slot_doc: dict[int, str] = {}
        _M_CAPACITY.set(self.capacity)

    # ------------------------------------------------------------------
    # registration + ingest

    def track(self, document_id: str, datastore_id: str,
              channel_id: str) -> int:
        key = (document_id, datastore_id, channel_id)
        if key in self._slots:
            return self._slots[key]
        if len(self._streams) >= self.max_docs:
            raise RuntimeError("sidecar document capacity exhausted")
        slot = len(self._streams)
        self._slots[key] = slot
        self._slot_doc[slot] = document_id
        self._doc_slots.setdefault(document_id, []).append(
            (slot, datastore_id, channel_id)
        )
        self._streams.append(DocStream())
        self._queued.append([])
        _M_TRACKED.set(len(self._streams))
        return slot

    def subscribe(self, server, document_id: str, datastore_id: str,
                  channel_id: str) -> None:
        """Attach to a LocalServer document's broadcaster (the
        sidecar's place in the pipeline: after deli, beside
        scriptorium)."""
        self.track(document_id, datastore_id, channel_id)
        orderer = server.get_orderer(document_id)
        # id(self) in the key: two sidecars (e.g. a shadow validating
        # the other executor route) may track the same channel without
        # silently replacing each other's subscription
        orderer.broadcaster.subscribe(
            f"tpu-sidecar-{id(self)}/{document_id}/{datastore_id}/"
            f"{channel_id}",
            lambda msg: self.ingest(document_id, msg),
        )

    def ingest(self, document_id: str, msg: SequencedMessage) -> None:
        """Consume one sequenced message of a document: channel ops for
        tracked channels encode as kernel ops; everything else becomes
        a NOOP that still advances the collab window.

        AT-LEAST-ONCE GUARD: a message at/below the document's last
        ingested sequence number is a duplicate delivery (a chaos-
        duplicated frame, a replayed broker record, an overlapping
        catch-up) and is DROPPED here — without this check a
        duplicate would extend the canonical encoded stream and the
        pool watermark would faithfully apply the op twice (the
        watermark dedupes double DISPATCH of the same stream ops, not
        a double-encoded stream). Same contract as the container's
        inbound seq check (loader/container.py _on_message)."""
        last = self._last_ingested.get(document_id, 0)
        if msg.sequence_number <= last:
            _M_DUP_DROPS.inc()
            return
        self._last_ingested[document_id] = msg.sequence_number
        if self.trace_ops and any(
            slot not in self._host
            for slot, _, _ in self._doc_slots.get(document_id, ())
        ):
            # one entry per ingested message: the pack/settle hops of
            # the round that carries it stamp this object later
            # (dataclasses.replace below shares the traces list, so
            # stamps land on the original message too). Fully-evicted
            # docs skip this — their ops never reach a dispatch round,
            # so buffering them here would grow without bound.
            self._round_msgs.append(msg)
        for slot, ds_id, ch_id in self._doc_slots.get(document_id, ()):
            stream = self._streams[slot]
            envelope = msg.contents if isinstance(msg.contents, dict) else {}
            if (
                msg.type == MessageType.OPERATION
                and envelope.get("kind", "op") == "op"
                and envelope.get("address") == ds_id
                and envelope.get("channel") == ch_id
            ):
                inner = dataclasses.replace(
                    msg, contents=envelope["contents"]
                )
            else:
                inner = dataclasses.replace(
                    msg, type=MessageType.NO_OP, contents=None,
                    client_id=None,
                )
            if slot in self._host:
                # evicted: the live replica is the state; no history
                # retention needed (eviction is one-way)
                self._host[slot].apply_msg(inner)
                continue
            before = len(stream.ops)
            before_payloads = len(stream.payloads)
            try:
                self._encode(stream, inner)
            except ValueError:
                # inexpressible in tensor form (more interned property
                # channels than PROP_CHANNELS, or a 33rd client): this
                # document leaves the device path. Roll the partial
                # encode back so the canonical stream stays exact, then
                # the full-fidelity host replica takes over — seeded by
                # decoding the stream, plus the message that failed.
                del stream.ops[before:]
                del stream.payloads[before_payloads:]
                self._settle()
                self._evict(slot)
                self._host[slot].apply_msg(inner)
                continue
            self._queued[slot].extend(stream.ops[before:])

    @staticmethod
    def _encode(stream: DocStream, inner: SequencedMessage) -> None:
        if inner.type == MessageType.OPERATION:
            stream.add_message(inner)
        else:
            stream.add_noop(inner.minimum_sequence_number)

    # ------------------------------------------------------------------
    # device application (the dispatch pipeline)

    @property
    def queued_ops(self) -> int:
        return sum(len(q) for q in self._queued)

    def apply(self) -> int:
        """Flush all queued windows in one batched dispatch. Returns
        the number of real (non-noop) ops applied.

        Pipelined (the default): this call returns at enqueue — the
        overflow flag of THIS round is read (and recovery run) at the
        next apply/read, inside ``_settle``, so the host can pack the
        next round while the device computes. ``pipeline=False`` keeps
        the old synchronous contract (settle before returning)."""
        if not self._queued or self.queued_ops == 0:
            return 0
        if self.breaker is not None:
            if not self.breaker.allow():
                # open (or out of probes): ops stay queued; the
                # backlog surfaces through queued_ops -> qos pressure
                return 0
            try:
                real = self._dispatch()
            except Exception as e:  # noqa: BLE001 - breaker records all
                self.breaker.record_failure(e)
                raise
            self.breaker.record_success()
        else:
            real = self._dispatch()
        self._applies += 1
        if self._applies % self._compact_every == 0:
            self._table = compact(self._table)
        if not self.pipeline:
            self._settle()
        return real

    def sync(self) -> None:
        """Barrier: settle the in-flight round (overflow recovery,
        deferred pool dispatch). Reads settle implicitly; hosts that
        inspect recovery counters (grow/evict/pool) right after an
        ``apply`` call this first — under the pipelined default those
        advance at the NEXT settle boundary, not inside ``apply``."""
        self._settle()

    def prewarm(self, max_bucket: Optional[int] = None) -> float:
        """Compile every shape the (docs, window, capacity) ladder can
        reach — each capacity rung's dispatch at every window bucket
        of the shared ``BucketLadder``, compact, and the pad step
        between rungs — so neither steady traffic (a window crossing
        into a new bucket) nor a regrow ever hits an XLA compile
        mid-serve (VERDICT r3 weak #5; the persistent compilation
        cache makes repeat processes skip the cost entirely). Warms
        the ACTIVE executor route, including the donated ping-pong
        form when donation is on. Returns seconds spent."""
        from ..ops.merge_kernel import pad_capacity

        t0 = time.perf_counter()
        noop = dict(
            kind=KIND_NOOP, pos1=0, pos2=0, seq=0, refseq=0,
            client=0, op_id=0, length=0, is_marker=0,
            prop_key=0, prop_val=0, min_seq=0,
        )
        dummy_prev = None
        for rung in BucketLadder.capacity_rungs(
                self.capacity, self.max_capacity):
            table = make_table(self.max_docs, rung)
            for bucket in self.ladder.window_buckets(max_bucket):
                arrays = _pack_rows(
                    self.max_docs, {0: [noop]}, bucket_floor=bucket
                )
                program = self._compile_program(arrays)
                # fresh donation fodder per bucket: the ping-pong jit
                # is a distinct program per window shape, and steady
                # serving dispatches through it — every rung x bucket
                # must compile here, not mid-serve
                dead = (make_table(self.max_docs, rung)
                        if self.donate else None)
                table = self._apply_program(table, program, dead)
                if self.executor == "egwalker":
                    # the egwalker route's concurrent SUFFIX rides the
                    # plain scan jit (never the ping-pong form — its
                    # input is the walker stage's live output), so the
                    # prewarm walk must compile that program per
                    # rung x bucket too; an all-noop prewarm window is
                    # fully critical and would never reach it
                    table = self._apply_program(table, arrays)
            table = compact(table)
            if dummy_prev is not None:
                pad_capacity(dummy_prev, rung)
            dummy_prev = table
        if self._pool is not None:
            self._warm_pool()
        np.asarray(table.count)  # force completion
        return time.perf_counter() - t0

    def _warm_pool(self) -> None:
        """Walk the pool tier's dispatch programs (see
        ``SeqShardedPool.prewarm`` / ``MeshShardedPool.prewarm``) —
        reached through the attribute-held pool, so both edges are
        declared in shapecheck.PREWARM_INDIRECT."""
        self._pool.prewarm()

    def _compile_program(self, arrays: dict, base_head=None) -> dict:
        """Host half of one dispatch: raw packed arrays for the scan
        route, the compiled chunk program for the chunked route, the
        event-graph program (critical prefix + concurrent suffix) for
        the egwalker route."""
        if self.executor == "chunked":
            return compile_chunks(arrays, k_max=CHUNK_K)
        if self.executor == "egwalker":
            return build_event_graph(
                arrays, base_head=base_head, k_max=EG_K,
                window_floor=self.ladder.window_floor,
            )
        return arrays

    def _apply_program(self, table, program: dict, dead=None):
        """Device half of one dispatch. ``dead`` (optional) is a
        retired same-shape table donated as the output buffer — the
        double-buffer scheme; see ``apply_window_pingpong``."""
        if dead is not None and (
            dead.capacity != table.capacity or dead.docs != table.docs
        ):
            dead = None  # shape changed (regrow): fodder is useless
        if "chunk_start" in program:
            if dead is not None:
                return apply_window_chunked_pingpong(
                    dead, table, program, K=CHUNK_K
                )
            return apply_window_chunked(table, program, K=CHUNK_K)
        if not program.get("egwalker"):
            batch = OpBatch(**{f: program[f] for f in OpBatch._fields})
            if dead is not None:
                return apply_window_pingpong(dead, table, batch)
            return apply_window(table, batch)
        # egwalker: walker over every doc's critical prefix first,
        # then the per-op scan over the concurrent suffixes (per doc
        # the suffix strictly follows the prefix in sequenced order;
        # across docs the stages touch disjoint lanes). Donation
        # rides the WALKER stage; the suffix input is that stage's
        # live output, so it always dispatches plain.
        if program["prefix"] is not None:
            if dead is not None:
                table = apply_window_egwalker_pingpong(
                    dead, table, program["prefix"], K=EG_K
                )
            else:
                table = apply_window_egwalker(
                    table, program["prefix"], K=EG_K
                )
        if program["suffix"] is not None:
            table = apply_window(table, OpBatch(**{
                f: program["suffix"][f] for f in OpBatch._fields
            }))
        return table

    def _dispatch(self) -> int:
        from ..ops.host_bridge import coalesce_noops

        # chaos seam, BEFORE any mutation: queues are intact, so the
        # raised transient is exactly a failed device dispatch — the
        # breaker (when wired) records it, ops stay queued, and the
        # next apply() retries the identical round
        fault = _SITE_DISPATCH.fire(queued=self.queued_ops)
        if fault is not None:
            _M_DISPATCH_FAULTS.inc()
            raise _SITE_DISPATCH.transient(fault)
        docs = self.max_docs
        t0 = time.perf_counter()
        # attribution span opens at round start (host clock, opt-in)
        attr_t0 = self._attr_clock() if self.heat is not None else 0.0
        # HOST HALF — runs while the device still computes the
        # previous round. Coalesce noop runs at pack time (safe here:
        # the queue is consumed whole), then pad the window to a
        # ladder bucket: the executors compile per (docs, window)
        # shape, and an exact-fit window would recompile on nearly
        # every flush (20-40s each on the real chip). Pow2 bucketing
        # bounds the shape count to log(n).
        packed = [coalesce_noops(q) for q in self._queued]
        attr_counts: dict[str, int] = {}
        if self.heat is not None:
            # per-document real-op counts off the pack metadata (host
            # ints; BEFORE the pool tier zeroes its slots out of the
            # primary window, so pooled docs attribute too). Committed
            # to self._attr_* only after the in-flight round settles
            # below — the mid-dispatch _settle charges the PREVIOUS
            # round from the previous snapshot.
            for slot, ops in enumerate(packed):
                if not ops:
                    continue
                n = sum(1 for op in ops if op["kind"] != KIND_NOOP)
                if n:
                    doc = self._slot_doc.get(slot)
                    if doc is not None:
                        attr_counts[doc] = attr_counts.get(doc, 0) + n
        pool_real = 0
        if self._pool is not None:
            # pooled docs dispatch from their canonical-stream tails at
            # the settle boundary (watermarked, rebuild-proof — see
            # SeqShardedPool.dispatch_pending); their queued copies are
            # counted here and dropped from the primary window
            for slot in list(self._pool.row_of):
                if packed[slot]:
                    pool_real += sum(
                        1 for op in packed[slot]
                        if op["kind"] != KIND_NOOP
                    )
                    packed[slot] = []
        arrays = _pack_rows(
            docs, {slot: ops for slot, ops in enumerate(packed) if ops},
            bucket_floor=self.ladder.window_floor,
        )
        program = self._compile_program(
            arrays, base_head=self._slot_head
        )
        if program.get("egwalker") and "span_splits" in program:
            # host-side scalar (the compiler counts absorbed breaks on
            # the way down); no device read
            _M_SPAN_SPLITS.inc(int(program["span_splits"].sum()))
        if self.executor == "egwalker":
            # advance the applied-head watermarks AFTER compiling: the
            # program's criticality was judged against the pre-window
            # heads (a grow re-apply reuses the compiled program, so
            # it never re-reads these)
            for slot, ops in enumerate(packed):
                for op in reversed(ops):
                    if op["kind"] != KIND_NOOP:
                        if op["seq"] > self._slot_head[slot]:
                            self._slot_head[slot] = op["seq"]
                        break
        real = sum(
            1 for ops in packed for op in ops
            if op["kind"] != KIND_NOOP
        )
        for queue in self._queued:
            queue.clear()
        pack_s = time.perf_counter() - t0
        self.stats["pack_s"] += pack_s
        self.stats["rounds"] += 1
        _M_ROUNDS.inc()
        _M_OPS.inc(real + pool_real)
        _M_PACK_MS.observe(pack_s * 1000.0)
        # host-side round record (timestamps + already-host scalars
        # only — nothing here may read the device)
        self.flight.record(
            "dispatch", round=self.stats["rounds"], real_ops=real,
            pool_ops=pool_real, pack_ms=round(pack_s * 1000.0, 3),
            capacity=self.capacity,
        )
        if self.trace_ops and self._round_msgs:
            pack_t = time.time()
            for m in self._round_msgs:
                trace_stamp(m.traces, "sidecar", "pack",
                            timestamp=pack_t)
        # SYNC BOUNDARY — read the previous round's overflow flag
        # (recovery if set) before its snapshot is retired below.
        self._settle()
        dead = self._dead
        self._dead = None
        if dead is None and self.donate:
            # no retired buffer yet (first dispatch, or recovery just
            # voided the fodder): donate a fresh zero table so the
            # dispatch still runs the PING-PONG program — prewarm
            # compiles that one (per rung x bucket), and falling back
            # to the never-warmed plain program here would hit a
            # 20-40s serve-time compile on the real chip
            dead = make_table(self.max_docs, self.capacity)
        # free pre-dispatch snapshot (immutable arrays): if this window
        # overflows, recovery pads THIS table and re-applies THIS
        # window instead of re-replaying history
        self._prev_table = self._table
        self._last_program = program
        self._unsettled = True
        # commit this round's attribution snapshot now that the
        # previous round has been charged (in the _settle above)
        if self.heat is not None:
            self._attr_counts = attr_counts
            self._attr_t0 = attr_t0
        # _settle above closed the PREVIOUS round's trace window; this
        # round's messages are now the in-flight set
        if self.trace_ops:
            self._inflight_msgs = self._round_msgs
            self._round_msgs = []
        # opt-in device-trace annotation (FFTPU_DEVICE_TRACE=1): the
        # dispatch window shows up by round in an XLA profiler trace;
        # disabled it is one env lookup, and either way it forces no
        # host<->device sync (the settle boundary stays the only one)
        with device_trace(f"sidecar:dispatch:r{self.stats['rounds']}"):
            self._table = self._apply_program(
                self._prev_table, program, dead if self.donate else None
            )
        return real + pool_real

    def _settle(self) -> None:
        """The designated host<->device sync boundary of the dispatch
        pipeline: read the in-flight round's overflow flag, run
        recovery if set, flush the deferred pool dispatch, and retire
        the now-dead snapshot as donation fodder for the next round.
        Reads (text/signature/overflowed) and the next dispatch both
        funnel through here; nothing else in the apply loop may force
        a device->host transfer."""
        if self._unsettled:
            self._unsettled = False
            t0 = time.perf_counter()
            overflowed = bool(np.asarray(self._table.overflow).any())
            settle_s = time.perf_counter() - t0
            self.stats["settle_s"] += settle_s
            _M_SETTLE_MS.observe(settle_s * 1000.0)
            # `overflowed` is a pre-fetched host bool by now — the
            # flight record costs no extra device read
            self.flight.record(
                "settle", settle_ms=round(settle_s * 1000.0, 3),
                overflow=overflowed,
            )
            if self.heat is not None and self._attr_counts:
                # the round's wall-ms (dispatch start -> here) splits
                # across its active documents proportional to ops —
                # host math over pre-captured ints at the sanctioned
                # sync boundary (obs/heat.py owns the formula and the
                # conservation invariant)
                round_ms = (self._attr_clock() - self._attr_t0) * 1000.0
                attribute_round(
                    self.heat, self._attr_counts, round_ms,
                    usage=self.usage, tenant_of=self.tenant_of,
                )
                self._attr_counts = {}
            if self.trace_ops and self._inflight_msgs:
                settle_t = time.time()
                for m in self._inflight_msgs:
                    trace_stamp(m.traces, "sidecar", "settle",
                                timestamp=settle_t)
                self.last_settled_msgs = self._inflight_msgs
                self._inflight_msgs = []
            if overflowed:
                _M_RECOVER.inc()
                # the automatic postmortem: what the dispatch loop did
                # in the rounds leading up to the overflow
                self.last_flight_dump = self.flight.dump_to(
                    reason="_settle found the overflow flag set "
                           "(recovery running)")
                self._recover()
                # recovery re-applied at a new capacity: retired
                # buffers of the old shape are useless as fodder
                self._dead = None
            elif self.donate:
                self._dead = self._prev_table
            self._prev_table = None
            self._last_program = None
            if self._pool is not None and self._pool.members:
                # pool tier: apply members' stream tails (the pool
                # reads its overflow flags on the spot, which is why
                # its dispatch lives at the sync boundary, not in
                # _dispatch). Inside the _unsettled branch on purpose:
                # the pool advances only when a flush is in flight, so
                # reads stay side-effect-free (no per-read dispatch +
                # compact) and tier-consistent — ingested-but-never-
                # applied ops stay invisible on BOTH tiers until the
                # next apply()
                for slot in self._pool.dispatch_pending(self._streams):
                    self._evict(slot)  # beyond even pooled capacity
                    # (_evict rebuilds the pool for the survivors)

    # ------------------------------------------------------------------
    # overflow recovery: grow ladder, then seq-sharded pool, then
    # host eviction

    def _recover(self) -> None:
        while True:
            overflowed = np.nonzero(np.asarray(self._table.overflow))[0]
            if overflowed.size == 0:
                return
            if self.capacity * 2 <= self.max_capacity:
                self._grow(self.capacity * 2)
            elif self._pool is not None:
                slots = overflowed.tolist()
                failed = self._admit_to_pool(slots)
                for slot in failed:
                    self._evict(slot)
                return
            else:
                for slot in overflowed.tolist():
                    self._evict(slot)
                return

    def _grow(self, new_capacity: int) -> None:
        """Grow the slab 2x and retry the failed window: pad the
        pre-dispatch snapshot (content-preserving, one kernel) and
        re-apply the SAME window at the new capacity. O(window) rather
        than the old full-history re-replay — the failed dispatch
        never mutated the snapshot (the chunked executor additionally
        PARKS overflowed docs pre-chunk, which this re-apply
        supersedes), so this is exact; with ``prewarm`` the
        new-capacity shapes are already compiled and a warm regrow
        costs about one steady apply."""
        from ..ops.merge_kernel import pad_capacity

        self.grow_count += 1
        _M_GROW.inc()
        self.capacity = new_capacity
        _M_CAPACITY.set(new_capacity)
        self.flight.record("recover-grow", capacity=new_capacity)
        if self._prev_table is None:  # pragma: no cover - first flush
            self._prev_table = make_table(self.max_docs, new_capacity)
        else:
            self._prev_table = pad_capacity(
                self._prev_table, new_capacity
            )
        # fresh fodder at the NEW capacity: the re-apply must ride the
        # same (prewarmed) ping-pong program the steady path uses
        self._table = self._apply_program(
            self._prev_table, self._last_program,
            make_table(self.max_docs, new_capacity)
            if self.donate else None,
        )

    def _retire_rows(self, slots: list) -> None:
        """Zero the primary-table count/overflow of ``slots`` — the
        one definition every retirement path (pool admission, host
        eviction, straggler re-applies) uses: reads route elsewhere
        for these docs, and a stale overflow flag would re-trigger
        (or wedge) recovery."""
        if not slots:
            return
        count = np.asarray(self._table.count).copy()
        overflow = np.asarray(self._table.overflow).copy()
        for slot in slots:
            count[slot] = 0
            overflow[slot] = 0
        self._table = self._table._replace(
            count=jnp.asarray(count), overflow=jnp.asarray(overflow),
        )

    def _admit_to_pool(self, slots: list) -> list:
        """Move slots to the sequence-sharded pool; retire their
        primary rows. Returns slots the pool could not hold."""
        # Already-members can reappear here via the pipelined
        # straggler window: a round packed BEFORE their admission
        # settled re-applies their ops onto the retired primary row,
        # which can re-flag overflow. Their pool state is already
        # current (admission replayed the canonical stream, which had
        # these ops), so they need only the row retirement again —
        # not another O(pool-history) replay, and not another count.
        fresh = [s for s in slots if s not in self._pool.row_of]
        # (the admission's full-stream rebuild advances every member's
        # watermark, so nothing it subsumed can dispatch again)
        failed = self._admit_with_retry(fresh) if fresh else []
        admitted = [s for s in slots if s not in failed]
        newly = len([s for s in fresh if s not in failed])
        self.pool_admit_count += newly
        _M_POOL_ADMIT.inc(newly)
        _M_POOLED.set(len(self._pool.members))
        self.flight.record("recover-pool", admitted=newly,
                           failed=len(failed))
        self._retire_rows(admitted)
        for slot in admitted:
            self._queued[slot].clear()  # replayed from the stream
        return failed

    def _admit_with_retry(self, fresh: list) -> list:
        """Pool admission with the chaos seam in front: a transient
        admission fault (fired BEFORE the pool mutates anything)
        retries once; a second fault degrades the slots to host
        eviction — the last-resort tier that always exists — instead
        of wedging the settle boundary. Served text is identical on
        every tier, so the degradation is invisible to readers."""
        for _attempt in (0, 1):
            fault = _SITE_POOL_ADMIT.fire(slots=len(fresh))
            if fault is None:
                return self._pool.admit(fresh, self._streams)
            _M_POOL_FAULTS.labels(tier="seq", op="admit").inc()
        self.flight.record("recover-pool-admit-degraded",
                           slots=len(fresh))
        return list(fresh)

    def _evict(self, slot: int) -> None:
        """Move one document to a host-side scalar oracle replica —
        full fidelity (arbitrary props, unbounded length), off the
        device batch path."""
        # retire the slot's device state FIRST, and even for an
        # already-evicted doc: reads go to the host replica, and a
        # pipelined round that packed before a prior eviction settled
        # can re-apply window ops onto the retired row — its stale
        # overflow flag would otherwise wedge recovery in a loop
        self._retire_rows([slot])
        if slot in self._host:
            return
        from ..ops.host_bridge import decode_stream

        self.evict_count += 1
        _M_EVICT.inc()
        self.flight.record("recover-evict", slot=slot)
        if self._pool is not None and slot in self._pool.row_of:
            # remove() is bookkeeping only: rebuild HERE so every
            # eviction path (dispatch overflow, ingest's
            # tensor-inexpressible ValueError, pool-admission failure)
            # leaves the remaining members' rows consistent
            self._pool.remove(slot)
            self._pool.rebuild(self._streams)
        obs = MergeTreeClient(f"sidecar-host-{slot}")
        obs.start_collaboration(f"sidecar-host-{slot}")
        self._host[slot] = obs
        _M_HOSTED.set(len(self._host))
        if self._pool is not None:
            _M_POOLED.set(len(self._pool.members))
        self._queued[slot].clear()
        for msg in decode_stream(self._streams[slot]):
            obs.apply_msg(msg)

    # ------------------------------------------------------------------
    # reads (service-side summarization / validation)

    def _slot(self, document_id: str, datastore_id: str,
              channel_id: str) -> int:
        return self._slots[(document_id, datastore_id, channel_id)]

    def text(self, document_id: str, datastore_id: str,
             channel_id: str) -> str:
        self._settle()
        slot = self._slot(document_id, datastore_id, channel_id)
        if slot in self._host:
            return self._host[slot].get_text()
        if self._pool is not None and slot in self._pool.row_of:
            return extract_text(
                self._pool.fetch(), self._streams[slot],
                self._pool.row_of[slot],
            )
        return extract_text(fetch(self._table), self._streams[slot], slot)

    def signature(self, document_id: str, datastore_id: str,
                  channel_id: str) -> tuple:
        self._settle()
        slot = self._slot(document_id, datastore_id, channel_id)
        if slot in self._host:
            return self._host_signature(slot)
        if self._pool is not None and slot in self._pool.row_of:
            return extract_signature(
                self._pool.fetch(), self._streams[slot],
                self._pool.row_of[slot],
            )
        return extract_signature(
            fetch(self._table), self._streams[slot], slot
        )

    def _host_signature(self, slot: int) -> tuple:
        from ..ops.host_bridge import interned_signature

        return interned_signature(self._host[slot], self._streams[slot])

    def host_mode_docs(self) -> int:
        return len(self._host)

    def pooled_docs(self) -> int:
        return len(self._pool.members) if self._pool else 0

    def overflowed(self) -> bool:
        """True only if a document is CURRENTLY wrong (should never
        happen: recovery runs inside the settle boundary)."""
        self._settle()
        return bool(np.asarray(self._table.overflow).any())
