"""TPU merge sidecar: device-resident merge state for the service
plane.

The north star (BASELINE.json): the ordering service's op stream is
batched into padded tensors and merge resolution runs on-device across
thousands of documents per dispatch, while the per-client host path
stays untouched. The sidecar subscribes to sequenced channel streams
(deli out-topic / broadcaster fan-out), accumulates per-document
windows, applies them with ``ops.apply_window``, and serves
text/summary state — powering service-side summarization, replay
validation, and the batched benchmarks.

Overflow recovery (VERDICT r1 weak #4): a document that outgrows its
slab or exceeds the interned property channels is never silently
wrong. The sidecar retains every document's sequenced stream, so on
overflow it either REGROWS the slab (2x, re-replaying all documents in
chunked dispatches — the capacity ladder) or, past ``max_capacity``,
EVICTS the document to a host-side scalar oracle replica that serves
the same text/signature reads.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..models.mergetree import MergeTreeClient
from ..ops import (
    DocStream,
    OpBatch,
    apply_window,
    compact,
    extract_signature,
    extract_text,
    fetch,
    make_table,
)
from ..ops.host_bridge import OP_FIELDS
from ..ops.segment_table import KIND_NOOP
from ..protocol.messages import MessageType, SequencedMessage


class TpuMergeSidecar:
    """Batched merge state for up to ``max_docs`` sequence channels.

    One tracked channel (doc slot) = one (document, datastore, channel)
    sequence stream. ``ingest`` consumes the document's sequenced
    envelope stream; ``apply`` flushes accumulated windows to the
    device in a single dispatch.
    """

    def __init__(self, max_docs: int = 1024, capacity: int = 1024,
                 compact_every: int = 8, max_capacity: int = 16384):
        self.max_docs = max_docs
        self.capacity = capacity
        self.max_capacity = max_capacity
        self._table = make_table(max_docs, capacity)
        self._slots: dict[tuple[str, str, str], int] = {}
        # per-document slot index: ingest is called once per sequenced
        # message per document — scanning every tracked channel there
        # was accidentally O(docs) per message (O(docs^2) per window)
        self._doc_slots: dict[str, list[tuple[int, str, str]]] = {}
        # the encoded stream is the single canonical per-doc history:
        # grow re-replays it on device, eviction decodes it back into
        # sequenced messages for the scalar replica (no duplicate raw
        # log — advisor r2)
        self._streams: list[DocStream] = []
        self._queued: list[list[dict]] = []
        # slot -> host oracle replica (evicted documents)
        self._host: dict[int, MergeTreeClient] = {}
        self._applies = 0
        self._compact_every = compact_every
        self.grow_count = 0
        self.evict_count = 0

    # ------------------------------------------------------------------
    # registration + ingest

    def track(self, document_id: str, datastore_id: str,
              channel_id: str) -> int:
        key = (document_id, datastore_id, channel_id)
        if key in self._slots:
            return self._slots[key]
        if len(self._streams) >= self.max_docs:
            raise RuntimeError("sidecar document capacity exhausted")
        slot = len(self._streams)
        self._slots[key] = slot
        self._doc_slots.setdefault(document_id, []).append(
            (slot, datastore_id, channel_id)
        )
        self._streams.append(DocStream())
        self._queued.append([])
        return slot

    def subscribe(self, server, document_id: str, datastore_id: str,
                  channel_id: str) -> None:
        """Attach to a LocalServer document's broadcaster (the
        sidecar's place in the pipeline: after deli, beside
        scriptorium)."""
        self.track(document_id, datastore_id, channel_id)
        orderer = server.get_orderer(document_id)
        orderer.broadcaster.subscribe(
            f"tpu-sidecar/{document_id}/{datastore_id}/{channel_id}",
            lambda msg: self.ingest(document_id, msg),
        )

    def ingest(self, document_id: str, msg: SequencedMessage) -> None:
        """Consume one sequenced message of a document: channel ops for
        tracked channels encode as kernel ops; everything else becomes
        a NOOP that still advances the collab window."""
        for slot, ds_id, ch_id in self._doc_slots.get(document_id, ()):
            stream = self._streams[slot]
            envelope = msg.contents if isinstance(msg.contents, dict) else {}
            if (
                msg.type == MessageType.OPERATION
                and envelope.get("kind", "op") == "op"
                and envelope.get("address") == ds_id
                and envelope.get("channel") == ch_id
            ):
                inner = dataclasses.replace(
                    msg, contents=envelope["contents"]
                )
            else:
                inner = dataclasses.replace(
                    msg, type=MessageType.NO_OP, contents=None,
                    client_id=None,
                )
            if slot in self._host:
                # evicted: the live replica is the state; no history
                # retention needed (eviction is one-way)
                self._host[slot].apply_msg(inner)
                continue
            before = len(stream.ops)
            before_payloads = len(stream.payloads)
            try:
                self._encode(stream, inner)
            except ValueError:
                # inexpressible in tensor form (more interned property
                # channels than PROP_CHANNELS, or a 33rd client): this
                # document leaves the device path. Roll the partial
                # encode back so the canonical stream stays exact, then
                # the full-fidelity host replica takes over — seeded by
                # decoding the stream, plus the message that failed.
                del stream.ops[before:]
                del stream.payloads[before_payloads:]
                self._evict(slot)
                self._host[slot].apply_msg(inner)
                continue
            self._queued[slot].extend(stream.ops[before:])

    @staticmethod
    def _encode(stream: DocStream, inner: SequencedMessage) -> None:
        if inner.type == MessageType.OPERATION:
            stream.add_message(inner)
        else:
            stream.add_noop(inner.minimum_sequence_number)

    # ------------------------------------------------------------------
    # device application

    @property
    def queued_ops(self) -> int:
        return sum(len(q) for q in self._queued)

    def apply(self) -> int:
        """Flush all queued windows in one batched dispatch. Returns
        the number of real (non-noop) ops applied."""
        if not self._queued or self.queued_ops == 0:
            return 0
        real = self._dispatch()
        self._applies += 1
        if self._applies % self._compact_every == 0:
            self._table = compact(self._table)
        if bool(np.asarray(self._table.overflow).any()):
            self._recover()
        return real

    def _dispatch(self) -> int:
        from ..ops.host_bridge import coalesce_noops

        docs = self.max_docs
        # Coalesce noop runs at pack time (safe here: the queue is
        # consumed whole), then pad the window to a power-of-two
        # bucket: ``apply_window`` is compiled per (docs, window)
        # shape, and an exact-fit window would recompile on nearly
        # every flush (20-40s each on the real chip). Pow2 bucketing
        # bounds the shape count to log(n).
        packed = [coalesce_noops(q) for q in self._queued]
        window = max(len(p) for p in packed)
        bucket = 16
        while bucket < window:
            bucket *= 2
        arrays = {f: np.zeros((docs, bucket), np.int32)
                  for f in OP_FIELDS}
        arrays["kind"][:] = KIND_NOOP
        real = 0
        for slot, (queue, ops) in enumerate(
            zip(self._queued, packed)
        ):
            if ops:
                block = np.array(
                    [[op[f] for f in OP_FIELDS] for op in ops],
                    np.int32,
                )
                for i, f in enumerate(OP_FIELDS):
                    arrays[f][slot, : len(ops)] = block[:, i]
                real += int((block[:, 0] != KIND_NOOP).sum())
            queue.clear()
        self._table = apply_window(self._table, OpBatch(**arrays))
        return real

    # ------------------------------------------------------------------
    # overflow recovery: grow ladder, then host eviction

    def _recover(self) -> None:
        while True:
            overflowed = np.nonzero(np.asarray(self._table.overflow))[0]
            if overflowed.size == 0:
                return
            if self.capacity * 2 <= self.max_capacity:
                self._grow(self.capacity * 2)
            else:
                for slot in overflowed.tolist():
                    self._evict(slot)
                return

    def _grow(self, new_capacity: int) -> None:
        """Rebuild the whole table at 2x capacity by re-replaying every
        document's encoded stream in chunked batched dispatches (the
        streams are the durable source; the old table is garbage the
        moment one op was skipped)."""
        self.grow_count += 1
        self.capacity = new_capacity
        self._table = make_table(self.max_docs, new_capacity)
        chunk = 256
        longest = max(
            (len(s.ops) for s in self._streams), default=0
        )
        for start in range(0, longest, chunk):
            arrays = {f: np.zeros((self.max_docs, chunk), np.int32)
                      for f in OP_FIELDS}
            arrays["kind"][:] = KIND_NOOP
            for slot, stream in enumerate(self._streams):
                if slot in self._host:
                    continue
                for w, op in enumerate(stream.ops[start:start + chunk]):
                    for f in OP_FIELDS:
                        arrays[f][slot, w] = op[f]
            self._table = apply_window(self._table, OpBatch(**arrays))
            self._table = compact(self._table)
        # everything queued was part of the replayed streams
        for queue in self._queued:
            queue.clear()

    def _evict(self, slot: int) -> None:
        """Move one document to a host-side scalar oracle replica —
        full fidelity (arbitrary props, unbounded length), off the
        device batch path."""
        if slot in self._host:
            return
        from ..ops.host_bridge import decode_stream

        self.evict_count += 1
        obs = MergeTreeClient(f"sidecar-host-{slot}")
        obs.start_collaboration(f"sidecar-host-{slot}")
        self._host[slot] = obs
        self._queued[slot].clear()
        # retire the slot's device state: reads go to the host replica
        # now, and a stale overflow flag would re-trigger recovery
        count = np.asarray(self._table.count).copy()
        overflow = np.asarray(self._table.overflow).copy()
        count[slot] = 0
        overflow[slot] = 0
        self._table = self._table._replace(
            count=jnp.asarray(count), overflow=jnp.asarray(overflow),
        )
        for msg in decode_stream(self._streams[slot]):
            obs.apply_msg(msg)

    # ------------------------------------------------------------------
    # reads (service-side summarization / validation)

    def _slot(self, document_id: str, datastore_id: str,
              channel_id: str) -> int:
        return self._slots[(document_id, datastore_id, channel_id)]

    def text(self, document_id: str, datastore_id: str,
             channel_id: str) -> str:
        slot = self._slot(document_id, datastore_id, channel_id)
        if slot in self._host:
            return self._host[slot].get_text()
        return extract_text(fetch(self._table), self._streams[slot], slot)

    def signature(self, document_id: str, datastore_id: str,
                  channel_id: str) -> tuple:
        slot = self._slot(document_id, datastore_id, channel_id)
        if slot in self._host:
            return self._host_signature(slot)
        return extract_signature(
            fetch(self._table), self._streams[slot], slot
        )

    def _host_signature(self, slot: int) -> tuple:
        from ..ops.host_bridge import interned_signature

        return interned_signature(self._host[slot], self._streams[slot])

    def host_mode_docs(self) -> int:
        return len(self._host)

    def overflowed(self) -> bool:
        """True only if a document is CURRENTLY wrong (should never
        happen: recovery runs inside apply)."""
        return bool(np.asarray(self._table.overflow).any())
