"""TPU merge sidecar: device-resident merge state for the service
plane.

The north star (BASELINE.json): the ordering service's op stream is
batched into padded tensors and merge resolution runs on-device across
thousands of documents per dispatch, while the per-client host path
stays untouched. The sidecar subscribes to sequenced channel streams
(deli out-topic / broadcaster fan-out), accumulates per-document
windows, applies them with ``ops.apply_window``, and serves
text/summary state — powering service-side summarization, replay
validation, and the batched benchmarks.

Overflow recovery (VERDICT r1 weak #4): a document that outgrows its
slab or exceeds the interned property channels is never silently
wrong. On overflow the sidecar REGROWS the slab (2x) by padding the
pre-dispatch table snapshot and re-applying just the failed window —
O(window), not O(history); JAX tables are immutable so the snapshot
is a free handle — or, past ``max_capacity``, admits the document to
the sequence-sharded pool / EVICTS it to a host-side scalar oracle
replica (the retained per-document encoded stream is the durable
source for those paths). ``prewarm`` compiles the whole ladder's
shapes up front so neither bucket jumps nor regrows ever hit an XLA
compile mid-serve.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..models.mergetree import MergeTreeClient
from ..ops import (
    DocStream,
    OpBatch,
    apply_window,
    compact,
    extract_signature,
    extract_text,
    fetch,
    make_table,
)
from ..ops.host_bridge import OP_FIELDS
from ..ops.segment_table import KIND_NOOP
from ..protocol.messages import MessageType, SequencedMessage


def _pack_rows(n_rows: int, ops_by_row: dict,
               bucket_floor: int = 16) -> dict:
    """Pack per-row op lists into padded [n_rows, bucket] arrays with
    power-of-two window bucketing — THE op-packing recipe (one
    definition; the primary dispatch, the grow/replay ladders, and the
    pool all use it, so the fill/bucket policy cannot drift)."""
    window = max((len(v) for v in ops_by_row.values()), default=0)
    bucket = bucket_floor
    while bucket < window:
        bucket *= 2
    arrays = {f: np.zeros((n_rows, bucket), np.int32)
              for f in OP_FIELDS}
    arrays["kind"][:] = KIND_NOOP
    for row, ops in ops_by_row.items():
        for w, op in enumerate(ops):
            for f in OP_FIELDS:
                arrays[f][row, w] = op[f]
    return arrays


def _replay_chunked(apply_fn, table, ops_by_row: dict,
                    chunk: int = 256):
    """Re-replay full per-row op histories in fixed-size chunked
    dispatches (the regrow/admission recipe)."""
    n_rows = table.docs
    longest = max((len(v) for v in ops_by_row.values()), default=0)
    for start in range(0, longest, chunk):
        arrays = _pack_rows(
            n_rows,
            {r: ops[start:start + chunk]
             for r, ops in ops_by_row.items()},
            bucket_floor=chunk,
        )
        table = apply_fn(table, arrays)
    return table


class SeqShardedPool:
    """Long-document tier (SURVEY §5.7 in the PRODUCT path): documents
    that outgrow the primary slab ladder move to a table whose SLOT
    axis is sharded across a device mesh — per-document capacity =
    n_seq_devices x the primary ladder top — instead of leaving the
    device path entirely (host eviction becomes the LAST resort, for
    documents that exceed even the pooled capacity or are
    tensor-inexpressible).

    Admissions are rare (a document must exhaust the primary ladder),
    so the pool keeps its machinery simple and correct: admitting
    rebuilds the pool table at the next power-of-two row count and
    re-replays every member's canonical encoded stream in chunked
    sequence-sharded dispatches (same recipe as the primary ladder's
    regrow)."""

    def __init__(self, mesh, per_doc_capacity: int):
        from ..parallel.seq_shard import SEQ_AXIS

        n_seq = mesh.shape[SEQ_AXIS]
        if per_doc_capacity % n_seq or per_doc_capacity // n_seq < 2:
            raise ValueError(
                f"pool capacity {per_doc_capacity} invalid for "
                f"{n_seq}-way seq mesh"
            )
        doc_axes = [a for a in mesh.axis_names if a != SEQ_AXIS]
        if doc_axes and mesh.shape[doc_axes[0]] != 1:
            raise ValueError(
                "pool requires an unsharded doc axis (doc_shards=1): "
                "row admissions don't track a sharded row axis"
            )
        self.mesh = mesh
        self.capacity = per_doc_capacity
        self.members: list[int] = []      # sidecar slot per pool row
        self.row_of: dict[int, int] = {}  # sidecar slot -> row
        self._table = None

    def _bucket(self) -> int:
        n = max(1, len(self.members))
        b = 1
        while b < n:
            b *= 2
        return b

    def _apply(self, table, arrays):
        from ..parallel import apply_window_seq_sharded

        # compact after every pool dispatch: remove-heavy histories
        # otherwise accumulate dead segments until they overflow a
        # pool that could easily hold the live text (the primary
        # ladder's _grow compacts per chunk for the same reason)
        return compact(apply_window_seq_sharded(
            table, OpBatch(**arrays), self.mesh
        ))

    def _replay_all(self, streams) -> None:
        """Rebuild the pool table and re-replay every member's stream
        (chunked sequence-sharded dispatches)."""
        if not self.members:
            self._table = None
            return
        table = make_table(self._bucket(), self.capacity)
        # chunk must leave headroom for the WORST-CASE transient
        # growth inside one chunk (each op can add 2 slots; compaction
        # only runs between chunks): chunk=256 against a small pool
        # would overflow on history alone even when the live set fits
        chunk = max(16, min(256, self.capacity // 4))
        self._table = _replay_chunked(
            self._apply, table,
            {row: streams[slot].ops
             for row, slot in enumerate(self.members)},
            chunk=chunk,
        )

    def admit(self, slots: list, streams) -> list:
        """Admit sidecar slots; returns the slots that FAILED (exceed
        even pooled capacity) and were rolled back out."""
        for slot in slots:
            if slot not in self.row_of:
                self.row_of[slot] = len(self.members)
                self.members.append(slot)
        self._replay_all(streams)
        failed = self.overflowed_slots()
        if failed:
            for slot in failed:
                self.remove(slot)
            self._replay_all(streams)
        return failed

    def remove(self, slot: int) -> None:
        """Bookkeeping only — the table still holds the removed row's
        data and flags at the OLD indices. Callers MUST follow with
        rebuild()/ _replay_all() before the next read or dispatch, or
        remaining members read the wrong rows and stale overflow flags
        evict innocent documents."""
        if slot not in self.row_of:
            return
        row = self.row_of.pop(slot)
        self.members.pop(row)
        for s2, r2 in self.row_of.items():
            if r2 > row:
                self.row_of[s2] = r2 - 1

    def rebuild(self, streams) -> None:
        self._replay_all(streams)

    def dispatch(self, packed_by_slot: dict) -> list:
        """Apply queued window ops for pooled docs; returns slots that
        overflowed the pool."""
        if self._table is None or not packed_by_slot:
            return []
        arrays = _pack_rows(self._table.docs, {
            self.row_of[slot]: ops
            for slot, ops in packed_by_slot.items()
            if slot in self.row_of
        })
        self._table = self._apply(self._table, arrays)
        return self.overflowed_slots()

    def overflowed_slots(self) -> list:
        if self._table is None:
            return []
        flags = np.asarray(self._table.overflow)
        return [self.members[r]
                for r in np.nonzero(flags)[0].tolist()
                if r < len(self.members)]

    def fetch(self):
        return fetch(self._table)


class TpuMergeSidecar:
    """Batched merge state for up to ``max_docs`` sequence channels.

    One tracked channel (doc slot) = one (document, datastore, channel)
    sequence stream. ``ingest`` consumes the document's sequenced
    envelope stream; ``apply`` flushes accumulated windows to the
    device in a single dispatch.
    """

    def __init__(self, max_docs: int = 1024, capacity: int = 1024,
                 compact_every: int = 8, max_capacity: int = 16384,
                 seq_mesh=None, pool_capacity: Optional[int] = None):
        self.max_docs = max_docs
        self.capacity = capacity
        self.max_capacity = max_capacity
        # long-document tier: past the ladder top, docs move to a
        # sequence-sharded pool on this mesh (SURVEY §5.7) before any
        # host eviction
        self._pool: Optional[SeqShardedPool] = None
        if seq_mesh is not None:
            if pool_capacity is None:
                from ..parallel.seq_shard import SEQ_AXIS

                pool_capacity = max_capacity * seq_mesh.shape[SEQ_AXIS]
            self._pool = SeqShardedPool(seq_mesh, pool_capacity)
        self.pool_admit_count = 0
        self._table = make_table(max_docs, capacity)
        self._slots: dict[tuple[str, str, str], int] = {}
        # per-document slot index: ingest is called once per sequenced
        # message per document — scanning every tracked channel there
        # was accidentally O(docs) per message (O(docs^2) per window)
        self._doc_slots: dict[str, list[tuple[int, str, str]]] = {}
        # the encoded stream is the single canonical per-doc history:
        # grow re-replays it on device, eviction decodes it back into
        # sequenced messages for the scalar replica (no duplicate raw
        # log — advisor r2)
        self._streams: list[DocStream] = []
        self._queued: list[list[dict]] = []
        # slot -> host oracle replica (evicted documents)
        self._host: dict[int, MergeTreeClient] = {}
        self._prev_table = None    # pre-dispatch snapshot (regrow)
        self._last_arrays = None   # the window that snapshot predates
        self._applies = 0
        self._compact_every = compact_every
        self.grow_count = 0
        self.evict_count = 0

    # ------------------------------------------------------------------
    # registration + ingest

    def track(self, document_id: str, datastore_id: str,
              channel_id: str) -> int:
        key = (document_id, datastore_id, channel_id)
        if key in self._slots:
            return self._slots[key]
        if len(self._streams) >= self.max_docs:
            raise RuntimeError("sidecar document capacity exhausted")
        slot = len(self._streams)
        self._slots[key] = slot
        self._doc_slots.setdefault(document_id, []).append(
            (slot, datastore_id, channel_id)
        )
        self._streams.append(DocStream())
        self._queued.append([])
        return slot

    def subscribe(self, server, document_id: str, datastore_id: str,
                  channel_id: str) -> None:
        """Attach to a LocalServer document's broadcaster (the
        sidecar's place in the pipeline: after deli, beside
        scriptorium)."""
        self.track(document_id, datastore_id, channel_id)
        orderer = server.get_orderer(document_id)
        orderer.broadcaster.subscribe(
            f"tpu-sidecar/{document_id}/{datastore_id}/{channel_id}",
            lambda msg: self.ingest(document_id, msg),
        )

    def ingest(self, document_id: str, msg: SequencedMessage) -> None:
        """Consume one sequenced message of a document: channel ops for
        tracked channels encode as kernel ops; everything else becomes
        a NOOP that still advances the collab window."""
        for slot, ds_id, ch_id in self._doc_slots.get(document_id, ()):
            stream = self._streams[slot]
            envelope = msg.contents if isinstance(msg.contents, dict) else {}
            if (
                msg.type == MessageType.OPERATION
                and envelope.get("kind", "op") == "op"
                and envelope.get("address") == ds_id
                and envelope.get("channel") == ch_id
            ):
                inner = dataclasses.replace(
                    msg, contents=envelope["contents"]
                )
            else:
                inner = dataclasses.replace(
                    msg, type=MessageType.NO_OP, contents=None,
                    client_id=None,
                )
            if slot in self._host:
                # evicted: the live replica is the state; no history
                # retention needed (eviction is one-way)
                self._host[slot].apply_msg(inner)
                continue
            before = len(stream.ops)
            before_payloads = len(stream.payloads)
            try:
                self._encode(stream, inner)
            except ValueError:
                # inexpressible in tensor form (more interned property
                # channels than PROP_CHANNELS, or a 33rd client): this
                # document leaves the device path. Roll the partial
                # encode back so the canonical stream stays exact, then
                # the full-fidelity host replica takes over — seeded by
                # decoding the stream, plus the message that failed.
                del stream.ops[before:]
                del stream.payloads[before_payloads:]
                self._evict(slot)
                self._host[slot].apply_msg(inner)
                continue
            self._queued[slot].extend(stream.ops[before:])

    @staticmethod
    def _encode(stream: DocStream, inner: SequencedMessage) -> None:
        if inner.type == MessageType.OPERATION:
            stream.add_message(inner)
        else:
            stream.add_noop(inner.minimum_sequence_number)

    # ------------------------------------------------------------------
    # device application

    @property
    def queued_ops(self) -> int:
        return sum(len(q) for q in self._queued)

    def apply(self) -> int:
        """Flush all queued windows in one batched dispatch. Returns
        the number of real (non-noop) ops applied."""
        if not self._queued or self.queued_ops == 0:
            return 0
        real = self._dispatch()
        self._applies += 1
        if self._applies % self._compact_every == 0:
            self._table = compact(self._table)
        if bool(np.asarray(self._table.overflow).any()):
            self._recover()
        return real

    def prewarm(self, max_bucket: int = 64) -> float:
        """Compile every shape the capacity ladder can reach — each
        rung's apply_window at every pow2 window bucket up to
        ``max_bucket``, compact, and the pad step between rungs — so
        neither steady traffic (a window crossing into a new bucket)
        nor a regrow ever hits an XLA compile mid-serve (VERDICT r3
        weak #5; the persistent compilation cache makes repeat
        processes skip the cost entirely). Returns seconds spent."""
        from ..ops.merge_kernel import pad_capacity

        t0 = time.perf_counter()
        rung = self.capacity
        dummy_prev = None
        while True:
            table = make_table(self.max_docs, rung)
            bucket = 16
            while bucket <= max_bucket:
                arrays = _pack_rows(self.max_docs, {0: [dict(
                    kind=KIND_NOOP, pos1=0, pos2=0, seq=0, refseq=0,
                    client=0, op_id=0, length=0, is_marker=0,
                    prop_key=0, prop_val=0, min_seq=0,
                )]}, bucket_floor=bucket)
                table = apply_window(table, OpBatch(**arrays))
                bucket *= 2
            table = compact(table)
            if dummy_prev is not None:
                pad_capacity(dummy_prev, rung)
            dummy_prev = table
            if rung >= self.max_capacity:
                break
            rung *= 2
        np.asarray(table.count)  # force completion
        return time.perf_counter() - t0

    def _dispatch(self) -> int:
        from ..ops.host_bridge import coalesce_noops

        docs = self.max_docs
        # Coalesce noop runs at pack time (safe here: the queue is
        # consumed whole), then pad the window to a power-of-two
        # bucket: ``apply_window`` is compiled per (docs, window)
        # shape, and an exact-fit window would recompile on nearly
        # every flush (20-40s each on the real chip). Pow2 bucketing
        # bounds the shape count to log(n).
        packed = [coalesce_noops(q) for q in self._queued]
        pool_packed = {}
        if self._pool is not None:
            for slot in list(self._pool.row_of):
                if packed[slot]:
                    pool_packed[slot] = packed[slot]
                    packed[slot] = []
        arrays = _pack_rows(
            docs, {slot: ops for slot, ops in enumerate(packed) if ops}
        )
        real = sum(
            1 for ops in packed for op in ops
            if op["kind"] != KIND_NOOP
        )
        for queue in self._queued:
            queue.clear()
        # free pre-dispatch snapshot (immutable arrays): if this window
        # overflows, recovery pads THIS table and re-applies THIS
        # window instead of re-replaying history
        self._prev_table = self._table
        self._last_arrays = arrays
        self._table = apply_window(self._table, OpBatch(**arrays))
        if pool_packed:
            real += sum(
                1 for ops in pool_packed.values()
                for op in ops if op["kind"] != KIND_NOOP
            )
            for slot in self._pool.dispatch(pool_packed):
                self._evict(slot)  # beyond even pooled capacity
                # (_evict rebuilds the pool for the survivors)
        return real

    # ------------------------------------------------------------------
    # overflow recovery: grow ladder, then seq-sharded pool, then
    # host eviction

    def _recover(self) -> None:
        while True:
            overflowed = np.nonzero(np.asarray(self._table.overflow))[0]
            if overflowed.size == 0:
                return
            if self.capacity * 2 <= self.max_capacity:
                self._grow(self.capacity * 2)
            elif self._pool is not None:
                slots = overflowed.tolist()
                failed = self._admit_to_pool(slots)
                for slot in failed:
                    self._evict(slot)
                return
            else:
                for slot in overflowed.tolist():
                    self._evict(slot)
                return

    def _grow(self, new_capacity: int) -> None:
        """Grow the slab 2x and retry the failed window: pad the
        pre-dispatch snapshot (content-preserving, one kernel) and
        re-apply the SAME window at the new capacity. O(window) rather
        than the old full-history re-replay — the failed dispatch
        never mutated the snapshot, so this is exact; with ``prewarm``
        the new-capacity shapes are already compiled and a warm regrow
        costs about one steady apply."""
        from ..ops.merge_kernel import pad_capacity

        self.grow_count += 1
        self.capacity = new_capacity
        if self._prev_table is None:  # pragma: no cover - first flush
            self._prev_table = make_table(self.max_docs, new_capacity)
        else:
            self._prev_table = pad_capacity(
                self._prev_table, new_capacity
            )
        self._table = apply_window(
            self._prev_table, OpBatch(**self._last_arrays)
        )

    def _admit_to_pool(self, slots: list) -> list:
        """Move slots to the sequence-sharded pool; retire their
        primary rows. Returns slots the pool could not hold."""
        failed = self._pool.admit(slots, self._streams)
        admitted = [s for s in slots if s not in failed]
        self.pool_admit_count += len(admitted)
        if admitted:
            count = np.asarray(self._table.count).copy()
            overflow = np.asarray(self._table.overflow).copy()
            for slot in admitted:
                count[slot] = 0
                overflow[slot] = 0
                self._queued[slot].clear()  # replayed from the stream
            self._table = self._table._replace(
                count=jnp.asarray(count),
                overflow=jnp.asarray(overflow),
            )
        return failed

    def _evict(self, slot: int) -> None:
        """Move one document to a host-side scalar oracle replica —
        full fidelity (arbitrary props, unbounded length), off the
        device batch path."""
        if slot in self._host:
            return
        from ..ops.host_bridge import decode_stream

        self.evict_count += 1
        if self._pool is not None and slot in self._pool.row_of:
            # remove() is bookkeeping only: rebuild HERE so every
            # eviction path (dispatch overflow, ingest's
            # tensor-inexpressible ValueError, pool-admission failure)
            # leaves the remaining members' rows consistent
            self._pool.remove(slot)
            self._pool.rebuild(self._streams)
        obs = MergeTreeClient(f"sidecar-host-{slot}")
        obs.start_collaboration(f"sidecar-host-{slot}")
        self._host[slot] = obs
        self._queued[slot].clear()
        # retire the slot's device state: reads go to the host replica
        # now, and a stale overflow flag would re-trigger recovery
        count = np.asarray(self._table.count).copy()
        overflow = np.asarray(self._table.overflow).copy()
        count[slot] = 0
        overflow[slot] = 0
        self._table = self._table._replace(
            count=jnp.asarray(count), overflow=jnp.asarray(overflow),
        )
        for msg in decode_stream(self._streams[slot]):
            obs.apply_msg(msg)

    # ------------------------------------------------------------------
    # reads (service-side summarization / validation)

    def _slot(self, document_id: str, datastore_id: str,
              channel_id: str) -> int:
        return self._slots[(document_id, datastore_id, channel_id)]

    def text(self, document_id: str, datastore_id: str,
             channel_id: str) -> str:
        slot = self._slot(document_id, datastore_id, channel_id)
        if slot in self._host:
            return self._host[slot].get_text()
        if self._pool is not None and slot in self._pool.row_of:
            return extract_text(
                self._pool.fetch(), self._streams[slot],
                self._pool.row_of[slot],
            )
        return extract_text(fetch(self._table), self._streams[slot], slot)

    def signature(self, document_id: str, datastore_id: str,
                  channel_id: str) -> tuple:
        slot = self._slot(document_id, datastore_id, channel_id)
        if slot in self._host:
            return self._host_signature(slot)
        if self._pool is not None and slot in self._pool.row_of:
            return extract_signature(
                self._pool.fetch(), self._streams[slot],
                self._pool.row_of[slot],
            )
        return extract_signature(
            fetch(self._table), self._streams[slot], slot
        )

    def _host_signature(self, slot: int) -> tuple:
        from ..ops.host_bridge import interned_signature

        return interned_signature(self._host[slot], self._streams[slot])

    def host_mode_docs(self) -> int:
        return len(self._host)

    def pooled_docs(self) -> int:
        return len(self._pool.members) if self._pool else 0

    def overflowed(self) -> bool:
        """True only if a document is CURRENTLY wrong (should never
        happen: recovery runs inside apply)."""
        return bool(np.asarray(self._table.overflow).any())
