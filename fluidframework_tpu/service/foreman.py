"""Foreman lambda — service-side task routing to agent workers.

Reference: server/routerlicious/packages/lambdas/src/foreman/ — the
lambda that watches the sequenced stream for help requests
("RemoteHelp" messages a runtime emits when it wants service-side
work: spell-check, translation, snapshot generation) and ROUTES each
task to a registered agent worker, rebalancing when workers come and
go. It completes the lambda inventory next to copier (raw capture),
scriptorium (log append), broadcaster (fan-out) and scribe
(summaries).

TPU-repo construction: ``ForemanLambda`` subscribes like any other
lambda (LocalOrderer stage or a Partition record hook). Help requests
are sequenced OPERATION envelopes ``{"kind": "help", "tasks": [...]}``
(the runtime-side emitter is ``request_help``). Routing is
deterministic least-loaded-first over the agents whose declared
capabilities cover the task, so every replica of the foreman reaches
the same assignment from the same stream — the same
determinism-by-sequencing rule every consensus component here uses.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..protocol.messages import MessageType, SequencedMessage


def help_envelope(tasks: list[str]) -> dict:
    """Contents of a help-request op (runtime -> service)."""
    return {"kind": "help", "tasks": list(tasks)}


@dataclass
class _Agent:
    name: str
    capabilities: frozenset
    run: Optional[Callable[[str, SequencedMessage], Any]]
    assigned: list = field(default_factory=list)


class ForemanLambda:
    """Routes sequenced help requests to registered agent workers."""

    def __init__(self) -> None:
        self._agents: dict[str, _Agent] = {}
        # task -> agent name (live assignments)
        self.assignments: dict[str, str] = {}
        # tasks no capable agent could take (retried on registration)
        self.unassigned: list[tuple[str, SequencedMessage]] = []

    # -- worker pool ---------------------------------------------------

    def register_agent(self, name: str, capabilities,
                       run: Optional[Callable] = None) -> None:
        """An agent worker joins the pool; queued tasks it can serve
        are handed over immediately. Re-registering a live name (a
        restarted worker) first releases its old assignments so they
        reroute instead of sticking to the dead incarnation."""
        if name in self._agents:
            self.unregister_agent(name)
        self._agents[name] = _Agent(
            name, frozenset(capabilities), run
        )
        still: list = []
        for task, msg in self.unassigned:
            if not self._assign(task, msg):
                still.append((task, msg))
        self.unassigned = still

    def unregister_agent(self, name: str) -> None:
        """Worker left (process death / rebalance): its tasks REROUTE
        to surviving capable agents or queue as unassigned."""
        agent = self._agents.pop(name, None)
        if agent is None:
            return
        for task, msg in agent.assigned:
            self.assignments.pop(task, None)
            if not self._assign(task, msg):
                self.unassigned.append((task, msg))

    def agent_load(self, name: str) -> int:
        return len(self._agents[name].assigned)

    # -- lambda surface --------------------------------------------------

    def handler(self, msg: SequencedMessage) -> None:
        """Stage hook: consume one sequenced message."""
        if msg.type != MessageType.OPERATION:
            return
        contents = msg.contents if isinstance(msg.contents, dict) \
            else {}
        if contents.get("kind") != "help":
            return
        for task in contents.get("tasks", ()):
            if task in self.assignments or any(
                t == task for t, _ in self.unassigned
            ):
                continue  # already routed/queued (duplicate request)
            if not self._assign(task, msg):
                self.unassigned.append((task, msg))

    def complete(self, task: str) -> None:
        """Agent finished a task: free its slot."""
        name = self.assignments.pop(task, None)
        if name and name in self._agents:
            agent = self._agents[name]
            agent.assigned = [
                (t, m) for t, m in agent.assigned if t != task
            ]

    # -- routing ---------------------------------------------------------

    def _assign(self, task: str, msg: SequencedMessage) -> bool:
        capable = [
            a for a in self._agents.values()
            if task in a.capabilities or "*" in a.capabilities
        ]
        if not capable:
            return False
        # deterministic: least loaded, name as tiebreak
        agent = min(capable, key=lambda a: (len(a.assigned), a.name))
        agent.assigned.append((task, msg))
        self.assignments[task] = agent.name
        if agent.run is not None:
            agent.run(task, msg)
        return True
