"""Service plane: sequencer (deli), lambda pipeline, in-proc orderer,
local server, TPU merge sidecar.

Reference analogue: server/routerlicious/packages/*.
"""
from .ingress import AlfredServer
from .lambdas import (
    BroadcasterLambda,
    OpLog,
    ScribeLambda,
    ScriptoriumLambda,
    SummaryStore,
)
from .local_orderer import LocalOrderer
from .local_server import DeltaConnection, LocalServer
from .sequencer import DocumentSequencer, TicketResult
from .tpu_sidecar import TpuMergeSidecar

__all__ = [
    "AlfredServer",
    "BroadcasterLambda",
    "DeltaConnection",
    "DocumentSequencer",
    "LocalOrderer",
    "LocalServer",
    "OpLog",
    "ScribeLambda",
    "ScriptoriumLambda",
    "SummaryStore",
    "TicketResult",
    "TpuMergeSidecar",
]
