"""Service plane: sequencer (deli), lambda pipeline, in-proc orderer,
local server, TPU merge sidecar.

Reference analogue: server/routerlicious/packages/*.
"""
from .ingress import AlfredServer
from .lambdas import (
    BroadcasterLambda,
    CopierLambda,
    OpLog,
    ScribeLambda,
    ScriptoriumLambda,
    SummaryStore,
)
from .local_orderer import LocalOrderer
from .local_server import DeltaConnection, LocalServer
from .partitioning import (
    CheckpointManager,
    FileOrderingQueue,
    InMemoryOrderingQueue,
    OrderingQueue,
    Partition,
    PartitionedOrderingService,
    partition_for,
)
from .sequencer import DocumentSequencer, TicketResult
from .tenancy import AuthError, Tenant, TenantManager, sign_token
from .tpu_sidecar import TpuMergeSidecar
from .tree_sidecar import ChannelKindRouter, TreeSeqPool, TreeSidecar

__all__ = [
    "AlfredServer",
    "BrokerServer",
    "RemoteOrderingQueue",
    "BroadcasterLambda",
    "CopierLambda",
    "CheckpointManager",
    "DeltaConnection",
    "DocumentSequencer",
    "FileOrderingQueue",
    "InMemoryOrderingQueue",
    "LocalOrderer",
    "LocalServer",
    "OrderingQueue",
    "Partition",
    "PartitionedOrderingService",
    "AuthError",
    "Tenant",
    "TenantManager",
    "sign_token",
    "partition_for",
    "OpLog",
    "ScribeLambda",
    "ScriptoriumLambda",
    "SummaryStore",
    "TicketResult",
    "TpuMergeSidecar",
    "ChannelKindRouter",
    "TreeSeqPool",
    "TreeSidecar",
]


def __getattr__(name):
    # lazy: `python -m fluidframework_tpu.service.broker` runs the
    # broker CLI; an eager import here would pre-load the module and
    # trip runpy's double-import warning
    if name in ("BrokerServer", "RemoteOrderingQueue"):
        from . import broker

        return getattr(broker, name)
    # same lazy treatment: `python -m fluidframework_tpu.service.moira`
    # runs the Materialized History CLI
    if name in ("MaterializedHistoryServer",
                "MaterializedHistoryClient", "MoiraLambda"):
        from . import moira

        return getattr(moira, name)
    raise AttributeError(name)
