"""Service plane: sequencer (deli), orderer pipeline, ingress.

Reference analogue: server/routerlicious/packages/*.
"""
from .sequencer import DocumentSequencer, TicketResult

__all__ = ["DocumentSequencer", "TicketResult"]
