"""Tenancy + auth — the riddler analogue.

Reference: server/routerlicious/packages/routerlicious-base/src/riddler
(tenant CRUD, per-tenant shared secrets) and the token path: clients
present a signed claims token on ``connect_document``
(services-utils jwt validation in alfred; protocol-definitions
ITokenClaims: documentId/tenantId/user/scopes/exp).

Stdlib construction: tokens are HMAC-SHA256-signed JSON claims
(base64url header-free JWS-style ``payload.signature``) — no external
jwt dependency. Scopes follow the reference vocabulary: ``doc:read``,
``doc:write``, ``summary:write``.
"""
from __future__ import annotations

import base64
import hashlib
import hmac
import json
import secrets
import time
from dataclasses import dataclass, field
from typing import Optional

SCOPE_READ = "doc:read"
SCOPE_WRITE = "doc:write"
SCOPE_SUMMARY = "summary:write"
DEFAULT_SCOPES = (SCOPE_READ, SCOPE_WRITE, SCOPE_SUMMARY)


class AuthError(Exception):
    pass


@dataclass
class Tenant:
    tenant_id: str
    key: str
    name: str = ""
    enabled: bool = True
    created_at: float = field(default_factory=time.time)


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def sign_token(key: str, tenant_id: str, document_id: str,
               user: str, scopes=DEFAULT_SCOPES,
               lifetime_s: float = 3600.0) -> str:
    """Create a claims token (the services-client generateToken
    analogue)."""
    claims = {
        "tenantId": tenant_id,
        "documentId": document_id,
        "user": {"id": user},
        "scopes": list(scopes),
        "exp": time.time() + lifetime_s,
        "iat": time.time(),
    }
    payload = _b64(json.dumps(claims, sort_keys=True).encode())
    sig = hmac.new(key.encode(), payload.encode(),
                   hashlib.sha256).digest()
    return f"{payload}.{_b64(sig)}"


class TenantManager:
    """riddler: tenant registry + token validation."""

    def __init__(self):
        self._tenants: dict[str, Tenant] = {}

    def create_tenant(self, tenant_id: str, name: str = "",
                      key: Optional[str] = None) -> Tenant:
        if tenant_id in self._tenants:
            raise ValueError(f"tenant {tenant_id!r} exists")
        t = Tenant(tenant_id, key or secrets.token_hex(32), name)
        self._tenants[tenant_id] = t
        return t

    def get_tenant(self, tenant_id: str) -> Optional[Tenant]:
        return self._tenants.get(tenant_id)

    def disable_tenant(self, tenant_id: str) -> None:
        t = self._tenants.get(tenant_id)
        if t is not None:
            t.enabled = False

    def refresh_key(self, tenant_id: str) -> str:
        t = self._tenants[tenant_id]
        t.key = secrets.token_hex(32)
        return t.key

    # ---- validation (alfred's verifyToken path)

    def validate_token(self, token: str, tenant_id: str,
                       document_id: str,
                       required_scope: str = SCOPE_READ) -> dict:
        """Verify signature/tenant/document/expiry/scope; returns the
        claims. Raises AuthError with a stable reason otherwise."""
        tenant = self._tenants.get(tenant_id)
        if tenant is None or not tenant.enabled:
            raise AuthError(f"unknown or disabled tenant {tenant_id!r}")
        try:
            payload, sig = token.split(".")
            expect = hmac.new(tenant.key.encode(), payload.encode(),
                              hashlib.sha256).digest()
            if not hmac.compare_digest(expect, _unb64(sig)):
                raise AuthError("bad signature")
            claims = json.loads(_unb64(payload))
            if not isinstance(claims, dict):
                # a signed non-object payload is malformed, not a
                # server error: claims.get below must never AttributeError
                raise AuthError("malformed token: claims not an object")
        except AuthError:
            raise
        except Exception as e:  # malformed token shape
            raise AuthError(
                f"malformed token: {type(e).__name__}") from e
        if claims.get("tenantId") != tenant_id:
            raise AuthError("token tenant mismatch")
        if claims.get("documentId") != document_id:
            raise AuthError("token document mismatch")
        if claims.get("exp", 0) < time.time():
            raise AuthError("token expired")
        if required_scope not in claims.get("scopes", []):
            raise AuthError(f"missing scope {required_scope!r}")
        return claims
