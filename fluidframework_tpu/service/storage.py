"""Durable service storage: content-addressed summary trees + a
file-backed op log + checkpoint persistence.

Reference: the storage microservices — historian/gitrest store summary
trees as git trees/blobs (server/historian, server/gitrest), where an
unchanged subtree re-uploaded in a new summary costs nothing because
git is content-addressed; scriptorium's Mongo op collection
(lambdas/src/scriptorium/lambda.ts:20) is the durable sequenced-op
store; deli checkpoints ({sequenceNumber, clients...}) persist so a
restarted partition resumes where it left off
(deli/checkpointContext.ts).

Design notes (TPU-native build):
- ``ContentStore`` hashes canonical JSON with sha256. ``write_tree``
  splits a summary dict into one object per node down to
  ``tree_depth`` levels (protocol / runtime / datastores/<id> /
  channels/<cid>), plus one object per element of any ``chunks`` list
  (the chunked merge-tree snapshot format, snapshotChunks.ts) — so the
  SECOND summary of a mostly-unchanged container writes O(changed
  channels) new objects, not O(container).
- ``SummaryType.Handle`` (summary.ts:55-59): client summaries may
  replace an unchanged subtree with {"__summary_handle__":
  "<path/in/previous/summary>"}; the store resolves handles against
  the previous version at write time, exactly like the service
  expanding incremental summaries against the last acked one.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from typing import Any, Optional

from ..protocol.messages import SequencedMessage
from ..protocol.serialization import message_from_json, message_to_json
from .lambdas import OpLog

HANDLE_KEY = "__summary_handle__"


def _canonical(obj: Any) -> bytes:
    return json.dumps(obj, sort_keys=True, separators=(",", ":")
                      ).encode("utf-8")


class ContentStore:
    """In-memory content-addressed object store (git object database
    analogue)."""

    def __init__(self) -> None:
        self._objects: dict[str, bytes] = {}

    def put(self, obj: Any) -> str:
        data = _canonical(obj)
        sha = hashlib.sha256(data).hexdigest()
        if sha not in self._objects:
            self._store(sha, data)
        return sha

    def get(self, sha: str) -> Any:
        return json.loads(self._load(sha).decode("utf-8"))

    def has(self, sha: str) -> bool:
        return sha in self._objects

    def object_count(self) -> int:
        return len(self._objects)

    # storage hooks (overridden by the file store)
    def _store(self, sha: str, data: bytes) -> None:
        self._objects[sha] = data

    def _load(self, sha: str) -> bytes:
        return self._objects[sha]


class FileContentStore(ContentStore):
    """On-disk object store: objects/<aa>/<sha> (gitrest layout)."""

    def __init__(self, root: str):
        super().__init__()
        self.root = root
        os.makedirs(os.path.join(root, "objects"), exist_ok=True)
        for shard in os.listdir(os.path.join(root, "objects")):
            shard_dir = os.path.join(root, "objects", shard)
            for name in os.listdir(shard_dir):
                self._objects[shard + name] = None  # lazily loaded

    def _path(self, sha: str) -> str:
        return os.path.join(self.root, "objects", sha[:2], sha[2:])

    def _store(self, sha: str, data: bytes) -> None:
        path = self._path(sha)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        self._objects[sha] = None

    def _load(self, sha: str) -> bytes:
        return open(self._path(sha), "rb").read()

    def has(self, sha: str) -> bool:
        return sha in self._objects or os.path.exists(self._path(sha))


_TREE = "__tree__"
_BLOB = "__blob__"
_CHUNKS = "__chunklist__"


class SummaryTreeStore:
    """Versioned summary storage over a ContentStore (the historian
    facade). Splits summaries into per-subtree objects and resolves
    incremental handles."""

    def __init__(self, store: Optional[ContentStore] = None,
                 tree_depth: int = 6):
        # depth 6 reaches protocol / runtime / datastores/<id> /
        # channels/<cid> / {type, content} — the channel's "content"
        # dict lands at depth 0 where the chunk split below engages
        # (verified: at depth 5 the whole multi-chunk snapshot stored
        # as ONE blob and per-chunk reuse never happened)
        self.store = store or ContentStore()
        self.tree_depth = tree_depth

    # -- write ---------------------------------------------------------

    def write(self, summary: dict,
              previous_root: Optional[str] = None) -> str:
        """Store a summary, resolving {"__summary_handle__": path}
        nodes against ``previous_root``; returns the new root sha."""
        resolved = self._resolve_handles(summary, previous_root)
        return self._write_node(resolved, self.tree_depth)

    def _resolve_handles(self, node: Any,
                         previous_root: Optional[str]) -> Any:
        if isinstance(node, dict):
            if HANDLE_KEY in node:
                if previous_root is None:
                    raise ValueError(
                        "summary handle with no previous summary"
                    )
                return self.read_path(previous_root, node[HANDLE_KEY])
            return {
                k: self._resolve_handles(v, previous_root)
                for k, v in node.items()
            }
        if isinstance(node, list):
            return [self._resolve_handles(v, previous_root)
                    for v in node]
        return node

    def _write_node(self, node: Any, depth: int) -> str:
        if depth > 0 and isinstance(node, dict):
            children = {
                k: self._write_node(v, depth - 1)
                for k, v in node.items()
            }
            return self.store.put({_TREE: children})
        if isinstance(node, dict) and isinstance(
            node.get("chunks"), list
        ):
            # chunked snapshot: one object per chunk so append-mostly
            # documents reuse every unchanged chunk
            rest = {k: v for k, v in node.items() if k != "chunks"}
            chunk_shas = [self.store.put(c) for c in node["chunks"]]
            return self.store.put({
                _CHUNKS: chunk_shas, _BLOB: rest,
            })
        return self.store.put({_BLOB: node})

    # -- read ----------------------------------------------------------

    def read(self, root: str) -> dict:
        return self._read_node(root)

    def _read_node(self, sha: str) -> Any:
        obj = self.store.get(sha)
        if _TREE in obj:
            return {
                k: self._read_node(v) for k, v in obj[_TREE].items()
            }
        if _CHUNKS in obj:
            out = dict(obj[_BLOB])
            out["chunks"] = [
                self.store.get(c) for c in obj[_CHUNKS]
            ]
            return out
        return obj[_BLOB]

    def read_path(self, root: str, path: str) -> Any:
        """Resolve "a/b/c" inside a stored summary without
        materializing the whole tree."""
        sha = root
        parts = [p for p in path.split("/") if p]
        for i, part in enumerate(parts):
            obj = self.store.get(sha)
            if _TREE not in obj:
                # descend into a blob's plain dict remainder
                node = self._read_node(sha)
                for rest in parts[i:]:
                    node = node[rest]
                return node
            sha = obj[_TREE][part]
        return self._read_node(sha)


@dataclasses.dataclass
class SummaryVersion:
    sequence_number: int
    root: str
    timestamp: float = dataclasses.field(default_factory=time.time)


class FileOpLog(OpLog):
    """Durable op log: the in-memory OpLog's semantics (contiguity,
    range reads, truncation) with JSONL persistence via the
    _persist_* hooks — same shape as FileContentStore/ContentStore."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        self._ops.append(
                            message_from_json(json.loads(line))
                        )
        self._fh = open(path, "a")

    def _persist_append(self, msg: SequencedMessage) -> None:
        self._fh.write(json.dumps(message_to_json(msg)) + "\n")
        self._fh.flush()

    def _persist_truncate(self) -> None:
        self._fh.close()
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            for m in self._ops:
                f.write(json.dumps(message_to_json(m)) + "\n")
        os.replace(tmp, self.path)
        self._fh = open(self.path, "a")


class DocumentStorage:
    """Per-document durable state: summary versions + op log +
    service checkpoint, all under one directory."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.trees = SummaryTreeStore(
            FileContentStore(os.path.join(root, "store"))
        )
        self.op_log = FileOpLog(os.path.join(root, "ops.jsonl"))
        self._versions_path = os.path.join(root, "versions.jsonl")
        self.versions: list[SummaryVersion] = []
        if os.path.exists(self._versions_path):
            with open(self._versions_path) as f:
                for line in f:
                    if line.strip():
                        self.versions.append(
                            SummaryVersion(**json.loads(line))
                        )
        self._checkpoint_path = os.path.join(root, "checkpoint.json")

    # summaries
    def write_summary(self, sequence_number: int,
                      summary: dict) -> str:
        prev = self.versions[-1].root if self.versions else None
        root = self.trees.write(summary, previous_root=prev)
        return self.commit_summary(sequence_number, root)

    def commit_summary(self, sequence_number: int, root: str) -> str:
        """Durably record a staged tree root as a version (the scribe
        ack of a client-uploaded summary)."""
        version = SummaryVersion(sequence_number, root)
        self.versions.append(version)
        with open(self._versions_path, "a") as f:
            f.write(json.dumps(dataclasses.asdict(version)) + "\n")
        return root

    def latest_summary(self) -> Optional[tuple[int, dict]]:
        if not self.versions:
            return None
        v = self.versions[-1]
        return v.sequence_number, self.trees.read(v.root)

    # service checkpoint (deli/checkpointContext.ts)
    def write_checkpoint(self, state: dict) -> None:
        tmp = self._checkpoint_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, self._checkpoint_path)

    def read_checkpoint(self) -> Optional[dict]:
        if not os.path.exists(self._checkpoint_path):
            return None
        with open(self._checkpoint_path) as f:
            return json.load(f)
