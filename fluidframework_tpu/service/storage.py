"""Durable service storage: content-addressed summary trees + a
file-backed op log + checkpoint persistence.

Reference: the storage microservices — historian/gitrest store summary
trees as git trees/blobs (server/historian, server/gitrest), where an
unchanged subtree re-uploaded in a new summary costs nothing because
git is content-addressed; scriptorium's Mongo op collection
(lambdas/src/scriptorium/lambda.ts:20) is the durable sequenced-op
store; deli checkpoints ({sequenceNumber, clients...}) persist so a
restarted partition resumes where it left off
(deli/checkpointContext.ts).

Design notes (TPU-native build):
- ``ContentStore`` hashes canonical JSON with sha256. ``write_tree``
  splits a summary dict into one object per node down to
  ``tree_depth`` levels (protocol / runtime / datastores/<id> /
  channels/<cid>), plus one object per element of any ``chunks`` list
  (the chunked merge-tree snapshot format, snapshotChunks.ts) — so the
  SECOND summary of a mostly-unchanged container writes O(changed
  channels) new objects, not O(container).
- ``SummaryType.Handle`` (summary.ts:55-59): client summaries may
  replace an unchanged subtree with {"__summary_handle__":
  "<path/in/previous/summary>"}; the store resolves handles against
  the previous version at write time, exactly like the service
  expanding incremental summaries against the last acked one.

CRASH ATOMICITY (docs/ROBUSTNESS.md "storage seams"): a crash may
land mid-write anywhere, so every durable write here either commits
whole or leaves the previous state intact — the write-temp + fsync +
rename protocol for the checkpoint (without the fsync, the rename
can be durable while the data is not, leaving a prefix-truncated
checkpoint.json that parses as garbage — the exact reordered-write
crash state "All File Systems Are Not Created Equal" enumerates),
fsync-per-append for the op log (the ack barrier: the orderer fans
an op out only after scriptorium's append returns, so a fanned-out
op is durable by construction), and torn-TAIL tolerance on every
JSONL load (a crash inside an append leaves a partial last line;
that op was never fanned out, so discarding it loudly is exact —
the client still holds it pending and resubmits). A torn line
ANYWHERE ELSE is real corruption and still fails loudly. The chaos
plane (qos/faults.py) enumerates these states in
tests/test_chaos.py + tests/test_durable_storage.py.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import sys
import time
import zlib
from typing import Any, Callable, Optional

from ..obs import metrics as obs_metrics
from ..protocol.messages import SequencedMessage
from ..protocol.serialization import message_from_json, message_to_json
from ..qos.faults import (
    KIND_CORRUPT,
    KIND_ERROR,
    KIND_ERROR_BURST,
    KIND_TORN_WRITE,
    PLANE,
    TransientIOFault,
)
from .lambdas import OpLog

HANDLE_KEY = "__summary_handle__"

_M_TORN = obs_metrics.REGISTRY.counter(
    "storage_torn_recoveries_total",
    "torn on-disk states discarded on load (crash recovery)",
    labelnames=("file",))
_M_SCRUB = obs_metrics.REGISTRY.counter(
    "storage_scrub_repairs_total",
    "bit-rotted records read-repaired from a quorum peer, by log",
    labelnames=("file",))
_M_DEBRIS = obs_metrics.REGISTRY.counter(
    "storage_crash_debris_cleaned_total",
    "leftover write-then-rename tmp files cleared at startup (the "
    "crash-between-write-and-rename state)", labelnames=("file",))

# chaos seams (docs/ROBUSTNESS.md): the checkpoint write consults its
# site per write (error faults exercise the storage breaker); the
# op-log site exists for the harness's crash-time torn-tail
# enumeration (force()d, never fired mid-run — a torn append IS a
# crash, and the process does not survive it)
_SITE_CHECKPOINT = PLANE.site(
    "storage.checkpoint_write",
    (KIND_ERROR, KIND_ERROR_BURST, KIND_TORN_WRITE))
_SITE_OPLOG = PLANE.site("storage.oplog_append", (KIND_TORN_WRITE,))
# bit rot: a record's bytes flip at rest (a disk sector going bad, not
# a crash). force()d by the harness when it plants corruption — like
# the torn states, the injection is a harness decision the plane
# records, never a mid-run fault draw
_SITE_BITROT = PLANE.site("storage.bitrot", (KIND_CORRUPT,))


def atomic_write(path: str, data: str) -> None:
    """THE crash-atomic write barrier — write-temp + fsync + rename —
    with ONE owner, so the checkpoint, the op-log rewrite and the
    versions rewrite cannot silently diverge on the protocol. Without
    the fsync the rename can become durable before the data, leaving
    a prefix-truncated file under the FINAL name (the reordered-write
    crash state the crash-consistency literature enumerates)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    # fsync the DIRECTORY too: without it the rename itself is not
    # durable — a crash can leave the directory entry pointing at the
    # pre-rewrite inode while later appends (already fsynced to the
    # NEW inode, and acked) vanish with it. The reordered-METADATA
    # sibling of the data state above.
    try:
        dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
    except OSError as e:  # pragma: no cover - exotic fs
        # skipping the directory fsync weakens the crash-durability
        # story for every write through this path — degrade loudly
        print(
            f"atomic_write[{path}]: cannot open directory for fsync "
            f"({e}); rename durability not guaranteed on this fs",
            file=sys.stderr,
        )
        return
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


CRC_KEY = "_crc"


def record_crc(row: dict) -> int:
    """Per-record checksum over the CANONICAL encoding (sorted keys,
    tight separators) of the row WITHOUT its own crc field — so the
    crc survives a round trip through any JSON re-encoder."""
    return zlib.crc32(_canonical(
        {k: v for k, v in row.items() if k != CRC_KEY}))


def jsonl_record(row: dict) -> str:
    """One CRC-stamped JSONL line (op logs, replica logs, queue record
    logs). The crc rides as an OPTIONAL field — the PR4/PR6 interop
    discipline: readers verify it when present and accept legacy rows
    without one, so pre-existing logs keep loading."""
    return json.dumps(dict(row, **{CRC_KEY: record_crc(row)})) + "\n"


class CorruptRecordError(ValueError):
    """A record whose bytes are wrong AT REST — a crc mismatch, or a
    malformed line that is not the torn tail. NOT a crash state: the
    write barriers rule those out, so this is bit rot (or an operator
    mishap) and must either be read-repaired from a quorum peer
    (:func:`scrub_repair_jsonl`) or fail loudly — never served."""

    def __init__(self, msg: str, path: str = "", index: int = -1):
        super().__init__(msg)
        self.path = path
        self.index = index  # 0-based record index in the file


def _check_record_crc(row: dict, label: str, path: str,
                      line_no: int) -> dict:
    """Verify (and strip) an optional per-record crc; raises
    :class:`CorruptRecordError` on mismatch."""
    if CRC_KEY not in row:
        return row  # legacy record: nothing to verify
    want = row[CRC_KEY]
    got = record_crc(row)
    if want != got:
        raise CorruptRecordError(
            f"{label} crc mismatch at line {line_no} of {path!r}: "
            f"stored {want}, computed {got} — bit rot, not a crash "
            "state; scrub-repair it from a quorum peer "
            "(docs/ROBUSTNESS.md)", path=path, index=line_no - 1)
    return {k: v for k, v in row.items() if k != CRC_KEY}


def read_jsonl_tolerant(path: str, label: str) -> tuple[list, bool]:
    """Parse a JSONL file tolerating ONE torn final line (the crash-
    mid-append state). Returns (parsed rows, tail_was_torn). A
    malformed line anywhere but the end — or a crc mismatch ANYWHERE,
    tail included (a completed fsynced write whose bytes changed is
    rot, not a tear) — is corruption, not a crash state: raised,
    never skipped."""
    rows: list = []
    with open(path) as f:
        lines = f.readlines()
    stripped = [ln.strip() for ln in lines]
    for i, line in enumerate(stripped):
        if not line:
            continue
        try:
            row = json.loads(line)
        except ValueError as e:
            if any(stripped[i + 1:]):
                raise CorruptRecordError(
                    f"{label} corrupt at line {i + 1} of {path!r}: "
                    "a non-tail torn record is not a crash state",
                    path=path, index=i,
                ) from e
            _M_TORN.labels(file=label).inc()
            print(
                f"storage: discarding torn {label} tail "
                f"(line {i + 1} of {path!r}) — crash mid-append; "
                "the op was never acked, clients resubmit it",
                file=sys.stderr,
            )
            return rows, True
        rows.append(_check_record_crc(row, label, path, i + 1))
    return rows, False


# ----------------------------------------------------------------------
# the scrubber: detect bit rot per record, read-repair from peers


@dataclasses.dataclass
class ScrubReport:
    """One log's scrub outcome. ``corrupt`` holds the 0-based record
    indexes that failed their crc (or tore mid-file); ``torn_tail``
    is the PR9-recoverable crash state — left for the loader's
    torn-tail discard, NOT treated as rot."""

    path: str
    records: int = 0
    torn_tail: bool = False
    corrupt: list = dataclasses.field(default_factory=list)
    repaired: int = 0


def _scan_jsonl(path: str) -> tuple[list, list[Optional[dict]],
                                    ScrubReport]:
    """(raw lines, parsed rows with None at corrupt slots, report)."""
    report = ScrubReport(path=path)
    with open(path) as f:
        lines = [ln for ln in f.readlines() if ln.strip()]
    rows: list[Optional[dict]] = []
    for i, line in enumerate(lines):
        try:
            row = json.loads(line)
        except ValueError:
            if i == len(lines) - 1:
                report.torn_tail = True
                rows.append(None)
                continue
            report.corrupt.append(i)
            rows.append(None)
            continue
        if CRC_KEY in row and row[CRC_KEY] != record_crc(row):
            report.corrupt.append(i)
            rows.append(None)
            continue
        rows.append({k: v for k, v in row.items() if k != CRC_KEY})
    report.records = len(lines)
    return lines, rows, report


def scrub_jsonl(path: str, label: str) -> ScrubReport:
    """Detect-only pass: classify every record as intact, bit-rotted
    (``corrupt``), or the torn tail."""
    _, _, report = _scan_jsonl(path)
    return report


def scrub_repair_jsonl(
        path: str, label: str,
        fetch: Callable[[int, list], Optional[dict]]) -> ScrubReport:
    """Read-repair: every corrupt record is replaced by the row
    ``fetch(index, rows)`` supplies (a quorum peer's copy — ``rows``
    gives the caller the intact neighbours to anchor identity, e.g.
    a contiguous op log's sequence numbers). A torn TAIL is left
    byte-for-byte for the loader's PR9 discard. ``fetch`` returning
    None means no surviving peer holds the record: raised loudly —
    a quorum-acked record with zero intact copies is data loss, and
    pretending otherwise would serve garbage."""
    lines, rows, report = _scan_jsonl(path)
    if not report.corrupt:
        return report
    out: list[str] = []
    for i, (line, row) in enumerate(zip(lines, rows)):
        if i in report.corrupt:
            repaired = fetch(i, rows)
            if repaired is None:
                raise CorruptRecordError(
                    f"{label} record {i} of {path!r} is corrupt and "
                    "no surviving peer holds an intact copy — "
                    "unrepairable bit rot", path=path, index=i)
            out.append(jsonl_record(
                {k: v for k, v in repaired.items() if k != CRC_KEY}))
            report.repaired += 1
            _M_SCRUB.labels(file=label).inc()
        elif row is None:
            out.append(line)  # the torn tail, verbatim
        else:
            out.append(jsonl_record(row))
    atomic_write(path, "".join(out))
    return report


def read_offset_tolerant(path: str, label: str = "offset") -> int:
    """Parse a committed-offset file, degrading LOUDLY to -1 (no
    commit) on garbage. With commits routed through ``atomic_write``
    a torn offset is unreachable going forward, but a pre-barrier
    data dir can still hold one — and re-consuming from scratch is
    exactly what at-least-once delivery absorbs, while a crash here
    would take the partition down for an operator restart."""
    with open(path) as f:
        raw = f.read().strip()
    try:
        return int(raw or -1)
    except ValueError:
        _M_TORN.labels(file=label).inc()
        print(
            f"storage: committed-offset file {path!r} is "
            f"torn/unparseable ({raw[:40]!r}); treating as no commit "
            "— the consumer re-reads from the log head and the "
            "at-least-once dedupe absorbs the replay",
            file=sys.stderr,
        )
        return -1


def _canonical(obj: Any) -> bytes:
    return json.dumps(obj, sort_keys=True, separators=(",", ":")
                      ).encode("utf-8")


class ContentStore:
    """In-memory content-addressed object store (git object database
    analogue)."""

    def __init__(self) -> None:
        self._objects: dict[str, bytes] = {}

    def put(self, obj: Any) -> str:
        data = _canonical(obj)
        sha = hashlib.sha256(data).hexdigest()
        if sha not in self._objects:
            self._store(sha, data)
        return sha

    def get(self, sha: str) -> Any:
        return json.loads(self._load(sha).decode("utf-8"))

    def has(self, sha: str) -> bool:
        return sha in self._objects

    def object_count(self) -> int:
        return len(self._objects)

    # storage hooks (overridden by the file store)
    def _store(self, sha: str, data: bytes) -> None:
        self._objects[sha] = data

    def _load(self, sha: str) -> bytes:
        return self._objects[sha]


class FileContentStore(ContentStore):
    """On-disk object store: objects/<aa>/<sha> (gitrest layout)."""

    def __init__(self, root: str):
        super().__init__()
        self.root = root
        os.makedirs(os.path.join(root, "objects"), exist_ok=True)
        for shard in os.listdir(os.path.join(root, "objects")):
            shard_dir = os.path.join(root, "objects", shard)
            for name in os.listdir(shard_dir):
                self._objects[shard + name] = None  # lazily loaded

    def _path(self, sha: str) -> str:
        return os.path.join(self.root, "objects", sha[:2], sha[2:])

    def _store(self, sha: str, data: bytes) -> None:
        path = self._path(sha)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        self._objects[sha] = None

    def _load(self, sha: str) -> bytes:
        return open(self._path(sha), "rb").read()

    def has(self, sha: str) -> bool:
        return sha in self._objects or os.path.exists(self._path(sha))


_TREE = "__tree__"
_BLOB = "__blob__"
_CHUNKS = "__chunklist__"


class SummaryTreeStore:
    """Versioned summary storage over a ContentStore (the historian
    facade). Splits summaries into per-subtree objects and resolves
    incremental handles."""

    def __init__(self, store: Optional[ContentStore] = None,
                 tree_depth: int = 6):
        # depth 6 reaches protocol / runtime / datastores/<id> /
        # channels/<cid> / {type, content} — the channel's "content"
        # dict lands at depth 0 where the chunk split below engages
        # (verified: at depth 5 the whole multi-chunk snapshot stored
        # as ONE blob and per-chunk reuse never happened)
        self.store = store or ContentStore()
        self.tree_depth = tree_depth

    # -- write ---------------------------------------------------------

    def write(self, summary: dict,
              previous_root: Optional[str] = None) -> str:
        """Store a summary, resolving {"__summary_handle__": path}
        nodes against ``previous_root``; returns the new root sha."""
        resolved = self._resolve_handles(summary, previous_root)
        return self._write_node(resolved, self.tree_depth)

    def _resolve_handles(self, node: Any,
                         previous_root: Optional[str]) -> Any:
        if isinstance(node, dict):
            if HANDLE_KEY in node:
                if previous_root is None:
                    raise ValueError(
                        "summary handle with no previous summary"
                    )
                return self.read_path(previous_root, node[HANDLE_KEY])
            return {
                k: self._resolve_handles(v, previous_root)
                for k, v in node.items()
            }
        if isinstance(node, list):
            return [self._resolve_handles(v, previous_root)
                    for v in node]
        return node

    def _write_node(self, node: Any, depth: int) -> str:
        if depth > 0 and isinstance(node, dict):
            children = {
                k: self._write_node(v, depth - 1)
                for k, v in node.items()
            }
            return self.store.put({_TREE: children})
        if isinstance(node, dict) and isinstance(
            node.get("chunks"), list
        ):
            # chunked snapshot: one object per chunk so append-mostly
            # documents reuse every unchanged chunk
            rest = {k: v for k, v in node.items() if k != "chunks"}
            chunk_shas = [self.store.put(c) for c in node["chunks"]]
            return self.store.put({
                _CHUNKS: chunk_shas, _BLOB: rest,
            })
        return self.store.put({_BLOB: node})

    # -- read ----------------------------------------------------------

    def read(self, root: str) -> dict:
        return self._read_node(root)

    def _read_node(self, sha: str) -> Any:
        obj = self.store.get(sha)
        if _TREE in obj:
            return {
                k: self._read_node(v) for k, v in obj[_TREE].items()
            }
        if _CHUNKS in obj:
            out = dict(obj[_BLOB])
            out["chunks"] = [
                self.store.get(c) for c in obj[_CHUNKS]
            ]
            return out
        return obj[_BLOB]

    def read_path(self, root: str, path: str) -> Any:
        """Resolve "a/b/c" inside a stored summary without
        materializing the whole tree."""
        sha = root
        parts = [p for p in path.split("/") if p]
        for i, part in enumerate(parts):
            obj = self.store.get(sha)
            if _TREE not in obj:
                # descend into a blob's plain dict remainder
                node = self._read_node(sha)
                for rest in parts[i:]:
                    node = node[rest]
                return node
            sha = obj[_TREE][part]
        return self._read_node(sha)


@dataclasses.dataclass
class SummaryVersion:
    sequence_number: int
    root: str
    timestamp: float = dataclasses.field(default_factory=time.time)


class FileOpLog(OpLog):
    """Durable op log: the in-memory OpLog's semantics (contiguity,
    range reads, truncation) with JSONL persistence via the
    _persist_* hooks — same shape as FileContentStore/ContentStore."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        if os.path.exists(path):
            rows, torn = read_jsonl_tolerant(path, "oplog")
            for row in rows:
                self._ops.append(message_from_json(row))
            if torn:
                # rewrite without the torn tail so a second crash
                # cannot stack a new append onto a half record
                self._rewrite()
        self._fh = open(path, "a")

    def _persist_append(self, msg: SequencedMessage) -> None:
        # crc-stamped record (jsonl_record): load + scrub verify it,
        # so a sector flipping at rest is DETECTED instead of served
        self._fh.write(jsonl_record(message_to_json(msg)))
        self._fh.flush()
        # the ACK BARRIER: the pipeline fans out (and acks) only after
        # this returns, so an op any client ever saw sequenced is
        # durable — the only tearable crash state is an op nobody was
        # told about (read_jsonl_tolerant discards exactly that)
        os.fsync(self._fh.fileno())

    def _persist_truncate(self) -> None:
        self._fh.close()
        self._rewrite()
        self._fh = open(self.path, "a")

    def _rewrite(self) -> None:
        atomic_write(self.path, "".join(
            jsonl_record(message_to_json(m)) for m in self._ops
        ))


class DocumentStorage:
    """Per-document durable state: summary versions + op log +
    service checkpoint, all under one directory."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.trees = SummaryTreeStore(
            FileContentStore(os.path.join(root, "store"))
        )
        self.op_log = self._make_op_log(
            os.path.join(root, "ops.jsonl"))
        self._versions_path = os.path.join(root, "versions.jsonl")
        self.versions: list[SummaryVersion] = []
        if os.path.exists(self._versions_path):
            rows, torn = read_jsonl_tolerant(
                self._versions_path, "versions")
            self.versions = [SummaryVersion(**row) for row in rows]
            if torn:
                # rewrite without the torn tail, like the op log: the
                # next commit_summary APPENDS, and stacking a fresh
                # record onto the half line would turn a recoverable
                # crash state into mid-file corruption at the load
                # after that
                atomic_write(self._versions_path, "".join(
                    json.dumps(dataclasses.asdict(v)) + "\n"
                    for v in self.versions
                ))
        self._checkpoint_path = os.path.join(root, "checkpoint.json")
        # a leftover checkpoint tmp is the crash-between-write-and-
        # rename state: the rename never happened, so the committed
        # checkpoint (or its absence) is the truth — clear the debris
        try:
            os.remove(self._checkpoint_path + ".tmp")
            _M_DEBRIS.labels(file="checkpoint").inc()
        except OSError:
            pass

    def _make_op_log(self, path: str) -> FileOpLog:
        """Op-log factory hook: the replicated sequencer
        (service/replication.py) swaps in a ReplicatedOpLog whose
        append blocks on the replication quorum."""
        return FileOpLog(path)

    # summaries
    def write_summary(self, sequence_number: int,
                      summary: dict) -> str:
        prev = self.versions[-1].root if self.versions else None
        root = self.trees.write(summary, previous_root=prev)
        return self.commit_summary(sequence_number, root)

    def commit_summary(self, sequence_number: int, root: str) -> str:
        """Durably record a staged tree root as a version (the scribe
        ack of a client-uploaded summary)."""
        version = SummaryVersion(sequence_number, root)
        self.versions.append(version)
        with open(self._versions_path, "a") as f:
            f.write(json.dumps(dataclasses.asdict(version)) + "\n")
            f.flush()
            os.fsync(f.fileno())  # ack barrier, like the op log
        return root

    def latest_summary(self) -> Optional[tuple[int, dict]]:
        if not self.versions:
            return None
        v = self.versions[-1]
        return v.sequence_number, self.trees.read(v.root)

    # service checkpoint (deli/checkpointContext.ts)
    def write_checkpoint(self, state: dict) -> None:
        fault = _SITE_CHECKPOINT.fire(doc=os.path.basename(self.root))
        if fault is not None:
            # both error kinds surface as the OSError shape the
            # storage breaker's recovery contract is keyed on; the
            # torn states themselves are enumerated at crash time by
            # the harness, not mid-run (a torn write IS a crash)
            raise TransientIOFault(
                f"chaos[storage.checkpoint_write]: injected {fault}")
        # the shared barrier (see atomic_write): the torn-final state
        # this rules out is exactly what read_checkpoint used to
        # parse as garbage
        atomic_write(self._checkpoint_path, json.dumps(state))

    def read_checkpoint(self) -> Optional[dict]:
        if not os.path.exists(self._checkpoint_path):
            return None
        with open(self._checkpoint_path) as f:
            raw = f.read()
        try:
            return json.loads(raw)
        except ValueError:
            # a torn/garbage checkpoint must degrade, not detonate:
            # the op log holds every sequenced op, and the orderer's
            # restore path fast-forwards from seq 0 when no
            # checkpoint loads — slower startup, never wrong state
            _M_TORN.labels(file="checkpoint").inc()
            print(
                f"storage: checkpoint {self._checkpoint_path!r} is "
                f"torn/unparseable ({len(raw)} bytes); ignoring it — "
                "restart fast-forwards from the op log",
                file=sys.stderr,
            )
            return None
