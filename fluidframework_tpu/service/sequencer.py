"""Per-document total-order sequencer — the deli ``ticket()`` semantics.

Reference: server/routerlicious/packages/lambdas/src/deli/lambda.ts
(``DeliLambda.handler`` :378 -> ``ticket()`` :741; msn computation :308;
per-client refSeq tracking in ``clientSeqManager.ts``).

One ``DocumentSequencer`` is the single ordering authority for one
document (the reference guarantees this with one Kafka partition per
document; we guarantee it with one sequencer instance per doc, sharded
over the service plane — SURVEY §2.9 axis 1).

Responsibilities:
- assign a monotone ``sequence_number`` to every raw op,
- track each connected client's ``reference_sequence_number`` and stamp
  the ``minimum_sequence_number`` (= min refSeq over connected clients)
  on every outgoing op,
- join/leave bookkeeping, duplicate/gap detection on
  ``client_sequence_number``, nack policies,
- checkpoint/restore so a sharded service can resume after
  reassignment (deli/checkpointContext.ts).
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

from ..obs import metrics as _metrics
from ..obs.trace import stamp as _stamp
from ..protocol.messages import (
    ClientDetail,
    DocumentMessage,
    MessageType,
    Nack,
    NackErrorType,
    SequencedMessage,
)

# process-wide aggregates across every document's sequencer (label-
# free on purpose: per-document label sets are unbounded cardinality)
_TICKETS = _metrics.REGISTRY.counter(
    "sequencer_tickets_total", "raw ops assigned a sequence number")
_NACKS = _metrics.REGISTRY.counter(
    "sequencer_nacks_total", "raw ops refused by the sequencer")
_SYSTEM_MSGS = _metrics.REGISTRY.counter(
    "sequencer_system_messages_total",
    "service-generated sequenced messages (joins/leaves/acks)")


@dataclass
class _ClientState:
    """clientSeqManager.ts entry: per-client sequencing state."""

    client_id: str
    reference_sequence_number: int
    client_sequence_number: int = 0
    can_evict: bool = True
    last_update: float = 0.0


@dataclass
class TicketResult:
    """Outcome of sequencing one raw op."""

    message: SequencedMessage | None = None
    nack: Nack | None = None

    @property
    def ok(self) -> bool:
        return self.message is not None


class DocumentSequencer:
    """deli ``ticket()`` (lambda.ts:741) for a single document."""

    def __init__(
        self,
        document_id: str = "",
        sequence_number: int = 0,
        minimum_sequence_number: int = 0,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.document_id = document_id
        self.sequence_number = sequence_number
        self.minimum_sequence_number = minimum_sequence_number
        # injectable wall clock (the qos/slo idiom): the wire-visible
        # ``timestamp`` stamps and trace hops route through it, so a
        # recorded corpus replayed under a manual clock is byte-stable
        # (production default stays real wall time — timestamps on
        # the wire MEAN server wall time)
        self._clock = clock or time.time
        self._clients: dict[str, _ClientState] = {}

    # ------------------------------------------------------------------
    # membership

    @property
    def clients(self) -> tuple[str, ...]:
        return tuple(self._clients)

    def client_join(self, detail: ClientDetail) -> SequencedMessage:
        """Server-generated join (alfred connect_document ->
        deli; lambdas/src/alfred/index.ts:465). The new client's refSeq
        starts at the seq BEFORE its join: the join itself hasn't
        reached the client yet, so crediting it with the join's seq
        lets the msn outrun what the client has provably processed —
        its first op (submitted before the join broadcast arrives over
        a real network) would then nack with 'refSeq below msn'
        (found by tools/net_stress over TCP; in-proc delivery is
        synchronous and never exposed the race)."""
        seq = self._next_seq()
        existing = self._clients.get(detail.client_id)
        if existing is None:
            self._clients[detail.client_id] = _ClientState(
                client_id=detail.client_id,
                reference_sequence_number=seq - 1,
                last_update=self._clock(),
            )
        # A redundant join (at-least-once ingress retry) must NOT reset
        # sequencing state, or replayed ops would be re-ticketed as new.
        return self._stamp_system(MessageType.CLIENT_JOIN, detail, seq)

    def client_leave(self, client_id: str) -> SequencedMessage | None:
        if client_id not in self._clients:
            return None
        del self._clients[client_id]
        seq = self._next_seq()
        return self._stamp_system(MessageType.CLIENT_LEAVE, client_id, seq)

    # ------------------------------------------------------------------
    # op sequencing

    def ticket(self, client_id: str, op: DocumentMessage) -> TicketResult:
        """Assign seq + msn to one raw client op, or nack it."""
        client = self._clients.get(client_id)
        if client is None:
            _NACKS.inc()
            return TicketResult(nack=Nack(
                operation=op,
                sequence_number=self.sequence_number,
                error_type=NackErrorType.BAD_REQUEST,
                message=f"client {client_id!r} not in quorum (join first)",
            ))

        # Duplicate / out-of-order client sequence numbers
        # (deli dup-detection around lambda.ts:800s).
        expected = client.client_sequence_number + 1
        if op.client_sequence_number < expected:
            # Duplicate delivery: drop silently (idempotence).
            return TicketResult()
        if op.client_sequence_number > expected:
            _NACKS.inc()
            return TicketResult(nack=Nack(
                operation=op,
                sequence_number=self.sequence_number,
                error_type=NackErrorType.BAD_REQUEST,
                message=(
                    f"clientSequenceNumber gap: got "
                    f"{op.client_sequence_number}, expected {expected}"
                ),
            ))

        # refSeq sanity: must be inside the collab window.
        if op.reference_sequence_number < self.minimum_sequence_number:
            _NACKS.inc()
            return TicketResult(nack=Nack(
                operation=op,
                sequence_number=self.sequence_number,
                error_type=NackErrorType.BAD_REQUEST,
                message=(
                    f"refSeq {op.reference_sequence_number} below msn "
                    f"{self.minimum_sequence_number}"
                ),
            ))
        if op.reference_sequence_number > self.sequence_number:
            _NACKS.inc()
            return TicketResult(nack=Nack(
                operation=op,
                sequence_number=self.sequence_number,
                error_type=NackErrorType.BAD_REQUEST,
                message="refSeq ahead of document sequence number",
            ))

        now = self._clock()
        client.client_sequence_number = op.client_sequence_number
        client.reference_sequence_number = op.reference_sequence_number
        client.last_update = now

        seq = self._next_seq()
        msn = self._compute_msn()
        _TICKETS.inc()
        # the deli stamp (deli/lambda.ts:1130): the op's client-side
        # hops travel with it; this marks the ordering authority.
        # timestamp= from the injected clock, so the stamp is as
        # replayable as the message it rides
        traces = _stamp(list(op.traces), "sequencer", "ticket",
                        timestamp=now)
        return TicketResult(message=SequencedMessage(
            client_id=client_id,
            sequence_number=seq,
            minimum_sequence_number=msn,
            client_sequence_number=op.client_sequence_number,
            reference_sequence_number=op.reference_sequence_number,
            type=op.type,
            contents=op.contents,
            metadata=op.metadata,
            timestamp=now,
            traces=traces,
        ))

    def system_message(self, msg_type: MessageType,
                       contents: Any) -> SequencedMessage:
        """Allocate a seq for a service-generated op (scribe's
        summaryAck/Nack loop back through deli the same way)."""
        _SYSTEM_MSGS.inc()
        return self._stamp_system(msg_type, contents, self._next_seq())

    def fast_forward(self, seq: int) -> None:
        """O(1) stream-position resume (restart fast-forward, follower
        promotion): equivalent to sequencing ``seq - sequence_number``
        NO_OPs — only the final seq and one msn recomputation are
        observable, and neither allocates per-op messages. A promoted
        follower with a full replicated log used to pay O(log) here."""
        if seq <= self.sequence_number:
            return
        self.sequence_number = seq
        self._compute_msn()

    # ------------------------------------------------------------------
    # checkpoint / resume (deli/checkpointContext.ts)

    def checkpoint(self) -> dict[str, Any]:
        return {
            "document_id": self.document_id,
            "sequence_number": self.sequence_number,
            "minimum_sequence_number": self.minimum_sequence_number,
            "clients": [
                {
                    "client_id": c.client_id,
                    "reference_sequence_number": c.reference_sequence_number,
                    "client_sequence_number": c.client_sequence_number,
                    "last_update": c.last_update,
                }
                for c in self._clients.values()
            ],
        }

    @classmethod
    def restore(cls, state: dict[str, Any],
                clock: Optional[Callable[[], float]] = None,
                ) -> "DocumentSequencer":
        seq = cls(
            document_id=state["document_id"],
            sequence_number=state["sequence_number"],
            minimum_sequence_number=state["minimum_sequence_number"],
            clock=clock,
        )
        for c in state["clients"]:
            seq._clients[c["client_id"]] = _ClientState(
                client_id=c["client_id"],
                reference_sequence_number=c["reference_sequence_number"],
                client_sequence_number=c["client_sequence_number"],
                # diagnostics parity with clientSeqManager (no code
                # consumes it yet): restored as recorded instead of
                # re-minted at restore-time, .get-defaulted for
                # checkpoints written before the field persisted
                last_update=c.get("last_update", 0.0),
            )
        return seq

    # ------------------------------------------------------------------
    # internals

    def _next_seq(self) -> int:
        self.sequence_number += 1
        return self.sequence_number

    def _compute_msn(self) -> int:
        """msn = min over connected clients' refSeqs (lambda.ts:308);
        with no clients the msn rides the sequence number. Monotone by
        construction (refSeqs only advance; joiners start at current
        seq)."""
        if self._clients:
            msn = min(
                c.reference_sequence_number for c in self._clients.values()
            )
        else:
            msn = self.sequence_number
        # msn never regresses even across leave/join churn.
        self.minimum_sequence_number = max(self.minimum_sequence_number, msn)
        return self.minimum_sequence_number

    def _stamp_system(
        self, msg_type: MessageType, contents: Any, seq: int
    ) -> SequencedMessage:
        msn = self._compute_msn()
        return SequencedMessage(
            client_id=None,
            sequence_number=seq,
            minimum_sequence_number=msn,
            client_sequence_number=-1,
            reference_sequence_number=-1,
            type=msg_type,
            contents=contents,
            timestamp=self._clock(),
        )
