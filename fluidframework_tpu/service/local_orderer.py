"""In-process orderer: the full deli -> {scriptorium, scribe,
broadcaster} pipeline for one document.

Reference: server/routerlicious/packages/memory-orderer/src/
localOrderer.ts (``setupLambdas`` :237) — the whole service in-proc
over an in-memory Kafka; used by tinylicious/local-server and every
integration test (SURVEY §4 pillar (c)).
"""
from __future__ import annotations

from collections import deque
from typing import Any, Callable, Optional

from ..protocol.messages import (
    ClientDetail,
    DocumentMessage,
    MessageType,
    Nack,
    SequencedMessage,
)
from .lambdas import (
    BroadcasterLambda,
    OpLog,
    ScribeLambda,
    ScriptoriumLambda,
    SummaryStore,
)
from .sequencer import DocumentSequencer


class LocalOrderer:
    """One document's ordering service instance."""

    def __init__(self, document_id: str, lumberjack=None,
                 storage=None, checkpoint_every: int = 1,
                 storage_breaker=None, write_fence=None,
                 clock=None):
        import os

        from .telemetry import Lumberjack
        self.document_id = document_id
        self.lumberjack = lumberjack or Lumberjack()
        # injectable wall clock for the sequencer's wire timestamps
        # (None = real wall time); survives checkpoint restore and
        # the checkpoint-ahead rebuild below
        self.clock = clock
        self.storage = storage
        # optional epoch-fence hook (service/replication.py), called
        # with the operation name ("submit"/"connect"/"disconnect" —
        # the truthful context for refusal diagnostics): consulted
        # BEFORE ticketing, so a deposed leader refuses a write
        # without consuming a sequence number — its sequencer state
        # stays aligned with its (refused) log
        self.write_fence = write_fence
        # optional qos.CircuitBreaker around checkpoint writes: a
        # hard-down disk degrades durability (the op log still has
        # every op; restart fast-forwards from it) instead of taking
        # the sequencing path down with it
        self.storage_breaker = storage_breaker
        self.op_log = storage.op_log if storage is not None else OpLog()
        self.summary_store = SummaryStore(storage)
        self.sequencer = DocumentSequencer(document_id, clock=clock)
        if os.environ.get("FFTPU_NATIVE_SEQUENCER") == "1":
            try:
                from ..native import NativeSequencerCore
                self.sequencer = NativeSequencerCore(document_id,
                                                     clock=clock)
            except (RuntimeError, OSError) as e:
                # toolchain unavailable: the Python path stands in,
                # but an env var that asked for the native core and
                # didn't get it must not fall back silently (the PR8
                # pool-route lesson)
                import sys

                print(
                    f"orderer[{document_id}]: FFTPU_NATIVE_SEQUENCER"
                    f"=1 but the native core is unavailable "
                    f"({type(e).__name__}: {e}); using the Python "
                    "sequencer",
                    file=sys.stderr,
                )
        self._checkpoint_every = checkpoint_every
        self._since_checkpoint = 0
        # leaves that could not replicate during a quorum-loss
        # degraded window (absorbed, not sequenced): settled at the
        # client's next join — sequencing the owed leave FIRST resets
        # the csn watermark, or the rejoining client's resubmits
        # would be silently swallowed by the duplicate-csn dedupe
        # (found by the netsplit differential as a merge-tree
        # view-length divergence three hops downstream)
        self._owed_leaves: set[str] = set()
        self.scriptorium = ScriptoriumLambda(self.op_log, clock=clock)
        self.broadcaster = BroadcasterLambda(clock=clock)
        self.scribe = ScribeLambda(
            self.summary_store, self._submit_system_op, self.op_log,
            clock=clock,
        )
        # deli out-topic consumers, in order (localOrderer.ts:237)
        self._pipeline: list[Callable[[SequencedMessage], None]] = [
            self.scriptorium.handler,
            self.scribe.handler,
            self.broadcaster.handler,
        ]
        # The reference decouples stages with Kafka topics; in-proc we
        # flatten re-entrancy with a pump: a submit made from inside a
        # delivery enqueues and is dispatched after the current message
        # finishes (LocalKafka's async delivery, memory-orderer).
        self._dispatch_queue: deque[SequencedMessage] = deque()  # fluidlint: disable=service-unbounded-queue -- drained to empty inside _dispatch before control returns to the submitter; depth is bounded by re-entrant submits within ONE pump, not by client traffic
        self._dispatching = False
        if storage is not None:
            state = storage.read_checkpoint()
            if state is not None:
                self.restore(state)
            if self.sequencer.sequence_number > self.op_log.last_seq:
                # checkpoint AHEAD of the op log: with the storage
                # barriers (scriptorium fsyncs its append before the
                # checkpoint of that dispatch writes) this state is
                # unreachable from a crash — it means a pre-barrier
                # data dir or a log that lost a torn tail the
                # checkpoint saw. The log is the truth the clients
                # were (never) told: discard the checkpoint and
                # rebuild from the log alone, loudly. (The scribe
                # replica needs no reset here: the unconditional
                # fast-forward below re-anchors it to the rebuilt
                # sequencer either way.)
                import sys

                print(
                    f"orderer[{document_id}]: checkpoint at seq "
                    f"{self.sequencer.sequence_number} is AHEAD of "
                    f"the op log (seq {self.op_log.last_seq}); "
                    "discarding it and fast-forwarding from the log",
                    file=sys.stderr,
                )
                self.sequencer = type(self.sequencer)(
                    document_id, clock=clock)
            # ops sequenced after the last checkpoint write (or with a
            # lost/absent checkpoint entirely) are in the durable log;
            # fast-forward the stream position so new tickets continue
            # the contiguous order
            if hasattr(self.sequencer, "fast_forward"):
                self.sequencer.fast_forward(self.op_log.last_seq)
            else:
                # implementations without the O(1) resume (the native
                # core) walk the gap the old way
                gap = (self.op_log.last_seq
                       - self.sequencer.sequence_number)
                for _ in range(max(0, gap)):
                    self.sequencer.system_message(
                        MessageType.NO_OP, None)
            # scribe's replica must fast-forward with the log too, or
            # the first post-restart message trips its contiguity
            # check (scribe/lambda.ts:108 skips below-checkpoint
            # messages the same way)
            self.scribe.protocol.sequence_number = (
                self.sequencer.sequence_number
            )
            self.scribe.protocol.minimum_sequence_number = (
                self.sequencer.minimum_sequence_number
            )
            # every pre-crash connection is gone: sequence leaves for
            # the checkpointed clients so (a) their stale csn state
            # cannot silently swallow a reconnecting client's ops as
            # duplicates, and (b) their refSeqs stop pinning the msn
            for cid in list(self.sequencer.clients):
                self.disconnect(cid)

    @property
    def inbox_depth(self) -> int:
        """Undispatched sequenced messages (the deli-inbox depth the
        qos pressure monitor samples; nonzero only mid-pump)."""
        return len(self._dispatch_queue)

    # ------------------------------------------------------------------
    # ingress (alfred submitOp path)

    def connect(self, detail: ClientDetail) -> SequencedMessage:
        if self.write_fence is not None:
            # refuse BEFORE the join consumes a sequence number: a
            # deposed leader's sequencer must stay aligned with its
            # (refused) log, or the unwind path's leave trips the
            # log-contiguity assert instead of the fence
            self.write_fence("connect")
            if detail.client_id in self._owed_leaves:
                # settle the leave the degraded window absorbed (the
                # gate above proved availability): the sequenced
                # leave resets the client's csn watermark, so the
                # reconnect's fresh csn 1 is a new stream, never a
                # "duplicate" the dedupe silently swallows
                pre_leave = self.sequencer.checkpoint()
                leave = self.sequencer.client_leave(detail.client_id)
                if leave is not None:
                    try:
                        self._dispatch(leave)
                    except self._unavailable_error():
                        # the window reopened between the gate and
                        # the leave's own barrier: still owed
                        self._rollback_ticket(pre_leave)
                        raise
                self._owed_leaves.discard(detail.client_id)
            # the join may still be the FIRST write to discover a
            # quorum loss (the barrier's deadline, not the cached
            # gate): snapshot so the refused ticket rolls back
            pre = self.sequencer.checkpoint()
            join = self.sequencer.client_join(detail)
            try:
                self._dispatch(join)
            except self._unavailable_error():
                self._rollback_ticket(pre)
                raise
            return join
        join = self.sequencer.client_join(detail)
        self._dispatch(join)
        return join

    def _unavailable_error(self):
        from .replication import QuorumUnavailableError

        return QuorumUnavailableError

    def _rollback_ticket(self, pre: dict) -> None:
        """Unwind a ticket whose replication was refused (quorum
        unavailable): the op log already unwound its append, so
        restoring the pre-ticket sequencer state re-aligns stream
        position, client table and msn — the seq slot is re-issued
        to the next accepted write. Only legal because the refused
        message never reached the broadcaster (scriptorium raises
        before the scribe/broadcaster stages run).

        The DURABLE LOG is the reconciliation floor: a re-entrant
        dispatch (a scribe loopback ack queued behind the ticketed
        op) may have quorum-committed intermediate ops AFTER the
        checkpoint was taken — rolling the sequencer below the log
        head would re-issue a seq the quorum already holds, so the
        restore fast-forwards back to the head. (A client whose op
        landed in that window may then see one csn-gap nack and ride
        the normal reconnect path — rare, loud, and ordered; never a
        fork.) Refused messages still queued from the aborted pump
        are dropped: never persisted, never fanned out, their
        submitters still hold them pending."""
        self.sequencer = type(self.sequencer).restore(
            pre, clock=self.clock)
        if hasattr(self.sequencer, "fast_forward"):
            self.sequencer.fast_forward(self.op_log.last_seq)
        else:
            gap = (self.op_log.last_seq
                   - self.sequencer.sequence_number)
            for _ in range(max(0, gap)):
                self.sequencer.system_message(MessageType.NO_OP, None)
        self._dispatch_queue.clear()
        self.scribe.protocol.sequence_number = \
            self.sequencer.sequence_number
        self.scribe.protocol.minimum_sequence_number = \
            self.sequencer.minimum_sequence_number

    def disconnect(self, client_id: str) -> Optional[SequencedMessage]:
        if self.write_fence is not None:
            from .replication import FencedWriteError

            try:
                self.write_fence("disconnect")
            except FencedWriteError:
                # teardown on a DEPOSED node must not detonate:
                # session close() runs this mid-cleanup (a transport
                # death during the deposed window), and a leave a
                # fenced node sequences could never reach a client
                # anyway — skip sequencing it; the client's lifecycle
                # continues on the real leader
                return None
            except self._unavailable_error():
                # quorum-loss degraded window: the leave cannot
                # replicate — absorbed, but OWED (see connect): the
                # cached verdict refuses it pre-ticket, so teardown
                # costs a flag, not a quorum deadline
                self._owed_leaves.add(client_id)
                return None
            pre = self.sequencer.checkpoint()
            leave = self.sequencer.client_leave(client_id)
            if leave is not None:
                try:
                    self._dispatch(leave)
                except self._unavailable_error():
                    # a leave that cannot replicate (quorum-loss
                    # window) is absorbed like the fenced teardown —
                    # but OWED: the client's next join sequences it
                    # first, resetting the csn watermark the stale
                    # entry would otherwise hold
                    self._rollback_ticket(pre)
                    self._owed_leaves.add(client_id)
                    return None
            return leave
        leave = self.sequencer.client_leave(client_id)
        if leave is not None:
            self._dispatch(leave)
        return leave

    def submit(self, client_id: str,
               op: DocumentMessage) -> Optional[Nack]:
        pre = None
        if self.write_fence is not None:
            try:
                # raises FencedWriteError when deposed; the
                # availability gate (quorum-loss degraded mode)
                # raises the RETRIABLE refusal, converted to a
                # throttle nack here so the client's PR9
                # pending/resubmit path rides it with no new
                # machinery
                self.write_fence("submit")
            except self._unavailable_error() as e:
                return self._unavailable_nack(op, e)
            # full checkpoint, not a scalar snapshot: checkpoint()/
            # restore() is the only rollback surface BOTH sequencer
            # implementations (python + native core) share, and its
            # cost is O(connected clients of THIS document) — the
            # collaborator count, not the fleet — paid only on the
            # replicated plane (write_fence unset = plain plane,
            # zero overhead)
            pre = self.sequencer.checkpoint()
        result = self.sequencer.ticket(client_id, op)
        if result.nack is not None:
            # structured service telemetry (Lumberjack, lumber.ts:23)
            self.lumberjack.log("nack", result.nack.message, {
                "documentId": self.document_id,
                "clientId": client_id,
                "errorType": int(result.nack.error_type),
            })
            return result.nack
        if result.message is not None:
            if pre is None:
                self._dispatch(result.message)
                return None
            try:
                self._dispatch(result.message)
            except self._unavailable_error() as e:
                # the quorum barrier's deadline lapsed mid-append:
                # the op log unwound its record; unwind the ticket
                # too and answer with the retriable nack
                self._rollback_ticket(pre)
                return self._unavailable_nack(op, e)
        return None

    def _unavailable_nack(self, op: DocumentMessage, e) -> Nack:
        from ..qos.policy import REASON_UNAVAILABLE
        from ..protocol.messages import NackErrorType

        nack = Nack(
            operation=op, sequence_number=0,
            error_type=NackErrorType.THROTTLING,
            message=str(e),
            retry_after_seconds=e.retry_after_seconds,
            shed_class=REASON_UNAVAILABLE,
        )
        self.lumberjack.log("nack", nack.message, {
            "documentId": self.document_id,
            "errorType": int(nack.error_type),
            "shedClass": REASON_UNAVAILABLE,
        })
        return nack

    # ------------------------------------------------------------------

    def _submit_system_op(self, msg_type: MessageType,
                          contents: Any) -> None:
        """Scribe emits summaryAck/Nack as service-generated sequenced
        ops (scribe -> deli loopback)."""
        self._dispatch(self.sequencer.system_message(msg_type, contents))

    def _dispatch(self, msg: SequencedMessage) -> None:
        self._dispatch_queue.append(msg)
        if self._dispatching:
            return
        self._dispatching = True
        try:
            while self._dispatch_queue:
                current = self._dispatch_queue.popleft()
                for stage in self._pipeline:
                    stage(current)
                self._since_checkpoint += 1
        finally:
            self._dispatching = False
        if (
            self.storage is not None
            and self._since_checkpoint >= self._checkpoint_every
        ):
            self._since_checkpoint = 0
            self._write_checkpoint_guarded()

    def _write_checkpoint_guarded(self) -> None:
        """Checkpoint write, optionally circuit-broken: with a
        breaker, a failing disk is recorded (and the breaker
        eventually refuses instantly instead of paying the fault per
        op) but sequencing continues — the op log is the recovery
        path. Without one, faults propagate as before."""
        if self.storage_breaker is None:
            self.storage.write_checkpoint(self.checkpoint())
            return
        from ..qos import BreakerOpenError

        try:
            self.storage_breaker.call(
                self.storage.write_checkpoint, self.checkpoint()
            )
        except BreakerOpenError:
            pass  # open: refusal already counted by the breaker
        except OSError as e:
            # recorded as a breaker failure by call(); degrade, don't
            # kill the submit path — restart replays the op log
            self.lumberjack.log("checkpointFailed", str(e), {
                "documentId": self.document_id,
            })

    # ------------------------------------------------------------------
    # checkpoint/resume (deli/checkpointContext.ts + scribe state)

    def checkpoint(self) -> dict:
        return {"sequencer": self.sequencer.checkpoint()}

    def restore(self, state: dict) -> None:
        # preserve the sequencer implementation (a NativeSequencerCore
        # must not silently degrade to the Python path on restart)
        self.sequencer = type(self.sequencer).restore(
            state["sequencer"], clock=self.clock
        )
        # scribe's replica resumes at the checkpointed stream position
        # (scribe/lambda.ts:108 skips replayed messages below it)
        self.scribe.protocol.sequence_number = self.sequencer.sequence_number
        self.scribe.protocol.minimum_sequence_number = (
            self.sequencer.minimum_sequence_number
        )
