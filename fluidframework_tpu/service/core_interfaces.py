"""Service-plane interface layer — the services-core analogue.

Reference: server/routerlicious/packages/services-core/src — the
contracts every deployable service component implements (IOrderer,
IOrdererManager, IProducer/IConsumer over the queue, IDocumentStorage,
ICache, ITenantManager), so that local/in-memory, single-box durable,
and clustered deployments swap behind the same types.

These are structural ``typing.Protocol``s: the concrete classes
(LocalOrderer, LocalServer, OrderingQueue impls, ContentStore,
TenantManager) already conform — tests/test_service_interfaces.py
pins the conformance so drift fails loudly.
"""
from __future__ import annotations

from typing import Any, Iterator, Optional, Protocol, runtime_checkable

from ..protocol.messages import (
    ClientDetail,
    DocumentMessage,
    Nack,
    SequencedMessage,
)


@runtime_checkable
class IOrderer(Protocol):
    """One document's ordering pipeline (services-core IOrderer)."""

    def connect(self, detail: ClientDetail) -> SequencedMessage: ...

    def disconnect(self, client_id: str) -> Optional[SequencedMessage]:
        ...

    def submit(self, client_id: str,
               op: DocumentMessage) -> Optional[Nack]: ...


@runtime_checkable
class IOrdererManager(Protocol):
    """Document -> orderer resolution (IOrdererManager /
    OrdererManager, routerlicious-base runnerFactory.ts:43)."""

    def get_orderer(self, document_id: str) -> Any: ...


@runtime_checkable
class IOpLog(Protocol):
    """Durable sequenced-op store (scriptorium's collection)."""

    def append(self, msg: SequencedMessage) -> None: ...

    def read(self, from_seq: int,
             to_seq: Optional[int] = None) -> list: ...

    def truncate_below(self, seq: int) -> int: ...


@runtime_checkable
class IProducer(Protocol):
    """Raw-op transport, producer side (services-core IProducer)."""

    def produce(self, partition: int, document_id: str,
                payload: dict) -> int: ...


@runtime_checkable
class IConsumer(Protocol):
    """Raw-op transport, consumer side (IConsumer + checkpointing)."""

    def read(self, partition: int, from_offset: int) -> Iterator: ...

    def committed(self, partition: int) -> int: ...

    def commit(self, partition: int, offset: int) -> None: ...


@runtime_checkable
class IContentStore(Protocol):
    """Content-addressed object store (gitrest's blob plane)."""

    def put(self, obj: Any) -> str: ...

    def get(self, sha: str) -> Any: ...

    def has(self, sha: str) -> bool: ...


@runtime_checkable
class ITenantManager(Protocol):
    """Tenant registry + token validation (riddler / ITenantManager)."""

    def get_tenant(self, tenant_id: str) -> Any: ...

    def validate_token(self, token: str, tenant_id: str,
                       document_id: str,
                       required_scope: str = ...) -> dict: ...


@runtime_checkable
class ITelemetrySink(Protocol):
    """Structured service telemetry (services-telemetry Lumberjack)."""

    def log(self, event: str, message: str,
            properties: Optional[dict] = None) -> None: ...
