"""Service pipeline stages — the routerlicious lambda equivalents.

Reference: server/routerlicious/packages/lambdas/src:
- deli (lambda.ts:192): sequencing — our ``DocumentSequencer`` wrapped
  by the orderer,
- scriptorium (scriptorium/lambda.ts:20): durable op log writes,
- broadcaster (broadcaster/lambda.ts:49): fan-out to connections,
- scribe (scribe/lambda.ts:46): server-side protocol replica that
  validates summaries and emits summaryAck/Nack.

Stages are synchronous callables over sequenced messages; the orderer
pipes deli's output through them in order (the reference's Kafka topics
collapse to direct calls in-proc, exactly like memory-orderer's
LocalKafka).
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..obs import metrics as _metrics
from ..obs.trace import stamp as _stamp
from ..protocol.messages import MessageType, SequencedMessage
from ..protocol.quorum import ProtocolOpHandler

_BROADCASTS = _metrics.REGISTRY.counter(
    "broadcaster_fanouts_total",
    "sequenced messages fanned out to subscribers")
_OPLOG_WRITES = _metrics.REGISTRY.counter(
    "scriptorium_writes_total", "sequenced ops persisted to the log")


class OpLog:
    """Scriptorium's Mongo op collection, in memory: the durable
    sequenced-op store backing delta storage reads
    (scriptorium/lambda.ts:20)."""

    def __init__(self) -> None:
        self._ops: list[SequencedMessage] = []

    def append(self, msg: SequencedMessage) -> None:
        if self._ops:
            assert msg.sequence_number == self._ops[-1].sequence_number + 1, (
                "op log must stay contiguous"
            )
        self._ops.append(msg)
        self._persist_append(msg)

    def read(self, from_seq: int, to_seq: Optional[int] = None
             ) -> list[SequencedMessage]:
        """Ops with from_seq < seq <= to_seq (delta-storage range
        semantics)."""
        out = []
        for msg in self._ops:
            if msg.sequence_number <= from_seq:
                continue
            if to_seq is not None and msg.sequence_number > to_seq:
                break
            out.append(msg)
        return out

    def truncate_below(self, seq: int) -> int:
        """Drop ops at/below ``seq`` (durableSequenceNumber advance —
        deli/lambda.ts:342 area). Returns dropped count."""
        before = len(self._ops)
        self._ops = [m for m in self._ops if m.sequence_number > seq]
        dropped = before - len(self._ops)
        if dropped:
            self._persist_truncate()
        return dropped

    # durability hooks (FileOpLog overrides; mirror of
    # ContentStore._store/_load)
    def _persist_append(self, msg: SequencedMessage) -> None:
        pass

    def _persist_truncate(self) -> None:
        pass

    @property
    def last_seq(self) -> int:
        return self._ops[-1].sequence_number if self._ops else 0

    def __len__(self) -> int:
        return len(self._ops)


class ScriptoriumLambda:
    def __init__(self, op_log: OpLog, clock=None):
        self.op_log = op_log
        # injectable wall clock for the hop stamp that PERSISTS with
        # the op (the log is a recorded corpus: on a manual clock it
        # must be byte-stable); None = stamp() wall default
        self._clock = clock

    def handler(self, msg: SequencedMessage) -> None:
        _stamp(msg.traces, "scriptorium", "write",
               timestamp=self._clock() if self._clock else None)
        _OPLOG_WRITES.inc()
        self.op_log.append(msg)


class CopierLambda:
    """copier — verbatim RAW-op capture BEFORE sequencing
    (lambdas/src/copier: writes the pre-deli input stream so the exact
    bytes a client submitted survive for audit/replay even when deli
    nacks or dedups them)."""

    def __init__(self, sink: Optional[list] = None) -> None:
        self.raw: list = sink if sink is not None else []

    def handler(self, document_id: str, client_id: str,
                payload: Any) -> None:
        import copy as _copy

        self.raw.append({
            "document_id": document_id,
            "client_id": client_id,
            "payload": _copy.deepcopy(payload),
        })

    def read(self, document_id: Optional[str] = None) -> list:
        """Deep copies: the capture is the audit record — a consumer
        mutating a returned dict must not corrupt it."""
        import copy as _copy

        return [_copy.deepcopy(r) for r in self.raw
                if document_id is None
                or r["document_id"] == document_id]


class BroadcasterLambda:
    """broadcaster/lambda.ts:49 — per-document fan-out."""

    def __init__(self, clock=None) -> None:
        self._subscribers: dict[str, Callable[[SequencedMessage], None]] = {}
        self._clock = clock

    def subscribe(self, subscriber_id: str,
                  handler: Callable[[SequencedMessage], None]) -> None:
        self._subscribers[subscriber_id] = handler

    def unsubscribe(self, subscriber_id: str) -> None:
        self._subscribers.pop(subscriber_id, None)

    def handler(self, msg: SequencedMessage) -> None:
        _stamp(msg.traces, "broadcaster", "fanout",
               timestamp=self._clock() if self._clock else None)
        _BROADCASTS.inc()
        for handler in list(self._subscribers.values()):
            handler(msg)


@dataclass
class ServiceSummary:
    sequence_number: int
    summary: dict
    timestamp: float = field(default_factory=time.time)


class SummaryStore:
    """Versioned summary storage (historian/gitrest facade): summaries
    are split into content-addressed subtree objects, incremental
    {"__summary_handle__": path} nodes are resolved against the
    previous version (SummaryType.Handle, summary.ts:55-59), and an
    unchanged subtree costs zero new objects. In-memory by default; a
    ``DocumentStorage`` backend makes it durable on disk."""

    def __init__(self, storage=None) -> None:
        from .storage import SummaryTreeStore

        self._storage = storage
        if storage is not None:
            self._trees = storage.trees
            self._mem_roots = None  # storage.versions is canonical
        else:
            self._trees = SummaryTreeStore()
            self._mem_roots: Optional[list[tuple[int, str]]] = []

    def write(self, sequence_number: int, summary: dict) -> str:
        """Store a summary (resolving handles); returns the root sha —
        the ack handle clients see (summaryAck.handle)."""
        return self.commit(sequence_number, self.stage(summary))

    def stage(self, summary: dict) -> str:
        """The client-upload half of the historian flow
        (driver-definitions/src/storage.ts:119
        uploadSummaryWithContext): write the tree CONTENT — resolving
        incremental handles against the last committed version — and
        return the root sha WITHOUT recording a version. The sha is
        the handle a summarize op proposes; scribe's ack commits it."""
        if self._storage is not None:
            prev = (self._storage.versions[-1].root
                    if self._storage.versions else None)
        else:
            prev = self._mem_roots[-1][1] if self._mem_roots else None
        return self._trees.write(summary, previous_root=prev)

    def has_tree(self, root: str) -> bool:
        """Is ``root`` a staged/committed tree in the content store?"""
        return self._trees.store.has(root)

    def commit(self, sequence_number: int, root: str) -> str:
        """Record a staged tree as the version at ``sequence_number``
        (scribe ack — the summary becomes the document's loadable
        state)."""
        if self._storage is not None:
            return self._storage.commit_summary(sequence_number, root)
        self._mem_roots.append((sequence_number, root))
        return root

    def latest(self) -> Optional[ServiceSummary]:
        if self._storage is not None:
            if not self._storage.versions:
                return None
            v = self._storage.versions[-1]
            return ServiceSummary(
                v.sequence_number, self._trees.read(v.root)
            )
        if not self._mem_roots:
            return None
        seq, root = self._mem_roots[-1]
        return ServiceSummary(seq, self._trees.read(root))

    @property
    def version_count(self) -> int:
        if self._storage is not None:
            return len(self._storage.versions)
        return len(self._mem_roots)

    def object_count(self) -> int:
        return self._trees.store.object_count()


class ScribeLambda:
    """scribe/lambda.ts:46 — holds a server-side ProtocolOpHandler,
    validates client summaries, writes service summaries, and emits
    summaryAck ops back through the sequencer."""

    def __init__(self, summary_store: SummaryStore,
                 submit_system_op: Callable[[MessageType, Any], None],
                 op_log: Optional[OpLog] = None, clock=None):
        self.protocol = ProtocolOpHandler()
        self.summary_store = summary_store
        self._submit_system_op = submit_system_op
        self._op_log = op_log
        self._clock = clock

    def handler(self, msg: SequencedMessage) -> None:
        _stamp(msg.traces, "scribe", "process",
               timestamp=self._clock() if self._clock else None)
        self.protocol.process_message(msg)
        if msg.type == MessageType.SUMMARIZE:
            self._handle_summarize(msg)

    def _handle_summarize(self, msg: SequencedMessage) -> None:
        contents = msg.contents or {}
        summary = contents.get("summary")
        staged = contents.get("handle")
        if isinstance(staged, str) and summary is None:
            # the reference flow (containerRuntime.ts:2477): the
            # summarizer client uploaded the tree to storage first and
            # proposes only the handle; scribe validates it exists and
            # commits the version
            if not self.summary_store.has_tree(staged):
                self._submit_system_op(MessageType.SUMMARY_NACK, {
                    "summaryProposal": msg.sequence_number,
                    "message": f"unknown summary handle {staged!r}",
                })
                return
            handle = self.summary_store.commit(
                msg.sequence_number, staged
            )
        elif isinstance(summary, dict):
            # inline payload (in-proc sessions without a storage plane)
            handle = self.summary_store.write(
                msg.sequence_number, summary
            )
        else:
            self._submit_system_op(MessageType.SUMMARY_NACK, {
                "summaryProposal": msg.sequence_number,
                "message": "malformed summary payload",
            })
            return
        # Ack advances the durable sequence number: ops at/below the
        # summarized seq can be truncated from the log (§3.4).
        if self._op_log is not None:
            self._op_log.truncate_below(
                contents.get("referenceSequenceNumber", 0)
            )
        self._submit_system_op(MessageType.SUMMARY_ACK, {
            "summaryProposal": msg.sequence_number,
            "handle": handle,
        })
