"""Runnable single-process dev service — the tinylicious analogue.

Reference: server/tinylicious (single-tenant, no-Kafka, in-memory
service for development). Usage:

    python -m fluidframework_tpu.service [--host H] [--port P]

Clients connect with
``drivers.socket_driver.SocketDocumentServiceFactory`` and the normal
``loader.Container`` on top.

Observability: a running service answers the ``metrics`` frame with
the process-wide registry (fluidframework_tpu/obs/metrics.py);

    python -m fluidframework_tpu.service --dump-metrics HOST:PORT

is the /metrics-equivalent dump command (Prometheus text exposition;
``--json`` for the structured snapshot). A service started with
``--slo`` additionally grades the default serving objectives
(ingress dispatch p99, goodput floor) with multi-window burn rates;

    python -m fluidframework_tpu.service --dump-slo HOST:PORT

prints the live ``slo_report`` (per-objective verdicts + context);

    python -m fluidframework_tpu.service --dump-fleet HOST:PORT

prints the FEDERATED metrics view (obs/federation.py — leader +
follower + partition-worker registries merged, node-labelled); and

    python -m fluidframework_tpu.service --dump-heat HOST:PORT

prints the cost-attribution view (obs/heat.py — top-k hot documents
by attributed device-ms and top-k tenants off the usage ledger;
``--top-k N`` overrides the server's default cut).
"""
from __future__ import annotations

import argparse
from typing import Optional

from .ingress import run_server


def dump_metrics(target: str, as_json: bool) -> int:
    """Connect to a running service and print its metrics registry."""
    import json
    import socket

    from .ingress import _parse_hostport, pack_frame, recv_frame_blocking

    host, port = _parse_hostport(target)
    with socket.create_connection((host, port), timeout=10.0) as sock:
        sock.sendall(pack_frame({"type": "metrics", "rid": 1}))
        frame = recv_frame_blocking(sock)
    if frame.get("type") != "metrics":
        print(f"unexpected response: {frame}")
        return 1
    if as_json:
        print(json.dumps(frame["metrics"], indent=2, sort_keys=True))
    else:
        print(frame["text"], end="")
    return 0


def dump_fleet(target: str, as_json: bool) -> int:
    """Connect to a running service and print its FEDERATED metrics
    view (obs/federation.py: leader + follower + partition-worker
    registries merged — sum counters, node-labelled gauges,
    bucket-wise histograms)."""
    import json
    import socket

    from .ingress import _parse_hostport, pack_frame, recv_frame_blocking

    host, port = _parse_hostport(target)
    with socket.create_connection((host, port), timeout=10.0) as sock:
        sock.sendall(pack_frame({"type": "fleet-metrics", "rid": 1}))
        frame = recv_frame_blocking(sock)
    if frame.get("type") != "fleet-metrics":
        print(f"unexpected response: {frame}")
        return 1
    if as_json:
        print(json.dumps(
            {"nodes": frame["nodes"], "metrics": frame["metrics"]},
            indent=2, sort_keys=True))
    else:
        print(f"# fleet nodes: {', '.join(frame['nodes'])}")
        print(frame["text"], end="")
    return 0


def dump_slo(target: str) -> int:
    """Connect to a running service and print its slo_report."""
    import json
    import socket

    from .ingress import _parse_hostport, pack_frame, recv_frame_blocking

    host, port = _parse_hostport(target)
    with socket.create_connection((host, port), timeout=10.0) as sock:
        sock.sendall(pack_frame({"type": "slo", "rid": 1}))
        frame = recv_frame_blocking(sock)
    if frame.get("type") != "slo":
        print(f"unexpected response: {frame}")
        return 1
    if frame.get("report") is None:
        print(frame.get("message", "no slo report"))
        return 1
    print(json.dumps(frame["report"], indent=2, sort_keys=True))
    return 0


def dump_heat(target: str, k: Optional[int] = None) -> int:
    """Connect to a running service and print its heat view (top-k
    hot documents + tenants off the attribution ledgers)."""
    import json
    import socket

    from .ingress import _parse_hostport, pack_frame, recv_frame_blocking

    host, port = _parse_hostport(target)
    req = {"type": "heat", "rid": 1}
    if k is not None:
        # optional-presence wire field: emitted only when the caller
        # asked for a specific cut (the server serves its default
        # otherwise)
        req["k"] = k
    with socket.create_connection((host, port), timeout=10.0) as sock:
        sock.sendall(pack_frame(req))
        frame = recv_frame_blocking(sock)
    if frame.get("type") != "heat":
        print(f"unexpected response: {frame}")
        return 1
    print(json.dumps(
        {"docs": frame.get("docs", []),
         "tenants": frame.get("tenants", [])},
        indent=2, sort_keys=True))
    return 0


def main() -> None:
    parser = argparse.ArgumentParser(
        prog="python -m fluidframework_tpu.service",
        description="fluidframework-tpu dev ordering service",
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=7070)
    parser.add_argument("--data-dir", default=None,
                        help="durable storage root (op logs, "
                             "summaries, checkpoints)")
    parser.add_argument("--partitions", type=int, default=0,
                        help="route through N queue partitions (the "
                             "scale-out pipeline shape); 0 = inline "
                             "orderer")
    parser.add_argument("--broker", default=None,
                        help="host:port of a running "
                             "fluidframework_tpu.service.broker — the "
                             "networked ordering queue (partitions "
                             "span hosts)")
    parser.add_argument("--qos", action="store_true",
                        help="enable admission control + "
                             "backpressure (docs/QOS.md): token-"
                             "bucket rate limits, pressure-tier load "
                             "shedding with honest retry-after "
                             "throttle nacks, checkpoint circuit "
                             "breaker")
    parser.add_argument("--qos-ops-per-sec", type=float,
                        default=2000.0,
                        help="per-connection op budget the other "
                             "qos limits scale from (default 2000)")
    parser.add_argument("--slo", action="store_true",
                        help="grade the default serving SLOs "
                             "(ingress dispatch p99, goodput floor) "
                             "with multi-window burn rates; serves "
                             "the `slo` frame for --dump-slo")
    parser.add_argument("--dump-metrics", default=None,
                        metavar="HOST:PORT",
                        help="print a RUNNING service's metrics "
                             "registry (Prometheus text) and exit "
                             "instead of serving")
    parser.add_argument("--dump-slo", default=None,
                        metavar="HOST:PORT",
                        help="print a RUNNING --slo service's "
                             "slo_report (per-objective burn-rate "
                             "verdicts, JSON) and exit")
    parser.add_argument("--dump-fleet", default=None,
                        metavar="HOST:PORT",
                        help="print a RUNNING service's FEDERATED "
                             "metrics view (leader + follower + "
                             "partition-worker registries merged; "
                             "Prometheus text, --json for the "
                             "snapshot) and exit")
    parser.add_argument("--dump-heat", default=None,
                        metavar="HOST:PORT",
                        help="print a RUNNING service's cost-"
                             "attribution view (top-k hot documents "
                             "by attributed device-ms + top-k "
                             "tenants, JSON) and exit")
    parser.add_argument("--top-k", type=int, default=None,
                        help="with --dump-heat: ask for this cut "
                             "instead of the server default")
    parser.add_argument("--json", action="store_true",
                        help="with --dump-metrics/--dump-fleet: emit "
                             "the JSON snapshot instead of text "
                             "exposition")
    args = parser.parse_args()
    if args.dump_metrics is not None:
        raise SystemExit(dump_metrics(args.dump_metrics, args.json))
    if args.dump_slo is not None:
        raise SystemExit(dump_slo(args.dump_slo))
    if args.dump_fleet is not None:
        raise SystemExit(dump_fleet(args.dump_fleet, args.json))
    if args.dump_heat is not None:
        raise SystemExit(dump_heat(args.dump_heat, args.top_k))
    run_server(args.host, args.port, args.data_dir, args.partitions,
               args.broker, qos_enabled=args.qos,
               qos_ops_per_sec=args.qos_ops_per_sec,
               slo_enabled=args.slo)


if __name__ == "__main__":
    main()
