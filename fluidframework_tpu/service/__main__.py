"""Runnable single-process dev service — the tinylicious analogue.

Reference: server/tinylicious (single-tenant, no-Kafka, in-memory
service for development). Usage:

    python -m fluidframework_tpu.service [--host H] [--port P]

Clients connect with
``drivers.socket_driver.SocketDocumentServiceFactory`` and the normal
``loader.Container`` on top.
"""
from __future__ import annotations

import argparse

from .ingress import run_server


def main() -> None:
    parser = argparse.ArgumentParser(
        prog="python -m fluidframework_tpu.service",
        description="fluidframework-tpu dev ordering service",
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=7070)
    parser.add_argument("--data-dir", default=None,
                        help="durable storage root (op logs, "
                             "summaries, checkpoints)")
    parser.add_argument("--partitions", type=int, default=0,
                        help="route through N queue partitions (the "
                             "scale-out pipeline shape); 0 = inline "
                             "orderer")
    parser.add_argument("--broker", default=None,
                        help="host:port of a running "
                             "fluidframework_tpu.service.broker — the "
                             "networked ordering queue (partitions "
                             "span hosts)")
    args = parser.parse_args()
    run_server(args.host, args.port, args.data_dir, args.partitions,
               args.broker)


if __name__ == "__main__":
    main()
