"""Scale-out partitioning — the kafka-service / document-router
analogue.

The reference scales the ordering service by sharding DOCUMENTS over
Kafka partitions: raw ops are produced keyed by document id, each
partition is consumed by a lambda host that demuxes records to
per-document lambda instances, and progress is committed as a
monotonic per-partition offset so a crashed/rebalanced consumer
resumes exactly where the checkpoint says (at-least-once delivery;
deli drops below-checkpoint duplicates by clientSequenceNumber).

Reference shapes rebuilt here:
- ``Partition`` (lambdas-driver/src/kafka-service/partition.ts:26):
  one consumed queue partition -> lambda, with a CheckpointManager.
- ``CheckpointManager`` (kafka-service/checkpointManager.ts:10):
  commit the lowest fully-processed offset, monotonically.
- ``DocumentLambda``/``DocumentPartition``
  (document-router/src/{documentLambda.ts:20,documentPartition.ts:20}):
  demux a partition's record stream to per-document orderers.
- The queue itself (services-ordering-rdkafka
  ``RdkafkaConsumer``/``Producer``) becomes an ``OrderingQueue``
  interface with in-memory and file-backed (durable) impls — the
  deployment seam where a real broker would plug in.

TPU mapping (SURVEY §2.9 axis 1): a partition is the host-side unit of
document-parallelism; each partition's documents batch into the same
TPU sidecar dispatch, and partitions map 1:1 onto mesh doc-axis shards
(parallel/mesh.py) or onto separate hosts (parallel/distributed.py).
"""
from __future__ import annotations

import itertools
import json
import os
import zlib
from typing import Any, Callable, Iterator, Optional

from ..obs import metrics as obs_metrics
from ..obs.trace import stamp as _trace_stamp
from ..protocol.messages import ClientDetail, DocumentMessage, Nack
from ..qos.faults import (
    KIND_DROP,
    KIND_DUPLICATE,
    KIND_ERROR,
    PLANE as _CHAOS,
)
from .local_orderer import LocalOrderer
from .storage import (
    CRC_KEY,
    DocumentStorage,
    atomic_write,
    jsonl_record,
    read_offset_tolerant,
    record_crc,
    scrub_repair_jsonl,
)

# chaos seams (docs/ROBUSTNESS.md): the consume side replays a record
# (at-least-once redelivery — deli's clientSequenceNumber dedupe must
# absorb it); the append side fails transiently (a flaky broker — the
# producer retries once, mirroring RemoteOrderingQueue's reconnect
# retry)
_SITE_APPEND = _CHAOS.site("broker.queue_append", (KIND_ERROR,))
_SITE_CONSUME = _CHAOS.site("broker.queue_consume", (KIND_DUPLICATE,))
# the partitioned plane shares the document plane's replication site
# (one schedule drives both harnesses — the socket.frame_* idiom;
# service/replication.py registers the same name)
_SITE_REPL_ACK = _CHAOS.site("repl.append_ack",
                             (KIND_DROP, KIND_ERROR))

# handling accounting for the seams above: every absorbed fault leaves
# a metric delta (the failsan fault-to-signal contract,
# docs/ROBUSTNESS.md)
_M_APPEND_RETRIES = obs_metrics.REGISTRY.counter(
    "broker_append_retries_total",
    "transiently-failed queue appends retried once by the producer")
_M_REDELIVERED = obs_metrics.REGISTRY.counter(
    "broker_redelivered_records_total",
    "op records replayed by at-least-once consume redelivery "
    "(absorbed by deli's clientSequenceNumber dedupe)")
_M_DEBRIS = obs_metrics.REGISTRY.counter(
    "storage_crash_debris_cleaned_total",
    "leftover write-then-rename tmp files cleared at startup (the "
    "crash-between-write-and-rename state)", labelnames=("file",))
_M_ACK_RETRIES = obs_metrics.REGISTRY.counter(
    "repl_ack_retries_total",
    "transiently-failed follower ack offers retried once "
    "(second failure skips the round; anti-entropy repairs)")


def partition_for(document_id: str, n_partitions: int) -> int:
    """Stable document -> partition routing (the Kafka key hash)."""
    return zlib.crc32(document_id.encode()) % n_partitions


# ----------------------------------------------------------------------
# Ordering queue: the broker seam


class QueueRecord:
    __slots__ = ("offset", "document_id", "payload")

    def __init__(self, offset: int, document_id: str, payload: dict):
        self.offset = offset
        self.document_id = document_id
        self.payload = payload


class OrderingQueue:
    """Partitioned, offset-addressed raw-op transport (the Kafka
    interface: services-ordering-rdkafka/src/rdkafkaProducer.ts:52,
    rdkafkaConsumer.ts:37). At-least-once: consumers re-read from the
    committed offset after a crash."""

    # True only when fanout_lag() is in-process arithmetic (safe to
    # sample on the ingress serving path as a qos pressure source);
    # networked implementations leave this False — their lag belongs
    # in an off-loop sampler, never a blocking probe inside admit()
    fanout_lag_is_local = False

    def produce(self, partition: int, document_id: str,
                payload: dict) -> int:
        raise NotImplementedError

    def read(self, partition: int, from_offset: int
             ) -> Iterator[QueueRecord]:
        raise NotImplementedError

    def committed(self, partition: int) -> int:
        """Last committed (fully processed) offset, -1 if none."""
        raise NotImplementedError

    def commit(self, partition: int, offset: int) -> None:
        raise NotImplementedError


class InMemoryOrderingQueue(OrderingQueue):
    # fanout_lag() is in-process arithmetic: safe to sample on the
    # ingress serving path (qos pressure source). The networked
    # RemoteOrderingQueue is NOT (blocking round trip) and leaves
    # this False.
    fanout_lag_is_local = True

    def __init__(self, n_partitions: int):
        self._logs: list[list[QueueRecord]] = [
            [] for _ in range(n_partitions)
        ]
        self._committed = [-1] * n_partitions

    def produce(self, partition: int, document_id: str,
                payload: dict) -> int:
        log = self._logs[partition]
        rec = QueueRecord(len(log), document_id, payload)
        log.append(rec)
        return rec.offset

    def read(self, partition: int, from_offset: int):
        yield from self._logs[partition][max(0, from_offset):]

    def committed(self, partition: int) -> int:
        return self._committed[partition]

    def commit(self, partition: int, offset: int) -> None:
        if offset > self._committed[partition]:
            self._committed[partition] = offset

    def fanout_lag(self) -> int:
        """Produced-but-uncommitted records across all partitions —
        the consumer-lag signal the qos pressure monitor samples
        (qos/pressure.py 'broker_fanout' source)."""
        return sum(
            len(log) - 1 - committed
            for log, committed in zip(self._logs, self._committed)
        )


class FileOrderingQueue(OrderingQueue):
    """Durable queue: one append-only jsonl per partition + a committed
    offset file — enough broker semantics (ordered, offset-addressed,
    survives the process) for single-box deployments and for the
    crash-restart tests."""

    fanout_lag_is_local = True  # counters in memory, no I/O

    def __init__(self, root: str, n_partitions: int,
                 fsync: bool = False):
        self.root = root
        self.n_partitions = n_partitions
        # fsync-per-produce: the replicated queue turns this on for
        # itself and its follower roots — its quorum-durability claim
        # is only as strong as each node's own write barrier. The
        # plain single-box queue keeps the cheaper buffered append
        # (its durability story is the per-document op log, as in
        # PR9).
        self.fsync = fsync
        os.makedirs(root, exist_ok=True)
        self._counts = [0] * n_partitions
        self._committed = [-1] * n_partitions
        # sequential-read cursor per partition: (record offset, byte
        # position) of the next unread record, so steady-state pumps
        # seek instead of rescanning the log from line 0 (O(N^2) over
        # the log's life otherwise)
        self._cursor: dict[int, tuple[int, int]] = {}
        for p in range(n_partitions):
            if os.path.exists(self._log_path(p)):
                with open(self._log_path(p)) as f:
                    self._counts[p] = sum(1 for _ in f)
            if os.path.exists(self._commit_path(p)):
                # tolerant parse: a pre-barrier torn overwrite (or any
                # garbage) degrades loudly to "no commit" — the
                # consumer re-reads from the head and the deli csn
                # dedupe absorbs the at-least-once replay
                self._committed[p] = read_offset_tolerant(
                    self._commit_path(p), label="queue-offset")
            # a leftover commit tmp is the crash-between-write-and-
            # rename state: the committed file is the truth
            try:
                os.remove(self._commit_path(p) + ".tmp")
                _M_DEBRIS.labels(file="queue-offset").inc()
            except OSError:
                pass

    def _log_path(self, p: int) -> str:
        return os.path.join(self.root, f"partition-{p}.jsonl")

    def _commit_path(self, p: int) -> str:
        return os.path.join(self.root, f"partition-{p}.offset")

    def produce(self, partition: int, document_id: str,
                payload: dict) -> int:
        offset = self._counts[partition]
        with open(self._log_path(partition), "a") as f:
            # crc-stamped record (storage.jsonl_record): the consume
            # path verifies it, so a bit-rotted queue record is
            # detected (and scrub-repairable from a replica root)
            # instead of sequencing garbage
            f.write(jsonl_record(
                {"document_id": document_id, "payload": payload}
            ))
            if self.fsync:
                f.flush()
                os.fsync(f.fileno())
        self._counts[partition] = offset + 1
        return offset

    def read(self, partition: int, from_offset: int):
        from .storage import CorruptRecordError

        path = self._log_path(partition)
        if not os.path.exists(path):
            return
        offset, byte_pos = 0, 0
        cur = self._cursor.get(partition)
        if cur is not None and cur[0] <= from_offset:
            offset, byte_pos = cur
        with open(path) as f:
            f.seek(byte_pos)
            while True:
                line = f.readline()
                if not line:
                    break
                rec_offset = offset
                offset += 1
                self._cursor[partition] = (offset, f.tell())
                if rec_offset < from_offset:
                    continue
                data = json.loads(line)
                if CRC_KEY in data and \
                        data[CRC_KEY] != record_crc(data):
                    raise CorruptRecordError(
                        f"queue record {rec_offset} of partition "
                        f"{partition} ({path!r}) failed its crc — "
                        "bit rot; scrub-repair it from a replica "
                        "root", path=path, index=rec_offset)
                yield QueueRecord(
                    rec_offset, data["document_id"], data["payload"]
                )

    def committed(self, partition: int) -> int:
        return self._committed[partition]

    def commit(self, partition: int, offset: int) -> None:
        if offset <= self._committed[partition]:
            return
        # the shared crash-atomic barrier (storage.atomic_write): the
        # plain overwrite this replaced could leave a TORN offset
        # file — a prefix like "1" of "15" silently rewinds the
        # checkpoint (absorbed, but slow) and garbage used to crash
        # the load (tests/test_durable_storage.py pins both states)
        atomic_write(self._commit_path(partition), str(offset))
        self._committed[partition] = offset

    def fanout_lag(self) -> int:
        """Produced-but-uncommitted records across all partitions
        (the qos 'broker_fanout' pressure source; see
        InMemoryOrderingQueue.fanout_lag)."""
        return sum(
            count - 1 - committed
            for count, committed in zip(self._counts, self._committed)
        )


# ----------------------------------------------------------------------
# Replicated counterparts (service/replication.py is the document-
# plane half; these are the PARTITIONED plane's: the per-partition
# queue log replicates to follower roots behind the same quorum ack,
# and the committed offset mirrors so a promoted follower resumes at
# the replicated head + checkpoint)


class ReplicatedFileOrderingQueue(FileOrderingQueue):
    """FileOrderingQueue with per-partition log replication to N
    follower roots behind a quorum ack — fsync-and-replicate-before-
    fanout for the partitioned plane (every node in the replica set
    fsyncs its appends; the plain queue's buffered write would make
    the quorum claim hollow) — and an epoch fence: given a SHARED
    ``fence`` (it models the external lease/coordination service;
    ``fence=None`` means fencing is explicitly off), a deposed
    producer's appends are refused before any consumer could see
    them. Promotion goes through :meth:`promote`, which — exactly
    like the document plane — anti-entropies the best-replicated
    follower root against every surviving peer first: under dropped
    acks a single follower may legitimately lag, and serving IT
    directly would lose quorum-acked records."""

    def __init__(self, root: str, n_partitions: int,
                 follower_roots: list[str],
                 quorum: Optional[int] = None,
                 fence: Optional[Any] = None,
                 epoch: Optional[int] = None,
                 registry: Optional[Any] = None):
        from .replication import _group_metrics

        super().__init__(root, n_partitions, fsync=True)
        # injectable registry (the replication satellite fix): a
        # partition worker under an in-process multi-node harness
        # keeps its repl series on its OWN registry instead of
        # double-counting into the process-wide one; default None =
        # process-wide, unchanged for production
        self._metrics = _group_metrics(
            registry or obs_metrics.REGISTRY)
        if not follower_roots:
            raise ValueError(
                "a replicated queue needs at least one follower root")
        self.followers = [
            FileOrderingQueue(r, n_partitions, fsync=True)
            for r in follower_roots
        ]
        self.quorum = quorum if quorum is not None else 2
        if self.quorum > 1 + len(self.followers):
            raise ValueError(
                f"quorum {self.quorum} unsatisfiable with "
                f"{len(self.followers)} followers")
        # fencing requires a SHARED EpochFence (it models the external
        # lease/coordination service — a queue-private fence could
        # never observe a competing producer, so defaulting one would
        # read as protection while providing none). fence=None means
        # fencing is explicitly OFF.
        self.fence = fence
        if epoch is not None:
            self.epoch = epoch
        else:
            self.epoch = fence.epoch if fence is not None else 0
        for p in range(n_partitions):
            self._metrics["followers"].labels(partition=str(p)).set(
                len(self.followers))

    @staticmethod
    def promote(follower_roots: list[str], n_partitions: int,
                fence: Optional[Any] = None) -> FileOrderingQueue:
        """Elect the best-replicated follower root into the leader
        role: anti-entropy pulls any missing per-partition suffix
        (and the highest mirrored commit) from every surviving peer —
        a quorum-acked record lives on at least one of them — so the
        promoted queue resumes at the TRUE replicated head, never a
        laggard's. Pass the SHARED ``fence`` to depose the old
        producer as part of promotion (``fence.advance()`` — without
        it a presumed-dead producer that revives keeps writing). The
        document plane's promotion protocol, queue-shaped."""
        queues = [FileOrderingQueue(r, n_partitions, fsync=True)
                  for r in follower_roots]
        best = max(queues, key=lambda q: sum(q._counts))
        for peer in queues:
            if peer is best:
                continue
            for p in range(n_partitions):
                if peer._counts[p] > best._counts[p]:
                    for rec in peer.read(p, best._counts[p]):
                        best.produce(p, rec.document_id, rec.payload)
                best.commit(p, min(peer.committed(p),
                                   best._counts[p] - 1))
        if fence is not None:
            # promotion IS the deposition: every stale-epoch producer
            # and checkpoint commit is refused from here on
            fence.advance()
        return best

    def produce(self, partition: int, document_id: str,
                payload: dict) -> int:
        # fence BEFORE the replicate gate (qoscheck:fence-before-
        # fanout): a deposed producer must not extend any replica
        if self.fence is not None:
            self.fence.check(self.epoch, partition=partition)
        offset = super().produce(partition, document_id, payload)
        self._replicate_before_fanout(partition, offset)
        return offset

    def _replicate_before_fanout(self, partition: int,
                                 offset: int) -> None:
        """Quorum-durable before the consumer side may observe the
        record — same contract (and the same ``repl.append_ack``
        site) as the document plane's barrier."""
        acked = 1  # the leader's own append
        behind: list[FileOrderingQueue] = []
        for f in self.followers:
            fault = _SITE_REPL_ACK.fire(partition=partition,
                                        offset=offset)
            if fault is not None:
                _M_ACK_RETRIES.inc()
                if _SITE_REPL_ACK.fire(
                        partition=partition, offset=offset,
                        retry=True) is not None:
                    behind.append(f)
                    continue
            self._sync_follower(f, partition, offset)
            acked += 1
        for f in behind:
            if acked >= self.quorum:
                break
            # the barrier BLOCKS on the laggard (see
            # ReplicatedSequencerGroup.replicate_before_fanout)
            self._sync_follower(f, partition, offset)
            acked += 1

    def _sync_follower(self, f: FileOrderingQueue, partition: int,
                       upto_offset: int) -> None:
        start = f._counts[partition]
        for rec in self.read(partition, start):
            if rec.offset > upto_offset:
                break
            f.produce(partition, rec.document_id, rec.payload)

    def commit(self, partition: int, offset: int) -> None:
        # the committed offset is CONSUMER authority — a deposed
        # consumer moving it would silently skip records for the
        # real one
        if self.fence is not None:
            self.fence.check(self.epoch, partition=partition,
                             op="commit")
        super().commit(partition, offset)
        for f in self.followers:
            f.commit(partition,
                     min(offset, f._counts[partition] - 1))

    def scrub(self) -> int:
        """Bit-rot scrub over every replica root's partition logs:
        a record that fails its crc on one node is read-repaired from
        any peer whose copy at the same offset is intact (the leader
        included — quorum replication is what makes the repair
        possible). Returns records repaired; raises
        ``CorruptRecordError`` when no peer holds an intact copy."""
        repaired = 0
        nodes = [self] + list(self.followers)
        for p in range(self.n_partitions):
            for node in nodes:
                path = node._log_path(p)
                if not os.path.exists(path):
                    continue

                def fetch(index: int, rows: list,
                          _node=node, _p=p) -> Optional[dict]:
                    for peer in nodes:
                        if peer is _node:
                            continue
                        try:
                            for rec in peer.read(_p, index):
                                return {
                                    "document_id": rec.document_id,
                                    "payload": rec.payload,
                                }
                        except ValueError:
                            # CorruptRecordError (this peer rotted
                            # too) or a raw json decode error (a
                            # torn/garbled line on an fsync=False
                            # peer): either way, try the next peer
                            continue
                    return None

                report = scrub_repair_jsonl(path, "queue", fetch)
                if report.repaired:
                    # the rewrite replaced the inode: drop the
                    # sequential-read cursor so the next read()
                    # reopens at a valid byte position
                    node._cursor.pop(p, None)
                    repaired += report.repaired
        return repaired


class ReplicatedCheckpointManager:
    """CheckpointManager with the epoch fence on every commit: the
    offset checkpoint is the consumer's claim to the partition, and
    two consumers both advancing it is exactly the split-brain the
    fence refuses. Same surface as :class:`CheckpointManager`."""

    def __init__(self, queue: OrderingQueue, partition: int,
                 fence: Any, epoch: int):
        self._inner = CheckpointManager(queue, partition)
        self._fence = fence
        self._epoch = epoch

    def starting(self, offset: int) -> None:
        self._inner.starting(offset)

    def completed(self, offset: int) -> None:
        self._fence.check(self._epoch, op="checkpoint")
        self._inner.completed(offset)


# ----------------------------------------------------------------------
# Checkpoint manager (kafka-service/checkpointManager.ts:10)


class CheckpointManager:
    """Monotonic offset commit over possibly out-of-order record
    completion: the checkpoint is the highest offset BELOW which every
    record has completed."""

    def __init__(self, queue: OrderingQueue, partition: int):
        self._queue = queue
        self._partition = partition
        self._inflight: set[int] = set()
        self._max_seen = queue.committed(partition)

    def starting(self, offset: int) -> None:
        self._inflight.add(offset)
        self._max_seen = max(self._max_seen, offset)

    def completed(self, offset: int) -> None:
        self._inflight.discard(offset)
        floor = min(self._inflight) - 1 if self._inflight \
            else self._max_seen
        if floor >= 0:
            self._queue.commit(self._partition, floor)


# ----------------------------------------------------------------------
# Per-document demux (document-router)


class DocumentPartition:
    """One document's lambda context inside a partition
    (document-router/src/documentPartition.ts:20): owns the document's
    orderer and applies its records in partition order."""

    def __init__(self, document_id: str,
                 orderer_factory: Callable[[str], LocalOrderer]):
        self.document_id = document_id
        self.orderer = orderer_factory(document_id)

    def process(self, payload: dict) -> Optional[Nack]:
        kind = payload.get("kind", "op")
        if kind == "join":
            self.orderer.connect(ClientDetail(**payload["detail"]))
            return None
        if kind == "leave":
            self.orderer.disconnect(payload["client_id"])
            return None
        from .ingress import document_message_from_json

        op = document_message_from_json(payload["op"])
        return self.orderer.submit(payload["client_id"], op)


class Partition:
    """One consumed queue partition (kafka-service/partition.ts:26):
    reads records from the committed offset, demuxes per document,
    commits progress through a CheckpointManager."""

    def __init__(self, queue: OrderingQueue, index: int,
                 orderer_factory: Callable[[str], LocalOrderer],
                 on_nack: Optional[
                     Callable[[str, str, Nack], None]] = None,
                 on_record: Optional[Callable] = None):
        self.queue = queue
        self.index = index
        self.checkpoints = CheckpointManager(queue, index)
        self.documents: dict[str, DocumentPartition] = {}
        self._orderer_factory = orderer_factory
        self._next_offset = queue.committed(index) + 1
        self._on_nack = on_nack
        # copier hook: observes every raw record pre-sequencing
        self._on_record = on_record
        self.paused = False

    def document(self, document_id: str) -> DocumentPartition:
        if document_id not in self.documents:
            self.documents[document_id] = DocumentPartition(
                document_id, self._orderer_factory
            )
        return self.documents[document_id]

    def pump(self, max_records: Optional[int] = None) -> int:
        """Process up to ``max_records`` pending records; returns the
        number processed."""
        if self.paused:
            return 0
        n = 0
        records = self.queue.read(self.index, self._next_offset)
        if max_records is not None:
            # bound the GENERATOR, not the loop: pulling one record
            # past the limit would advance a file-backed queue's read
            # cursor beyond _next_offset and force a full-log rescan
            # on the next pump
            records = itertools.islice(records, max_records)
        for rec in records:
            self.checkpoints.starting(rec.offset)
            payload = rec.payload
            client_id = payload.get("client_id") or \
                (payload.get("detail") or {}).get("client_id", "")
            if self._on_record is not None:
                self._on_record(rec.document_id, client_id, payload)
            nack = self.document(rec.document_id).process(rec.payload)
            if nack is not None and self._on_nack is not None:
                self._on_nack(rec.document_id, client_id, nack)
            if (rec.payload.get("kind", "op") == "op"
                    and _SITE_CONSUME.fire(
                        offset=rec.offset) is not None):
                # chaos seam: at-least-once REDELIVERY of the record
                # (a consumer crash between process and commit replays
                # it) — deli's clientSequenceNumber dedupe must drop
                # the duplicate, or the op log's contiguity assert
                # detonates. Op records only: join/leave are control
                # records the reference's dedupe does not cover.
                _M_REDELIVERED.inc()
                self.document(rec.document_id).process(rec.payload)
            self.checkpoints.completed(rec.offset)
            self._next_offset = rec.offset + 1
            n += 1
        return n


# ----------------------------------------------------------------------
# Partition manager


class PartitionedOrderingService:
    """N-partition ordering service: produce raw ops keyed by document,
    pump partitions to sequence them, resume from checkpoints after a
    crash. The scale-out seam: each partition is independent, so
    partitions can live on different processes/hosts with the queue as
    the only shared substrate (exactly Kafka's role in the
    reference)."""

    def __init__(self, n_partitions: int = 4,
                 queue: Optional[OrderingQueue] = None,
                 durable_dir: Optional[str] = None,
                 copier: Optional[Any] = None,
                 on_nack: Optional[
                     Callable[[str, str, Nack], None]] = None,
                 storage_breaker: Optional[Any] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.n_partitions = n_partitions
        self.durable_dir = durable_dir
        # injectable wall clock for every partition sequencer's wire
        # timestamps; None = real wall time
        self.clock = clock
        # shared qos.CircuitBreaker across every document's checkpoint
        # writes (same semantics as LocalServer.storage_breaker)
        self.storage_breaker = storage_breaker
        # external nack hook: every partition (including ones created
        # by resume_partition) routes through _dispatch_nack, which
        # records centrally then forwards here
        self._on_nack_hook = on_nack
        if queue is None:
            if durable_dir is not None:
                queue = FileOrderingQueue(
                    os.path.join(durable_dir, "queue"), n_partitions
                )
            else:
                queue = InMemoryOrderingQueue(n_partitions)
        self.queue = queue
        self.copier = copier  # CopierLambda: raw pre-deli capture
        self.nacks: list[tuple[str, str, Nack]] = []
        self.partitions = [
            Partition(queue, p, self._make_orderer, self._record_nack,
                      on_record=copier.handler if copier else None)
            for p in range(n_partitions)
        ]

    def _record_nack(self, document_id: str, client_id: str,
                     nack: Nack) -> None:
        self.nacks.append((document_id, client_id, nack))
        if self._on_nack_hook is not None:
            self._on_nack_hook(document_id, client_id, nack)

    def _make_orderer(self, document_id: str) -> LocalOrderer:
        storage = None
        if self.durable_dir is not None:
            storage = DocumentStorage(
                os.path.join(self.durable_dir, "docs", document_id)
            )
        return LocalOrderer(document_id, storage=storage,
                            storage_breaker=self.storage_breaker,
                            clock=self.clock)

    # -- producer side (alfred -> queue) -------------------------------
    def partition_of(self, document_id: str) -> int:
        return partition_for(document_id, self.n_partitions)

    def produce_join(self, document_id: str,
                     detail: ClientDetail) -> None:
        import dataclasses

        self.queue.produce(
            self.partition_of(document_id), document_id,
            {"kind": "join", "detail": dataclasses.asdict(detail)},
        )

    def produce_leave(self, document_id: str, client_id: str) -> None:
        self.queue.produce(
            self.partition_of(document_id), document_id,
            {"kind": "leave", "client_id": client_id},
        )

    def produce_op(self, document_id: str, client_id: str,
                   op: DocumentMessage) -> None:
        from .ingress import document_message_to_json

        # the cross-node hop: the raw op entered the partitioned
        # transport. Stamped BEFORE serialization so the hop rides the
        # queue record to the consuming partition worker (timestamp
        # from the injected clock when one exists — recorded queue
        # corpora stay byte-stable per seed)
        _trace_stamp(op.traces, "partition", "route",
                     timestamp=self.clock() if self.clock else None)
        payload = {"kind": "op", "client_id": client_id,
                   "op": document_message_to_json(op)}
        partition = self.partition_of(document_id)
        # chaos seam: a transiently-failing append (flaky broker) is
        # retried ONCE — the queue mutated nothing when the fault
        # fired, so the retry is exact (RemoteOrderingQueue's
        # drop-and-reconnect retry has the same shape); a second
        # consecutive fault propagates as the loud error it is
        if _SITE_APPEND.fire(doc=document_id) is not None:
            _M_APPEND_RETRIES.inc()
            if _SITE_APPEND.fire(doc=document_id, retry=True) \
                    is not None:
                raise _SITE_APPEND.transient(KIND_ERROR)
        self.queue.produce(partition, document_id, payload)

    # -- consumer side --------------------------------------------------
    def pump(self) -> int:
        """Drain every partition; returns total records processed."""
        return sum(p.pump() for p in self.partitions)

    def orderer(self, document_id: str) -> LocalOrderer:
        p = self.partitions[self.partition_of(document_id)]
        return p.document(document_id).orderer

    # -- rebalance ------------------------------------------------------
    def pause_partition(self, index: int) -> None:
        self.partitions[index].paused = True

    def resume_partition(self, index: int) -> None:
        """Partition reassignment: a fresh consumer takes the partition
        over from its committed checkpoint (Kafka consumer-group
        rebalance). Per-document state is rebuilt from durable deli
        checkpoints + at-least-once replay — which requires durable
        storage; without it the rebuilt orderers would silently restart
        sequencing from 0 while skipping committed records."""
        if self.durable_dir is None:
            raise RuntimeError(
                "partition reassignment requires durable_dir: "
                "document state cannot be rebuilt from an in-memory "
                "consumer (unpause the existing partition instead)"
            )
        self.partitions[index] = Partition(
            self.queue, index, self._make_orderer, self._record_nack,
            on_record=self.copier.handler if self.copier else None,
        )


# ----------------------------------------------------------------------
# LocalServer-surface adapter


class _PartitionedDeltaConnection:
    """DeltaConnection surface whose submit PRODUCES into the queue
    (alfred -> Kafka -> deli), then pumps the owning partition."""

    def __init__(self, server: "PartitionedServer", document_id: str,
                 client_id: str, connection_id: str,
                 read_only: bool = False):
        self._server = server
        self.document_id = document_id
        self.client_id = client_id
        self.connection_id = connection_id
        self.read_only = read_only
        self.open = True
        self.on_message = None
        self.on_nack = None

    def submit(self, op: DocumentMessage) -> None:
        assert self.open, "submit on closed connection"
        if self.read_only:
            raise PermissionError(
                "submit on a read-mode connection (doc:read scope)")
        self._server.svc.produce_op(
            self.document_id, self.client_id, op)
        self._server.pump_document(self.document_id)

    def disconnect(self) -> None:
        if not self.open:
            return
        self.open = False
        orderer = self._server.svc.orderer(self.document_id)
        orderer.broadcaster.unsubscribe(self.connection_id)
        # only remove OUR registration: a reconnect may already have
        # re-registered the same (doc, client) for a newer connection
        key = (self.document_id, self.client_id)
        route = self._server._nack_routes.get(key)
        if route is not None and route[0] == self.connection_id:
            self._server._nack_routes.pop(key, None)
        if not self.read_only:
            self._server.svc.produce_leave(
                self.document_id, self.client_id)
            self._server.pump_document(self.document_id)


class PartitionedServer:
    """The LocalServer surface over the PARTITIONED pipeline: the
    single-box deployment shape where the front door produces raw
    records into the broker seam and per-partition consumers sequence
    them (alfred -> Kafka -> deli -> broadcaster), instead of calling
    deli inline. Drop-in for AlfredServer's ``local=``; selected by
    ``python -m fluidframework_tpu.service --partitions N``."""

    def __init__(self, n_partitions: int = 4,
                 durable_dir: Optional[str] = None,
                 copier=None, queue: Optional[OrderingQueue] = None,
                 storage_breaker=None, clock=None):
        import itertools as _it

        self.svc = PartitionedOrderingService(
            n_partitions=n_partitions, durable_dir=durable_dir,
            copier=copier, on_nack=self._route_nack, queue=queue,
            storage_breaker=storage_breaker, clock=clock,
        )
        self._nack_routes: dict[tuple[str, str], Any] = {}
        self._conn_counter = _it.count()

    @property
    def queue(self):
        """The underlying ordering queue — exposed so the ingress can
        wire its fanout lag as a qos pressure source (the partitioned
        deployment's real backpressure signal lives HERE, not in the
        inline dispatch queue)."""
        return self.svc.queue

    # nacks route to the SUBMITTING client's connection only (alfred
    # emits them on the submitting socket) — the partition hands us
    # the raw record's client id, so the lookup is exact
    def _route_nack(self, document_id: str, client_id: str,
                    nack) -> None:
        route = self._nack_routes.get((document_id, client_id))
        if route is not None:
            route[1](nack)

    def get_orderer(self, document_id: str) -> LocalOrderer:
        return self.svc.orderer(document_id)

    def connect(self, document_id: str, client_id: str,
                on_message, on_nack=None, detail=None,
                read_only: bool = False) -> _PartitionedDeltaConnection:
        orderer = self.svc.orderer(document_id)
        connection_id = f"pconn-{next(self._conn_counter)}"
        conn = _PartitionedDeltaConnection(
            self, document_id, client_id, connection_id,
            read_only=read_only,
        )
        conn.on_message = on_message
        conn.on_nack = on_nack
        # subscribe BEFORE the join so the client sees its own join
        orderer.broadcaster.subscribe(
            connection_id,
            lambda msg: conn.on_message and conn.on_message(msg),
        )
        if on_nack is not None:
            # keyed by (doc, client) -> (connection_id, handler): the
            # newest connection wins, and only its own disconnect may
            # remove the route
            self._nack_routes[(document_id, client_id)] = (
                connection_id, on_nack)
        if not read_only:
            self.svc.produce_join(
                document_id, detail or ClientDetail(client_id))
            self.pump_document(document_id)
        return conn

    def pump_document(self, document_id: str) -> int:
        """Drain only the partition that owns ``document_id`` — the
        connection hot path must not do O(n_partitions) queue reads
        per op."""
        return self.svc.partitions[
            self.svc.partition_of(document_id)
        ].pump()

    def read_ops(self, document_id: str, from_seq: int,
                 to_seq: Optional[int] = None):
        return self.svc.orderer(document_id).op_log.read(
            from_seq, to_seq)

    def latest_summary(self, document_id: str):
        return self.svc.orderer(document_id).summary_store.latest()
