"""Networked ordering broker — the rdkafka tier of the scale-out path.

Reference: server/routerlicious/packages/services-ordering-rdkafka/
src/rdkafkaConsumer.ts:37 / rdkafkaProducer.ts:52 — the reference's
partitions live on a NETWORKED broker so consumer hosts scale out
independently of producers. VERDICT r3 missing #3: the in-repo
``OrderingQueue`` seam only had in-memory and local-file
implementations, so ``--partitions N`` could not span hosts.

This module closes that: a framed-TCP ``BrokerServer`` owns the
durable partition logs (backed by ``FileOrderingQueue``, so broker
restarts preserve offsets and records), and ``RemoteOrderingQueue``
implements the exact ``OrderingQueue`` interface over the wire —
``Partition``/``CheckpointManager``/``PartitionedServer`` plug in
unchanged. Semantics match the reference's consumer contract:

- ordered, offset-addressed records per partition;
- AT-LEAST-ONCE delivery: consumers re-read from the committed offset
  after a crash (commit is monotonic server-side; deli's
  clientSequenceNumber dedupe drops the replayed duplicates);
- committed offsets are durable on the broker, so a consumer host can
  die and a replacement resumes exactly at the checkpoint.

The wire protocol reuses the ingress framing (4-byte length + JSON).
Request/response only — no server push — so the client is a small
blocking socket with no receive pump. Reads return bounded batches
(the consumer pump polls, like a Kafka poll loop).
"""
from __future__ import annotations

import asyncio
import json
import socket
import threading
from typing import Iterator, Optional

from ..obs import metrics as obs_metrics
from .ingress import pack_frame, read_frame, recv_frame_blocking
from .partitioning import (
    FileOrderingQueue,
    InMemoryOrderingQueue,
    OrderingQueue,
    QueueRecord,
)

_PRODUCED = obs_metrics.REGISTRY.counter(
    "broker_records_produced_total", "records appended to partitions")
_READ = obs_metrics.REGISTRY.counter(
    "broker_records_read_total", "records served to consumers")
_COMMITS = obs_metrics.REGISTRY.counter(
    "broker_commits_total", "consumer offset commits")
_BROKER_ERRORS = obs_metrics.REGISTRY.counter(
    "broker_frame_errors_total", "broker frames that raised")
_BROKER_LAG = obs_metrics.REGISTRY.gauge(
    "broker_fanout_lag",
    "produced-but-uncommitted records across partitions at last "
    "sample (the qos backpressure signal)")


class BrokerServer:
    """Framed-TCP broker owning the partition logs."""

    def __init__(self, n_partitions: int,
                 data_dir: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.queue: OrderingQueue = (
            FileOrderingQueue(data_dir, n_partitions)
            if data_dir is not None
            else InMemoryOrderingQueue(n_partitions)
        )
        self.n_partitions = n_partitions
        self.host = host
        self.port = port
        # _dispatch runs on executor threads — the durable
        # FileOrderingQueue appends/commits are disk writes, which
        # must never run on the event loop (the same
        # async-blocking-call shape concheck pinned in moira; here
        # the I/O hides behind the queue seam, out of static
        # resolution's reach, so this fix is belt-and-suspenders).
        # The lock serializes queue access across connections exactly
        # as the loop used to.
        self._state_lock = threading.Lock()
        self._server: Optional[asyncio.base_events.Server] = None
        # dict-as-ordered-set: connection order is deterministic per
        # run, so shutdown fan-out (and any future broadcast) walks a
        # stable order — a plain set iterates per-process
        # (PYTHONHASHSEED), the detcheck iteration-order-leak hazard
        self._writers: dict[asyncio.StreamWriter, None] = {}

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def serve_forever(self) -> None:
        if self._server is None:
            await self.start()
        async with self._server:
            await self._server.serve_forever()

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # abort live client connections or wait_closed() blocks on
            # their handler coroutines (clients poll long-lived)
            for w in list(self._writers):
                try:
                    w.close()
                except Exception:  # pragma: no cover - already gone
                    pass
            await self._server.wait_closed()
            self._server = None

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        self._writers[writer] = None
        loop = asyncio.get_running_loop()
        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                try:
                    resp = await loop.run_in_executor(
                        None, self._dispatch_locked, frame)
                except Exception as e:  # noqa: BLE001 - report per frame
                    _BROKER_ERRORS.inc()
                    resp = {
                        "type": "error",
                        "message": f"{type(e).__name__}: {e}",
                    }
                resp["rid"] = frame.get("rid")
                writer.write(pack_frame(resp))
                await writer.drain()
        finally:
            self._writers.pop(writer, None)
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError,
                    RuntimeError):
                pass  # loop shutting down mid-close is fine

    def _dispatch_locked(self, frame: dict) -> dict:
        with self._state_lock:
            return self._dispatch(frame)

    def _dispatch(self, frame: dict) -> dict:
        kind = frame.get("type")
        p = int(frame.get("partition", -1))
        if not 0 <= p < self.n_partitions and \
                kind not in ("meta", "lag"):
            raise ValueError(f"partition {p} out of range")
        if kind == "produce":
            offset = self.queue.produce(
                p, frame["document_id"], frame["payload"]
            )
            _PRODUCED.inc()
            return {"type": "produced", "offset": offset}
        if kind == "read":
            limit = int(frame.get("max", 500))
            out = []
            for rec in self.queue.read(p, int(frame["from_offset"])):
                out.append({
                    "offset": rec.offset,
                    "document_id": rec.document_id,
                    "payload": rec.payload,
                })
                if len(out) >= limit:
                    break
            _READ.inc(len(out))
            return {"type": "records", "records": out}
        if kind == "committed":
            return {"type": "committed_offset",
                    "offset": self.queue.committed(p)}
        if kind == "commit":
            self.queue.commit(p, int(frame["offset"]))
            _COMMITS.inc()
            return {"type": "commit_ack"}
        if kind == "meta":
            return {"type": "meta",
                    "n_partitions": self.n_partitions}
        if kind == "lag":
            # consumer-lag probe (the qos 'broker_fanout' pressure
            # source): cheap server-side arithmetic, no log reads
            lag = self.fanout_lag()
            return {"type": "lag", "lag": lag}
        raise ValueError(f"unknown broker frame {kind!r}")

    def fanout_lag(self) -> int:
        """Produced-but-uncommitted records across all partitions."""
        lag = self.queue.fanout_lag()
        _BROKER_LAG.set(lag)
        return lag


class RemoteOrderingQueue(OrderingQueue):
    """OrderingQueue over a BrokerServer connection. Strictly
    request/response, so one blocking socket + a lock suffices; the
    connection re-establishes transparently after a broker restart
    (offsets are durable broker-side)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.host = host
        self.port = port
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        meta = self._request({"type": "meta"})
        self.n_partitions = meta["n_partitions"]

    # -- transport -----------------------------------------------------

    def _connect(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout
            )
        return self._sock

    def _request(self, data: dict) -> dict:
        with self._lock:
            for attempt in (0, 1):
                try:
                    sock = self._connect()
                    sock.sendall(pack_frame(data))
                    frame = recv_frame_blocking(sock)
                    break
                except (OSError, ConnectionError):
                    # broker restarted: drop the socket and retry once
                    self._close_sock()
                    if attempt:
                        raise
                except Exception:
                    # protocol fault (oversized/corrupt length prefix
                    # -> ValueError, garbage body -> JSONDecodeError):
                    # the stream position is desynced — the socket
                    # must never be reused, and retrying would parse
                    # mid-frame garbage as a fresh frame
                    self._close_sock()
                    raise
            if frame.get("type") == "error":
                raise RuntimeError(frame.get("message", "broker error"))
            return frame

    def _close_sock(self) -> None:
        # caller holds _lock: _sock is lock-guarded (the retry path
        # in _request swaps it under the same lock)
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        # take the lock: closing concurrently with an in-flight
        # _request must not yank the socket mid-recv (waits for the
        # request to finish instead)
        with self._lock:
            self._close_sock()

    # -- OrderingQueue surface ----------------------------------------

    def produce(self, partition: int, document_id: str,
                payload: dict) -> int:
        return self._request({
            "type": "produce", "partition": partition,
            "document_id": document_id, "payload": payload,
        })["offset"]

    READ_BATCH = 500

    def read(self, partition: int, from_offset: int
             ) -> Iterator[QueueRecord]:
        offset = from_offset
        while True:
            frame = self._request({
                "type": "read", "partition": partition,
                "from_offset": offset, "max": self.READ_BATCH,
            })
            records = frame["records"]
            for r in records:
                yield QueueRecord(
                    r["offset"], r["document_id"], r["payload"]
                )
            if len(records) < self.READ_BATCH:
                # short batch = end of log: no extra empty round trip
                return
            offset = records[-1]["offset"] + 1

    def committed(self, partition: int) -> int:
        return self._request({
            "type": "committed", "partition": partition,
        })["offset"]

    def commit(self, partition: int, offset: int) -> None:
        self._request({
            "type": "commit", "partition": partition,
            "offset": offset,
        })

    # a BLOCKING round trip: tooling/off-loop samplers only — the
    # ingress refuses to wire it as a serving-path pressure source
    # (fanout_lag_is_local stays False; see OrderingQueue)
    def fanout_lag(self) -> int:
        """Broker-side consumer lag (one round trip)."""
        return self._request({"type": "lag"})["lag"]


def run_broker(host: str = "127.0.0.1", port: int = 7081,
               partitions: int = 4,
               data_dir: Optional[str] = None) -> None:
    """Blocking broker entry point (`python -m
    fluidframework_tpu.service.broker`)."""
    broker = BrokerServer(partitions, data_dir, host, port)

    async def main():
        await broker.start()
        print(f"broker listening on {broker.host}:{broker.port} "
              f"({partitions} partitions, "
              f"{'durable' if data_dir else 'in-memory'})",
              flush=True)
        await broker.serve_forever()

    try:
        asyncio.run(main())
    except KeyboardInterrupt:  # pragma: no cover - operator stop
        pass


if __name__ == "__main__":  # pragma: no cover - CLI
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=7081)
    ap.add_argument("--partitions", type=int, default=4)
    ap.add_argument("--data-dir", default=None)
    a = ap.parse_args()
    run_broker(a.host, a.port, a.partitions, a.data_dir)
