"""Moira lambda — PropertyDDS changeset publishing to a Materialized
History service (branch + commit graph).

Reference: server/routerlicious/packages/lambdas/src/moira/lambda.ts
:30 (handler: collect sequenced PropertyDDS changeset ops per branch),
:64 (sendPending: double-buffered pending/current batches, checkpoint
after each published batch), :95 (createDerivedGuid: sha1-derived
uuid), :127 (processMoiraCore: first commit with no referenceGuid
creates the branch with a derived root commit), :154 (createBranch
POST /branch), :183 (createCommit POST /branch/{guid}/commit with
changeSet + rebase flag + seq/msn meta). The reference publishes over
HTTP (Axios) to the Materialized History endpoint; this repo's
service plane is framed TCP (ingress framing), so the MH service here
is a framed-TCP server with the same two verbs and the same record
shapes — drivers/consumers are process-separable exactly like the
broker tier (tests run it in another OS process).

The lambda keeps the reference's batching structure: ``handler``
accumulates sequenced changeset ops per branch; ``flush`` publishes
current batches branch-by-branch IN SEQUENCE ORDER (per-branch
ordering is what the reference's per-branch promise chaining
enforces) and then checkpoints the batch offset via the callback.
Commit guids and the branch root are derived deterministically
(sha1), so every replica of the lambda publishes the identical graph
from the identical stream — determinism-by-sequencing, as everywhere
else in this service tier.
"""
from __future__ import annotations

import asyncio
import hashlib
import json
import os
import socket
import threading
from typing import Any, Callable, Optional

from ..obs import metrics as obs_metrics
from ..protocol.messages import MessageType, SequencedMessage
from .ingress import pack_frame, read_frame, recv_frame_blocking

_COMMITS_PUBLISHED = obs_metrics.REGISTRY.counter(
    "moira_commits_published_total",
    "changeset commits published to materialized history")
_BRANCHES_CREATED = obs_metrics.REGISTRY.counter(
    "moira_branches_created_total", "MH branches created")
_FLUSH_FAILURES = obs_metrics.REGISTRY.counter(
    "moira_flush_failures_total",
    "publish batches restored for at-least-once replay")


def derived_guid(reference_guid: str, identifier: str) -> str:
    """sha1-derived uuid (moira/lambda.ts:95 createDerivedGuid)."""
    h = hashlib.sha1(
        f"{reference_guid}:{identifier}".encode()
    ).hexdigest()
    return f"{h[0:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:32]}"


# ======================================================================
# Materialized History service (framed TCP)


class MaterializedHistoryServer:
    """Branch/commit store behind the two moira verbs. In-memory by
    default; ``data_dir`` makes it durable (one JSON log per branch)
    so a restarted MH process serves the published history back."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 data_dir: Optional[str] = None):
        self.host = host
        self.port = port
        self.data_dir = data_dir
        self.branches: dict[str, dict] = {}
        # _dispatch runs on executor threads (its _persist does file
        # I/O, which must never run on the event loop — concheck's
        # async-blocking-call rule); the lock serializes branch-state
        # access across connections exactly as the loop used to
        self._state_lock = threading.Lock()
        self._server: Optional[asyncio.base_events.Server] = None
        if data_dir is not None:
            os.makedirs(data_dir, exist_ok=True)
            for name in os.listdir(data_dir):
                if name.endswith(".json"):
                    with open(os.path.join(data_dir, name)) as f:
                        b = json.load(f)
                    self.branches[b["guid"]] = b

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def serve_forever(self) -> None:
        assert self._server is not None
        async with self._server:
            await self._server.serve_forever()

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    def _persist(self, branch: dict) -> None:
        if self.data_dir is None:
            return
        path = os.path.join(self.data_dir, f"{branch['guid']}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(branch, f)
        os.replace(tmp, path)

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        loop = asyncio.get_running_loop()
        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                try:
                    # executor hop: _persist writes the branch log to
                    # disk, and a disk stall must park only THIS
                    # request, not every connection on the loop
                    resp = await loop.run_in_executor(
                        None, self._dispatch_locked, frame)
                except Exception as e:  # noqa: BLE001 - per frame
                    resp = {"type": "error",
                            "message": f"{type(e).__name__}: {e}"}
                resp["rid"] = frame.get("rid")
                writer.write(pack_frame(resp))
                await writer.drain()
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError,
                    RuntimeError):
                pass

    def _dispatch_locked(self, frame: dict) -> dict:
        with self._state_lock:
            return self._dispatch(frame)

    def _dispatch(self, frame: dict) -> dict:
        kind = frame.get("type")
        if kind == "branch":
            # POST /branch (lambda.ts:154): idempotent — the lambda
            # may republish after a crash-replay
            guid = str(frame["guid"])
            if guid not in self.branches:
                self.branches[guid] = {
                    "guid": guid,
                    "rootCommitGuid": str(frame["rootCommitGuid"]),
                    "meta": frame.get("meta", {}),
                    "commits": [],
                }
                self._persist(self.branches[guid])
            return {"type": "branch_ok",
                    "rootCommitGuid":
                        self.branches[guid]["rootCommitGuid"]}
        if kind == "commit":
            # POST /branch/{guid}/commit (lambda.ts:183); idempotent
            # on commit guid for at-least-once publishing
            branch = self.branches.get(str(frame["branchGuid"]))
            if branch is None:
                raise KeyError(
                    f"unknown branch {frame['branchGuid']!r}")
            guid = str(frame["guid"])
            if all(c["guid"] != guid for c in branch["commits"]):
                heads = ([branch["rootCommitGuid"]]
                         + [c["guid"] for c in branch["commits"]])
                if str(frame["parentGuid"]) not in heads:
                    raise ValueError(
                        f"commit {guid} parent "
                        f"{frame['parentGuid']!r} not in branch")
                branch["commits"].append({
                    "guid": guid,
                    "parentGuid": str(frame["parentGuid"]),
                    "meta": frame.get("meta", {}),
                    "changeSet": frame.get("changeSet"),
                    "rebase": bool(frame.get("rebase", True)),
                })
                self._persist(branch)
            return {"type": "commit_ok", "guid": guid}
        if kind == "branch_get":
            branch = self.branches.get(str(frame["guid"]))
            return {"type": "branch_state", "branch": branch}
        raise ValueError(f"unknown moira frame {kind!r}")


class MaterializedHistoryClient:
    """Blocking request/response client for the MH server (the
    lambda's Axios equivalent over the repo's framed-TCP plane)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.host = host
        self.port = port
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._rid = 0
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout
            )
        return self._sock

    def _request(self, data: dict) -> dict:
        with self._lock:
            self._rid += 1
            data = dict(data, rid=self._rid)
            try:
                sock = self._connect()
                sock.sendall(pack_frame(data))
                resp = recv_frame_blocking(sock)
            except Exception:
                # connection faults AND protocol faults (oversized/
                # corrupt frame -> ValueError/JSONDecodeError): in
                # either case the stream position is unusable — drop
                # the socket so the next request reconnects fresh
                self._close_sock()  # already under _lock
                raise
        if resp.get("type") == "error":
            raise RuntimeError(resp.get("message", "MH error"))
        return resp

    def create_branch(self, guid: str, root_commit_guid: str,
                      meta: Optional[dict] = None) -> str:
        resp = self._request({
            "type": "branch", "guid": guid,
            "rootCommitGuid": root_commit_guid,
            "meta": meta or {},
        })
        return resp["rootCommitGuid"]

    def create_commit(self, branch_guid: str, guid: str,
                      parent_guid: str, meta: dict,
                      change_set: Any, rebase: bool = True) -> None:
        self._request({
            "type": "commit", "branchGuid": branch_guid,
            "guid": guid, "parentGuid": parent_guid, "meta": meta,
            "changeSet": change_set, "rebase": rebase,
        })

    def get_branch(self, guid: str) -> Optional[dict]:
        return self._request(
            {"type": "branch_get", "guid": guid}
        )["branch"]

    def _close_sock(self) -> None:
        # caller holds _lock: _sock is swapped under it by _connect
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def close(self) -> None:
        # lock so a close racing an in-flight _request waits for the
        # request instead of yanking its socket mid-recv
        with self._lock:
            self._close_sock()


# ======================================================================
# the lambda


class MoiraLambda:
    """Watches the sequenced stream for PropertyDDS changeset ops and
    publishes them as commits on per-channel branches.

    ``document_id`` scopes branch identity; the branch guid is derived
    from document/datastore/channel (the reference reads the branch
    guid from the op envelope's address — lambda.ts:110). ``handler``
    only collects; ``flush`` publishes and checkpoints, mirroring the
    reference's pending/current swap (lambda.ts:64) — callers drive
    flush from their pump/partition loop.
    """

    def __init__(self, client: MaterializedHistoryClient,
                 document_id: str,
                 checkpoint: Optional[Callable[[Any], None]] = None):
        self.client = client
        self.document_id = document_id
        self._checkpoint = checkpoint
        # branch guid -> list of (seq, msn, changeset)
        self.pending: dict[str, list[tuple[int, int, Any]]] = {}
        self._pending_offset: Any = None
        # branch guid -> head commit guid (created branches only)
        self.heads: dict[str, str] = {}
        self.published = 0

    # -- stream side ---------------------------------------------------

    def handler(self, msg: SequencedMessage,
                offset: Any = None) -> None:
        """Collect a sequenced message (lambda.ts:30). Uncompressed
        channel-op envelopes only — compressed batches are opaque
        here, exactly as the reference's JSON.parse of the raw op
        contents only sees plain PropertyDDS submissions."""
        if msg.type != MessageType.OPERATION:
            return
        env = msg.contents
        if not (isinstance(env, dict) and env.get("kind") == "op"):
            return
        contents = env.get("contents")
        if not (isinstance(contents, dict)
                and "changeset" in contents):
            return
        branch = derived_guid(
            self.document_id,
            f"{env.get('address')}/{env.get('channel')}",
        )
        self.pending.setdefault(branch, []).append((
            msg.sequence_number,
            msg.minimum_sequence_number,
            contents["changeset"],
        ))
        self._pending_offset = offset

    # -- publish side --------------------------------------------------

    def flush(self) -> int:
        """Publish all pending batches (lambda.ts:64 sendPending /
        :127 processMoiraCore), then checkpoint. Returns commits
        published. Per-branch order is sequence order; a failure
        raises with pending intact, so a crash-restart replays
        at-least-once into the idempotent MH verbs."""
        if not self.pending:
            return 0
        current, self.pending = self.pending, {}
        offset, self._pending_offset = self._pending_offset, None
        try:
            n = 0
            for branch in sorted(current):
                for seq, msn, changeset in current[branch]:
                    parent = self.heads.get(branch)
                    if parent is None:
                        # first commit with no reference: create the
                        # branch with the derived root (lambda.ts:145)
                        parent = self.client.create_branch(
                            branch, derived_guid(branch, "root"),
                            meta={"documentId": self.document_id},
                        )
                        _BRANCHES_CREATED.inc()
                    commit = derived_guid(branch, f"commit-{seq}")
                    self.client.create_commit(
                        branch, commit, parent,
                        meta={
                            "sequenceNumber": seq,
                            "minimumSequenceNumber": msn,
                        },
                        change_set=changeset, rebase=True,
                    )
                    self.heads[branch] = commit
                    n += 1
            self.published += n
            _COMMITS_PUBLISHED.inc(n)
        except Exception:
            # restore for replay (context.error(restart) equivalent)
            _FLUSH_FAILURES.inc()
            for b, items in current.items():
                self.pending.setdefault(b, [])[:0] = items
            self._pending_offset = offset
            raise
        if self._checkpoint is not None and offset is not None:
            self._checkpoint(offset)
        return n

    def close(self) -> None:
        self.pending.clear()


def run_mh_server(host: str = "127.0.0.1", port: int = 7091,
                  data_dir: Optional[str] = None) -> None:
    """Blocking MH entry point (`python -m
    fluidframework_tpu.service.moira`)."""
    server = MaterializedHistoryServer(host, port, data_dir)

    async def main():
        await server.start()
        print(f"materialized-history listening on "
              f"{server.host}:{server.port} "
              f"({'durable' if data_dir else 'in-memory'})",
              flush=True)
        await server.serve_forever()

    try:
        asyncio.run(main())
    except KeyboardInterrupt:  # pragma: no cover - operator stop
        pass


if __name__ == "__main__":  # pragma: no cover - CLI
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=7091)
    ap.add_argument("--data-dir", default=None)
    a = ap.parse_args()
    run_mh_server(a.host, a.port, a.data_dir)
