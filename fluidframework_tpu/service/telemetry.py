"""Service-side structured telemetry: Lumber/Lumberjack.

Reference: server/routerlicious/packages/services-telemetry —
``Lumber`` (src/lumber.ts:23): one metric with properties, timing and
success/failure outcome; ``Lumberjack`` (src/lumberjack.ts:21): the
factory with pluggable engines (sinks).
"""
from __future__ import annotations

import time
from enum import Enum
from typing import Any, Optional


class LumberType(Enum):
    METRIC = "metric"
    LOG = "log"


class Lumber:
    """lumber.ts:23 — one unit of service telemetry."""

    def __init__(self, event_name: str, lumber_type: LumberType,
                 engines: list, properties: Optional[dict] = None):
        self.event_name = event_name
        self.type = lumber_type
        self._engines = engines
        self.properties: dict[str, Any] = dict(properties or {})
        self.start_time = time.time()
        self.duration_ms: Optional[float] = None
        self.successful: Optional[bool] = None
        self.message: Optional[str] = None
        self._emitted = False

    def set_property(self, key: str, value: Any) -> "Lumber":
        self.properties[key] = value
        return self

    def success(self, message: str = "") -> None:
        self._complete(True, message)

    def error(self, message: str = "",
              exception: Optional[BaseException] = None) -> None:
        if exception is not None:
            self.properties["exception"] = repr(exception)
        self._complete(False, message)

    def _complete(self, successful: bool, message: str) -> None:
        if self._emitted:
            # A double-completion is a caller bug, but the old
            # ``assert`` guard vanished under ``python -O`` (silent
            # double emit) and crashed the service path otherwise
            # (interpreter-dependent behavior either way). Record it
            # LOUDLY as its own error event instead: the first
            # emission stands, the duplicate becomes evidence.
            from ..obs import metrics as _metrics

            _metrics.REGISTRY.counter(
                "telemetry_lumber_double_emit_total",
                "Lumber success()/error() called after completion",
            ).inc()
            dup = Lumber(
                f"{self.event_name}:doubleEmit", LumberType.LOG,
                self._engines, dict(self.properties),
            )
            dup.properties["firstOutcome"] = self.successful
            dup.properties["secondOutcome"] = successful
            dup._emitted = True
            dup.duration_ms = 0.0
            dup.successful = False
            dup.message = (
                f"lumber {self.event_name!r} completed twice "
                f"(second message: {message!r})"
            )
            for engine in self._engines:
                engine.emit(dup)
            return
        self._emitted = True
        self.duration_ms = (time.time() - self.start_time) * 1000
        self.successful = successful
        self.message = message
        for engine in self._engines:
            engine.emit(self)


class Lumberjack:
    """lumberjack.ts:21 — engine registry + metric factory."""

    def __init__(self, engines: Optional[list] = None,
                 global_properties: Optional[dict] = None):
        self.engines = list(engines or [])
        self.global_properties = dict(global_properties or {})

    def add_engine(self, engine) -> None:
        self.engines.append(engine)

    def new_metric(self, event_name: str,
                   properties: Optional[dict] = None) -> Lumber:
        return Lumber(
            event_name, LumberType.METRIC, self.engines,
            {**self.global_properties, **(properties or {})},
        )

    def log(self, event_name: str, message: str = "",
            properties: Optional[dict] = None) -> None:
        lumber = Lumber(
            event_name, LumberType.LOG, self.engines,
            {**self.global_properties, **(properties or {})},
        )
        lumber.success(message)


class InMemoryLumberjackEngine:
    """Test/engine double (services-telemetry test engines)."""

    def __init__(self) -> None:
        self.emitted: list[Lumber] = []

    def emit(self, lumber: Lumber) -> None:
        self.emitted.append(lumber)

    def events_named(self, event_name: str) -> list[Lumber]:
        return [l for l in self.emitted if l.event_name == event_name]
