"""PropertyDDS — the typed property-tree DDS family.

Reference: experimental/PropertyDDS/packages — ``property-properties``
(typed property tree: NodeProperty containers, value properties with
typeids, array/map contexts), ``property-changeset`` (ChangeSet with
insert/modify/remove + SQUASH composition), ``property-dds``
(SharedPropertyTree: local edits accumulate into a working changeset
that COMMIT submits as one op).

The distinctive semantics rebuilt here (not shared by map/tree DDSes):

- **Typed schemas**: property templates are registered by typeid and
  validated at insert (property-properties PropertyFactory.register).
- **Commit model**: edits do NOT stream op-per-mutation; they squash
  into a working changeset locally and ship on ``commit()``
  (property-dds SharedPropertyTree.commit). Remote changesets apply
  atomically per commit.
- **ChangeSet squash**: insert∘modify = insert(updated), insert∘remove
  = nothing, modify∘modify = last, modify∘remove = remove, remove∘
  insert = replace-insert (property-changeset ChangeSet.applyChangeSet
  squash rules).
- **Path-addressed merge**: concurrent commits merge per path — LWW on
  modify, remove-wins over nested edits (a modify under a removed
  subtree is a no-op because the path no longer resolves).
"""
from __future__ import annotations

import copy
from typing import Any, Optional

from ..protocol.messages import SequencedMessage
from ..runtime.shared_object import SharedObject
from ..utils.events import EventEmitter

PRIMITIVES = {"Int32", "Float64", "String", "Bool"}
_DEFAULTS = {"Int32": 0, "Float64": 0.0, "String": "", "Bool": False}


class PropertySchemaRegistry:
    """PropertyFactory.register analogue: templates by typeid."""

    def __init__(self):
        self._templates: dict[str, dict] = {}

    def register(self, template: dict) -> None:
        tid = template["typeid"]
        for prop in template.get("properties", []):
            if "id" not in prop or "typeid" not in prop:
                raise ValueError(f"malformed template {tid!r}")
        self._templates[tid] = template

    def get(self, typeid: str) -> Optional[dict]:
        return self._templates.get(typeid)

    def instantiate(self, typeid: str, value: Any = None) -> dict:
        """Build a property node of ``typeid`` (recursively for
        template-typed children)."""
        if typeid in PRIMITIVES:
            v = value if value is not None else _DEFAULTS[typeid]
            _check_primitive(typeid, v)
            return {"typeid": typeid, "value": v}
        if typeid in ("NodeProperty", "map", "array"):
            node = {"typeid": typeid,
                    "children": {} if typeid != "array" else []}
            return node
        template = self.get(typeid)
        if template is None:
            raise ValueError(f"unregistered typeid {typeid!r}")
        children: dict[str, dict] = {}
        for prop in template.get("properties", []):
            ctx = prop.get("context", "single")
            if ctx == "array":
                children[prop["id"]] = {"typeid": "array",
                                        "children": []}
            elif ctx == "map":
                children[prop["id"]] = {"typeid": "map",
                                        "children": {}}
            else:
                children[prop["id"]] = self.instantiate(prop["typeid"])
        node = {"typeid": typeid, "children": children}
        if value:
            for k, v in value.items():
                if k not in children:
                    raise ValueError(
                        f"{typeid!r} has no property {k!r}")
                ch = children[k]
                if ch["typeid"] in PRIMITIVES:
                    _check_primitive(ch["typeid"], v)
                    ch["value"] = v
                else:
                    raise ValueError(
                        f"cannot initialize non-primitive {k!r} inline")
        return node


def _check_primitive(typeid: str, v: Any) -> None:
    ok = {
        "Int32": lambda x: isinstance(x, int)
        and not isinstance(x, bool),
        "Float64": lambda x: isinstance(x, (int, float))
        and not isinstance(x, bool),
        "String": lambda x: isinstance(x, str),
        "Bool": lambda x: isinstance(x, bool),
    }[typeid]
    if not ok(v):
        raise TypeError(f"{v!r} is not a {typeid}")


# ----------------------------------------------------------------------
# changesets: {"insert": {path: node}, "modify": {path: value},
#              "remove": [path]}   (paths are "a.b.c" strings)


def empty_changeset() -> dict:
    return {"insert": {}, "modify": {}, "remove": []}


def is_empty(cs: dict) -> bool:
    return not cs["insert"] and not cs["modify"] and not cs["remove"]


def squash(base: dict, nxt: dict) -> dict:
    """base then nxt, composed (ChangeSet.applyChangeSet squash)."""
    out = copy.deepcopy(base)
    for path in nxt["remove"]:
        if path in out["insert"]:
            # insert∘remove annihilates
            del out["insert"][path]
        else:
            owner = _insert_owning(out["insert"], path)
            if owner is not None:
                # the removed path lives INSIDE a pending insert:
                # delete it from the insert spec (a global remove
                # would no-op — removes apply before inserts)
                ins_path, node = owner
                _remove_in_node(node, _rel(path, ins_path))
            elif path not in out["remove"]:
                out["remove"].append(path)
        # drop any earlier edits at/under the removed path
        out["modify"] = {
            p: v for p, v in out["modify"].items()
            if not _under(p, path)
        }
        out["insert"] = {
            p: v for p, v in out["insert"].items()
            if not _under(p, path)
        }
    for path, node in nxt["insert"].items():
        # remove∘insert = replace (keep the remove so apply clears
        # first), insert wins the slot
        out["insert"][path] = copy.deepcopy(node)
    for path, val in nxt["modify"].items():
        owner = _insert_owning(out["insert"], path)
        if owner is not None:
            ins_path, node = owner
            _modify_in_node(node, _rel(path, ins_path), val)
        else:
            out["modify"][path] = val
    return out


def _under(path: str, prefix: str) -> bool:
    return path == prefix or path.startswith(prefix + ".")


def _insert_owning(inserts: dict, path: str):
    for ip, node in inserts.items():
        if _under(path, ip):
            return ip, node
    return None


def _rel(path: str, prefix: str) -> list[str]:
    if path == prefix:
        return []
    return path[len(prefix) + 1:].split(".")


def _remove_in_node(node: dict, rel: list[str]) -> None:
    cur = node
    for part in rel[:-1]:
        kids = cur.get("children")
        if kids is None:
            return
        cur = kids[int(part)] if isinstance(kids, list) else kids[part]
    kids = cur.get("children")
    leaf = rel[-1]
    if isinstance(kids, list):
        i = int(leaf)
        if 0 <= i < len(kids):
            del kids[i]
    elif kids is not None:
        kids.pop(leaf, None)


def _modify_in_node(node: dict, rel: list[str], val: Any) -> None:
    cur = node
    for part in rel:
        kids = cur.get("children")
        if isinstance(kids, list):
            cur = kids[int(part)]
        else:
            cur = kids[part]
    _check_primitive(cur["typeid"], val) \
        if cur["typeid"] in PRIMITIVES else None
    cur["value"] = val


# ----------------------------------------------------------------------
# the DDS


class SharedPropertyTree(SharedObject, EventEmitter):
    """property-dds SharedPropertyTree: a typed property tree with
    squash-on-commit changesets."""

    type_name = "sharedpropertytree"

    def __init__(self, channel_id: str,
                 schemas: Optional[PropertySchemaRegistry] = None):
        SharedObject.__init__(self, channel_id)
        EventEmitter.__init__(self)
        self.schemas = schemas or PropertySchemaRegistry()
        self._root: dict = {"typeid": "NodeProperty", "children": {}}
        self._working = empty_changeset()   # uncommitted local edits
        self._pending: list[dict] = []      # committed, unacked

    # ---- navigation

    def _resolve(self, state: dict, path: str,
                 create: bool = False) -> Optional[dict]:
        if path == "":
            return state
        cur = state
        for part in path.split("."):
            kids = cur.get("children")
            if kids is None:
                return None
            if isinstance(kids, list):
                i = int(part)
                if not (0 <= i < len(kids)):
                    return None
                cur = kids[i]
            elif part in kids:
                cur = kids[part]
            else:
                return None
        return cur

    def resolve(self, path: str) -> Optional[dict]:
        """Resolve against the local (optimistic) view."""
        return self._resolve(self._local_view(), path)

    def get_value(self, path: str, default: Any = None) -> Any:
        node = self.resolve(path)
        return default if node is None else node.get("value", default)

    # ---- editing (property-properties mutation API)

    def insert_property(self, path: str, typeid: str,
                        value: Any = None) -> None:
        node = self.schemas.instantiate(typeid, value)
        self._working = squash(
            self._working,
            {"insert": {path: node}, "modify": {}, "remove": []})
        self.emit("changed", path)

    def set_value(self, path: str, value: Any) -> None:
        view = self._local_view()
        target = self._resolve(view, path)
        if target is None:
            raise KeyError(f"no property at {path!r}")
        if target["typeid"] in PRIMITIVES:
            _check_primitive(target["typeid"], value)
        self._working = squash(
            self._working,
            {"insert": {}, "modify": {path: value}, "remove": []})
        self.emit("changed", path)

    def remove_property(self, path: str) -> None:
        self._working = squash(
            self._working,
            {"insert": {}, "modify": {}, "remove": [path]})
        self.emit("changed", path)

    def commit(self) -> None:
        """Ship the squashed working changeset as ONE op
        (SharedPropertyTree.commit)."""
        if is_empty(self._working):
            return
        cs, self._working = self._working, empty_changeset()
        self._pending.append(cs)
        self.submit_local_message({"changeset": cs})

    @property
    def dirty(self) -> bool:
        return not is_empty(self._working)

    # ---- state

    def _apply_changeset(self, state: dict, cs: dict) -> None:
        for path in cs["remove"]:
            self._remove_at(state, path)
        for path, node in cs["insert"].items():
            parent_path, _, leaf = path.rpartition(".")
            parent = self._resolve(state, parent_path)
            if parent is None:
                continue  # parent concurrently removed: edit is moot
            kids = parent.get("children")
            if isinstance(kids, list):
                i = min(int(leaf), len(kids))
                kids.insert(i, copy.deepcopy(node))
            elif kids is not None:
                kids[leaf] = copy.deepcopy(node)
        for path, val in cs["modify"].items():
            target = self._resolve(state, path)
            if target is None:
                continue  # concurrently removed: remove wins
            target["value"] = val

    def _remove_at(self, state: dict, path: str) -> None:
        parent_path, _, leaf = path.rpartition(".")
        parent = self._resolve(state, parent_path)
        if parent is None:
            return
        kids = parent.get("children")
        if isinstance(kids, list):
            i = int(leaf)
            if 0 <= i < len(kids):
                del kids[i]
        elif kids is not None:
            kids.pop(leaf, None)

    def _local_view(self) -> dict:
        view = copy.deepcopy(self._root)
        for cs in self._pending:
            self._apply_changeset(view, cs)
        self._apply_changeset(view, self._working)
        return view

    # ---- SharedObject contract

    def process_core(self, msg: SequencedMessage, local: bool,
                     local_op_metadata: Any = None) -> None:
        cs = msg.contents["changeset"]
        self._apply_changeset(self._root, cs)
        if local and self._pending:
            self._pending.pop(0)
        self.emit("commitApplied", local)

    def resubmit_core(self, contents: Any, metadata: Any = None) -> None:
        self.submit_local_message(contents, metadata)

    def apply_stashed_op(self, contents: Any) -> Any:
        self._pending.append(contents["changeset"])
        return contents

    def summarize_core(self) -> dict:
        assert not self._pending and is_empty(self._working), \
            "summarize with uncommitted local changes"
        return {"version": 1, "root": copy.deepcopy(self._root)}

    def load_core(self, summary: dict) -> None:
        self._root = copy.deepcopy(summary["root"])

    def signature(self) -> Any:
        return self._root
