"""OT bridge — operational-transform channels.

Reference: experimental/dds/ot/ot/src/ot.ts — the generic
``SharedOT<TState, TOp>`` base keeps (a) a GLOBAL state = every
sequenced op applied in order, (b) the window of sequenced ops above
the msn, and (c) the local pending queue; an incoming sequenced op is
TRANSFORMED over every sequenced op its sender had not seen
(refSeq < seq, different client) before joining the global state
(ot.ts:91-118 processCore). The optimistic local view is global +
pending, rebuilt lazily (ot.ts:42-45). The collab window prune is
ot.ts:93-96 (ops below minSeq can never transform anything again).

The concrete type here is a JSON OT (the reference wraps sharejs
json1): path-addressed components over nested dicts/lists. It is an
original, deliberately small composition of the classic json-OT rules
— list-index shifting, deleted-subtree dropping, commuting numeric
adds — not a port of json1's internals.
"""
from __future__ import annotations

import abc
import copy
from dataclasses import dataclass
from typing import Any, Optional

from ..protocol.messages import SequencedMessage
from ..runtime.shared_object import SharedObject
from ..utils.events import EventEmitter


@dataclass
class _SeqOp:
    seq: int
    client: Optional[str]
    op: Any


class SharedOT(SharedObject, EventEmitter):
    """Generic transform-based channel (ot.ts:22). Subclasses define
    ``apply_core(state, op) -> state`` and ``transform(input, over) ->
    op`` (adjust ``input`` for an earlier-sequenced ``over``)."""

    def __init__(self, channel_id: str, initial: Any):
        SharedObject.__init__(self, channel_id)
        EventEmitter.__init__(self)
        self._global = initial
        self._sequenced: list[_SeqOp] = []
        self._pending: list[Any] = []
        self._local: Any = initial
        self._dirty = False

    # ---- abstract OT type

    @abc.abstractmethod
    def apply_core(self, state: Any, op: Any) -> Any:
        """Apply ``op`` to ``state``, returning the new state."""

    @abc.abstractmethod
    def transform(self, input_op: Any, over: Any) -> Any:
        """Adjust ``input_op`` to account for the earlier ``over``."""

    # ---- public

    @property
    def state(self) -> Any:
        if self._dirty:
            s = self._global
            for op in self._pending:
                s = self.apply_core(s, op)
            self._local = s
            self._dirty = False
        return self._local

    def apply(self, op: Any) -> None:
        """Optimistically apply + submit (ot.ts:54 apply)."""
        self._local = self.apply_core(self.state, op)
        self._pending.append(op)
        self.submit_local_message({"op": op})

    # ---- SharedObject contract

    def process_core(self, msg: SequencedMessage, local: bool,
                     local_op_metadata: Any = None) -> None:
        op = msg.contents["op"]
        # transform over concurrent ops the sender had not seen
        for info in self._sequenced:
            if msg.reference_sequence_number < info.seq \
                    and msg.client_id != info.client:
                op = self.transform(op, info.op)
        self._sequenced.append(
            _SeqOp(msg.sequence_number, msg.client_id, op))
        self._global = self.apply_core(self._global, op)
        if local and self._pending:
            self._pending.pop(0)
        else:
            # transform the pending local queue over the remote op so the
            # optimistic view replays against the shifted global state
            # (ot.ts:125-127 pendingOps[i] = transform(pendingOps[i], op))
            self._pending = [self.transform(p, op) for p in self._pending]
        self._dirty = True
        self.emit("op", local)

    def on_sequence_advance(self, seq: int, min_seq: int) -> None:
        while self._sequenced and self._sequenced[0].seq < min_seq:
            self._sequenced.pop(0)

    def resubmit_core(self, contents: Any, metadata: Any = None) -> None:
        self.submit_local_message(contents, metadata)

    def apply_stashed_op(self, contents: Any) -> Any:
        self._pending.append(contents["op"])
        self._dirty = True
        return contents

    def summarize_core(self) -> dict:
        assert not self._pending, "summarize with pending local ops"
        return {"state": copy.deepcopy(self._global)}

    def load_core(self, summary: dict) -> None:
        self._global = copy.deepcopy(summary["state"])
        self._local = self._global
        self._dirty = False

    def signature(self) -> Any:
        return self._global


# ----------------------------------------------------------------------
# JSON OT type
#
# An op is a LIST of components, applied in order. Components:
#   {"p": [...path], "oi": v}            set object key (insert/replace)
#   {"p": [...path], "od": true}         delete object key
#   {"p": [...path, i], "li": v}         list insert at index i
#   {"p": [...path, i], "ld": true}      list delete at index i
#   {"p": [...path], "na": n}            add n to a number
# Paths address into nested dicts (str keys) and lists (int indices).


def _descend(state, path):
    cur = state
    for k in path:
        cur = cur[k]
    return cur


def _apply_component(state, c):
    path = c["p"]
    if "na" in c:
        parent = _descend(state, path[:-1])
        parent[path[-1]] = (parent[path[-1]] or 0) + c["na"]
        return
    if "oi" in c:
        _descend(state, path[:-1])[path[-1]] = copy.deepcopy(c["oi"])
        return
    if "od" in c:
        _descend(state, path[:-1]).pop(path[-1], None)
        return
    if "li" in c:
        seq = _descend(state, path[:-1])
        idx = min(path[-1], len(seq))
        seq.insert(idx, copy.deepcopy(c["li"]))
        return
    if "ld" in c:
        seq = _descend(state, path[:-1])
        if path[-1] < len(seq):
            del seq[path[-1]]
        return
    raise ValueError(f"unknown component {c}")


def _is_prefix(prefix, path):
    return len(prefix) <= len(path) and path[:len(prefix)] == prefix


def _transform_component(c, o):
    """Transform component ``c`` over earlier component ``o``; returns
    the adjusted component or None (dropped)."""
    c = copy.deepcopy(c)
    cp, op_ = c["p"], o["p"]

    if "ld" in o or "li" in o:
        d = len(op_) - 1          # index position within the list path
        same_list = len(cp) > d and cp[:d] == op_[:d] \
            and isinstance(cp[d], int)
        if not same_list:
            return c
        ci, idx = cp[d], op_[d]
        if "ld" in o:
            if ci > idx:
                cp[d] = ci - 1
            elif ci == idx:
                if len(cp) > d + 1:
                    return None     # c addressed inside the deleted one
                if "li" in c:
                    pass            # insert at the vacated index: fine
                else:
                    return None     # element gone (ld/oi/od/na on it)
        else:  # li
            # tie at the same index: the earlier-sequenced insert
            # keeps the left slot, later shifts right
            if ci >= idx:
                cp[d] = ci + 1
        return c

    if "od" in o:
        # key (and subtree) gone: ops inside it drop; a sibling oi on
        # the same key recreates it and survives
        if _is_prefix(op_, cp):
            if len(cp) == len(op_) and "oi" in c:
                return c
            return None
        return c

    if "oi" in o:
        # a replace invalidates ops INSIDE the old subtree — and a
        # numeric add ON the replaced value (the replacement may not
        # be a number; adding to it is meaningless and would poison
        # apply on every replica)
        if _is_prefix(op_, cp):
            if len(cp) > len(op_):
                return None
            if "na" in c:
                return None
        return c

    # na commutes with everything (including another na)
    return c


class SharedJson(SharedOT):
    """Concrete JSON OT channel (the reference's sharejs-json1 wrapper
    class, ot/src/index.ts)."""

    type_name = "sharedjson"

    def __init__(self, channel_id: str):
        super().__init__(channel_id, initial={})

    def apply_core(self, state, op):
        state = copy.deepcopy(state)
        for c in op:
            _apply_component(state, c)
        return state

    def transform(self, input_op, over):
        out = []
        for c in input_op:
            for o in over:
                c = _transform_component(c, o)
                if c is None:
                    break
            if c is not None:
                out.append(c)
        return out

    # convenience API
    def set(self, path: list, value: Any) -> None:
        self.apply([{"p": list(path), "oi": value}])

    def remove(self, path: list) -> None:
        self.apply([{"p": list(path), "od": True}])

    def list_insert(self, path: list, index: int, value: Any) -> None:
        self.apply([{"p": list(path) + [index], "li": value}])

    def list_delete(self, path: list, index: int) -> None:
        self.apply([{"p": list(path) + [index], "ld": True}])

    def add(self, path: list, n: float) -> None:
        self.apply([{"p": list(path), "na": n}])

    def get(self, path: list, default: Any = None) -> Any:
        try:
            return _descend(self.state, path)
        except (KeyError, IndexError, TypeError):
            return default
