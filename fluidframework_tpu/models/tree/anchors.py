"""AnchorSet: stable node references that slide with edits.

Reference: packages/dds/tree/src/core/tree/anchorSet.ts — anchors are
paths into the tree, rebased over every delta the view applies; a
deleted node's anchor becomes unresolvable.

TPU-native re-design: an anchor is a path of (field_key, index) steps.
The EditManager applies to the AnchorSet exactly the deltas the VIEW
experiences: each local change as authored, and on every peer commit
the inverse/trunk/rebased-locals sandwich it already computes — so
anchor updates are incremental even though the forest itself is
recomputed by replay.
"""
from __future__ import annotations

import itertools
from typing import Optional, Sequence


class Anchor:
    __slots__ = ("id", "path", "dead")

    def __init__(self, anchor_id: int, path: tuple):
        self.id = anchor_id
        self.path = path  # ((field, index), ...)
        self.dead = False

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        state = "dead" if self.dead else "at"
        return f"<Anchor {self.id} {state} {self.path}>"


class AnchorSet:
    def __init__(self) -> None:
        self._anchors: dict[int, Anchor] = {}
        self._ids = itertools.count(1)

    def track(self, path: Sequence) -> Anchor:
        """``path`` alternates field keys and indexes and ends on an
        index: ("children", 2) or ("children", 2, "items", 0)."""
        if len(path) % 2 != 0:
            raise ValueError("anchor path must end on a node index")
        steps = tuple(
            (path[i], path[i + 1]) for i in range(0, len(path), 2)
        )
        anchor = Anchor(next(self._ids), steps)
        self._anchors[anchor.id] = anchor
        return anchor

    def forget(self, anchor: Anchor) -> None:
        self._anchors.pop(anchor.id, None)

    def locate(self, anchor: Anchor) -> Optional[tuple]:
        """Current flat path, or None if the node was deleted."""
        if anchor.dead or anchor.id not in self._anchors:
            return None
        out: list = []
        for key, idx in anchor.path:
            out.extend((key, idx))
        return tuple(out)

    # ------------------------------------------------------------------
    # delta application

    def apply(self, changes: dict) -> None:
        """Rebase every live anchor over one field-changes delta."""
        for anchor in self._anchors.values():
            if not anchor.dead:
                self._apply_one(anchor, changes)

    def _apply_one(self, anchor: Anchor, changes: dict) -> None:
        new_path = []
        fields = changes
        for depth, (key, idx) in enumerate(anchor.path):
            marks = (fields or {}).get(key)
            if not marks:
                new_path.append((key, idx))
                new_path.extend(anchor.path[depth + 1:])
                break
            new_idx, node_mark = self._adjust(marks, idx)
            if new_idx is None:
                anchor.dead = True
                return
            new_path.append((key, new_idx))
            fields = (node_mark or {}).get("fields") \
                if node_mark is not None else None
        anchor.path = tuple(new_path)

    @staticmethod
    def _adjust(marks: list, idx: int):
        """New index of input-node ``idx`` after ``marks``, plus the
        mod mark covering it (for descending). Returns (None, None)
        when a delete covers the node — unless a rev in the same list
        revives that very node (a MOVE: the anchor follows it to the
        destination, anchorSet.ts move semantics)."""
        # pre-pass: output position of every revived node identity
        rev_map: dict = {}
        out_scan = 0
        for m in marks:
            t = m["t"]
            if t == "rev":
                for j in range(m["n"]):
                    rev_map[(m["rev"], m["idx"] + j)] = out_scan + j
                out_scan += m["n"]
            elif t == "skip":
                out_scan += m["n"]
            elif t == "ins":
                out_scan += len(m["content"])
            elif t == "mod":
                out_scan += 1
            # del / tomb contribute no output

        in_pos = 0   # input coordinate walker
        out_pos = 0  # output coordinate walker
        for m in marks:
            t = m["t"]
            if t == "skip":
                if in_pos + m["n"] > idx:
                    return out_pos + (idx - in_pos), None
                in_pos += m["n"]
                out_pos += m["n"]
            elif t == "ins":
                out_pos += len(m["content"])
            elif t == "rev":
                out_pos += m["n"]
            elif t == "del":
                if in_pos + m["n"] > idx:
                    did = m.get("did")
                    if did is not None:
                        dest = rev_map.get(
                            (did[0], did[1] + (idx - in_pos))
                        )
                        if dest is not None:
                            return dest, None  # moved, not deleted
                    return None, None
                in_pos += m["n"]
            elif t == "mod":
                if in_pos == idx:
                    return out_pos, m
                in_pos += 1
                out_pos += 1
            elif t == "tomb":
                pass  # 0 input, 0 output
            else:  # pragma: no cover - forward compat
                raise ValueError(f"unknown mark {t!r}")
        return out_pos + (idx - in_pos), None
