"""SharedTree: op-based tree CRDT with rebasing (packages/dds/tree)."""
from . import changeset
from .changeset import compose, invert, rebase
from .editmanager import Commit, EditManager
from .forest import Forest, node
from .sharedtree import SharedTree, wrap_path

__all__ = [
    "changeset", "compose", "invert", "rebase",
    "Commit", "EditManager", "Forest", "node", "SharedTree", "wrap_path",
]
