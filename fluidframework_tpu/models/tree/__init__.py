"""SharedTree: op-based tree CRDT with rebasing (packages/dds/tree)."""
from . import changeset
from .anchors import Anchor, AnchorSet
from .changeset import compose, invert, rebase
from .editable import EditableField, EditableNode, EditableRoot
from .editmanager import Commit, EditManager
from .forest import Forest, node
from .schema import (
    FieldSchema,
    NodeSchema,
    SchemaViolation,
    StoredSchema,
)
from .sharedtree import SharedTree, wrap_path

__all__ = [
    "changeset", "compose", "invert", "rebase",
    "Anchor", "AnchorSet",
    "Commit", "EditManager",
    "EditableField", "EditableNode", "EditableRoot",
    "FieldSchema", "Forest", "NodeSchema", "SchemaViolation",
    "StoredSchema",
    "node", "SharedTree", "wrap_path",
]
