"""Forest: the tree state changesets apply to, plus repair data.

Reference semantics: packages/dds/tree/src/core/forest (IForest, 305 LoC)
with the object-forest implementation
(feature-libraries/object-forest) and the repair-data store
(feature-libraries/forestRepairDataStore.ts) that captures detached
subtrees so inverted deletes (``rev`` marks) can reattach real content.

TPU-native re-design: nodes are plain JSON-safe dicts
``{"type": str, "value": any, "fields": {key: [child nodes]}}`` — the
same shape the wire format and summaries use, and the shape the batched
tree kernel flattens into (parent, field, position, type, value) columns.
A forest is a root field map. Applying a changeset walks marks in list
order with nested fields sorted by key; every ``del`` stores its
detached subtrees in ``repair[(revision, running_index)]``, the exact
order :func:`changeset.invert` assigns detach indexes, so a later
``rev`` mark can fetch them by ``(rev, idx)``.
"""
from __future__ import annotations

import copy
import json
from typing import Any, Optional

from .changeset import (
    FieldChanges,
    Mark,
    MarkList,
    _reg_apply as reg_apply,
    is_reg,
    walk_apply,
)


def node(type_: str, value: Any = None,
         fields: Optional[dict] = None) -> dict:
    n: dict = {"type": type_}
    if value is not None:
        n["value"] = value
    if fields:
        n["fields"] = fields
    return n


class Forest:
    """Mutable tree state for one SharedTree."""

    def __init__(self, fields: Optional[dict] = None):
        self.fields: dict[str, list] = fields or {}
        # (revision, detach_index) -> detached subtree, one per node
        self.repair: dict[tuple, dict] = {}

    # ------------------------------------------------------------------

    def clone(self) -> "Forest":
        f = Forest(copy.deepcopy(self.fields))
        f.repair = dict(self.repair)
        return f

    def content(self) -> dict:
        """Canonical user-visible state (no repair data)."""
        return copy.deepcopy(self.fields)

    def signature(self) -> str:
        return json.dumps(self.fields, sort_keys=True, default=str)

    # ------------------------------------------------------------------

    def apply(self, changes: FieldChanges, revision: Any) -> None:
        """Apply a changeset, capturing repair data under
        ``revision``. Capture runs as a PRE-PASS over the whole
        changeset so a rev may reference a del of the SAME changeset
        regardless of mark order — that is exactly a move
        (changeset.move: detach+revive pair)."""
        counter = [0]
        self._capture_fields(self.fields, changes, revision, counter)
        counter[0] = 0
        self._apply_fields(self.fields, changes, revision, counter)

    def _capture_fields(self, fields: dict, changes: FieldChanges,
                        revision: Any, counter: list) -> None:
        for key in sorted(changes):
            ch = changes[key]
            if is_reg(ch):
                # register fields: only the nested mods touch existing
                # content (the set's old rides inline; post applies to
                # fresh content and captures late, during apply)
                if ch.get("mods"):
                    self._capture_marks(
                        fields.get(key, []), ch["mods"], revision,
                        counter,
                    )
                continue
            self._capture_marks(
                fields.get(key, []), ch, revision, counter
            )

    def _capture_marks(self, seq: list, marks: MarkList,
                       revision: Any, counter: list) -> None:
        pos = 0
        for m in marks:
            t = m["t"]
            if t == "del":
                u, base = m["did"] if "did" in m \
                    else (revision, counter[0])
                for i, nd in enumerate(seq[pos:pos + m["n"]]):
                    self.repair[(u, base + i)] = copy.deepcopy(nd)
                counter[0] += m["n"]
                pos += m["n"]
            elif t == "skip":
                pos += m["n"]
            elif t == "mod":
                if m.get("fields"):
                    # recurse even when pos is past the end of the
                    # field (the apply walk mods a dummy node there):
                    # nested dels must still consume counter slots or
                    # the pre-pass keys desynchronize from the walk's
                    # — and from changeset.invert's — del numbering
                    sub = seq[pos].get("fields", {}) \
                        if pos < len(seq) else {}
                    self._capture_fields(
                        sub, m["fields"], revision, counter,
                    )
                pos += 1
            # ins / rev / tomb consume no input

    def _apply_fields(self, fields: dict, changes: FieldChanges,
                      revision: Any, counter: list) -> None:
        for key in sorted(changes):
            ch = changes[key]
            if is_reg(ch):
                fields[key] = reg_apply(
                    fields.get(key, []), ch,
                    lambda seq, marks: self._apply_marks(
                        seq, marks, revision, counter),
                )
                continue
            fields[key] = self._apply_marks(
                fields.get(key, []), ch, revision, counter)

    def _apply_marks(self, seq: list, marks: MarkList,
                     revision: Any, counter: list) -> list:
        """One shared walker (``changeset.walk_apply``) with repair
        hooks attached."""

        def on_del(m, nodes):
            # capture already ran in the pre-pass (Forest.apply); the
            # hook only keeps the unstamped-del counter in step with
            # the canonical walk order
            counter[0] += m["n"]

        def on_rev(m):
            out = []
            for i in range(m["n"]):
                sub = self.repair.get((m["rev"], m["idx"] + i))
                out.append(copy.deepcopy(sub) if sub is not None
                           else node("repair-missing"))
            return out

        def mod_node(nd, m):
            if "value" in m:
                nd["value"] = m["value"]["new"]
            if m.get("fields"):
                nd.setdefault("fields", {})
                self._apply_fields(nd["fields"], m["fields"],
                                   revision, counter)
            return nd

        return walk_apply(seq, marks, on_del=on_del, on_rev=on_rev,
                          mod_node=mod_node)
