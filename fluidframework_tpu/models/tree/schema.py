"""Stored schema for SharedTree: field kinds + node type validation.

Reference: packages/dds/tree/src/feature-libraries/modular-schema/
(FieldKind-indexed composition), core/schema-stored (the document's
persisted schema) and schema-view. The reference registers field kinds
(value / optional / sequence / forbidden) and per-node-type allowed
child types; the stored schema is itself replicated document state.

TPU-native re-design: TWO concrete field-kind families — sequence
(the mark algebra) and REGISTER (value/optional fields: LWW
single-node writes, changeset.reg_set — the modular-schema second
kind). JSON-safe schema documents ride ops and summaries unchanged,
and validation happens at the editing surface so a schema violation
fails BEFORE an op is authored.

Cardinality under concurrency: value/optional fields edited through
the register kind (SharedTree.set_register / EditableField.set)
converge LWW — two clients concurrently filling an empty optional
field merge to ONE winner. Sequence-kind editing of a value/optional
field (insert/delete) remains subject to the optimistic-cardinality
caveat (author-local validation), same as any optimistic schema
system; readers can detect drift via ``validate_tree``.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

# field multiplicity (modular-schema FieldKinds)
VALUE = "value"        # exactly one node
OPTIONAL = "optional"  # zero or one node
SEQUENCE = "sequence"  # any number of nodes
FORBIDDEN = "forbidden"

_KINDS = (VALUE, OPTIONAL, SEQUENCE, FORBIDDEN)

# node value constraints
VALUE_KINDS = ("none", "number", "string", "boolean", "any")


class SchemaViolation(ValueError):
    """An edit or tree does not conform to the stored schema."""


@dataclass
class FieldSchema:
    kind: str = SEQUENCE
    # None = any node type allowed
    allowed_types: Optional[tuple] = None

    def to_json(self) -> dict:
        out: dict = {"kind": self.kind}
        if self.allowed_types is not None:
            out["types"] = sorted(self.allowed_types)
        return out

    @classmethod
    def from_json(cls, data: dict) -> "FieldSchema":
        if data.get("kind", SEQUENCE) not in _KINDS:
            raise SchemaViolation(f"unknown field kind {data!r}")
        return cls(
            kind=data.get("kind", SEQUENCE),
            allowed_types=tuple(data["types"])
            if "types" in data else None,
        )


@dataclass
class NodeSchema:
    name: str
    value: str = "none"  # VALUE_KINDS
    fields: dict = field(default_factory=dict)  # key -> FieldSchema
    # open node: fields not listed are allowed as free sequences
    extra_fields: bool = False

    def to_json(self) -> dict:
        return {
            "value": self.value,
            "fields": {k: f.to_json() for k, f in self.fields.items()},
            "extraFields": self.extra_fields,
        }

    @classmethod
    def from_json(cls, name: str, data: dict) -> "NodeSchema":
        if data.get("value", "none") not in VALUE_KINDS:
            raise SchemaViolation(f"unknown value kind {data!r}")
        return cls(
            name=name,
            value=data.get("value", "none"),
            fields={
                k: FieldSchema.from_json(f)
                for k, f in data.get("fields", {}).items()
            },
            extra_fields=data.get("extraFields", False),
        )


_VALUE_CHECK = {
    "none": lambda v: v is None,
    "number": lambda v: isinstance(v, (int, float))
    and not isinstance(v, bool),
    "string": lambda v: isinstance(v, str),
    "boolean": lambda v: isinstance(v, bool),
    "any": lambda v: True,
}


class StoredSchema:
    """The document schema: node types + root field constraints.
    ``None`` anywhere means unconstrained (schema-off documents behave
    exactly as before)."""

    def __init__(self, nodes: Optional[dict] = None,
                 root_fields: Optional[dict] = None):
        self.nodes: dict[str, NodeSchema] = nodes or {}
        # root field key -> FieldSchema; None = open roots
        self.root_fields: Optional[dict] = root_fields

    # -- wire/summary form ---------------------------------------------

    def to_json(self) -> dict:
        out: dict = {
            "nodes": {n: s.to_json() for n, s in self.nodes.items()},
        }
        if self.root_fields is not None:
            out["root"] = {
                k: f.to_json() for k, f in self.root_fields.items()
            }
        return out

    @classmethod
    def from_json(cls, data: dict) -> "StoredSchema":
        return cls(
            nodes={
                n: NodeSchema.from_json(n, s)
                for n, s in data.get("nodes", {}).items()
            },
            root_fields={
                k: FieldSchema.from_json(f)
                for k, f in data["root"].items()
            } if "root" in data else None,
        )

    # -- validation ----------------------------------------------------

    def field_schema(self, node_type: Optional[str],
                     key: str) -> Optional[FieldSchema]:
        """Schema of field ``key`` under a node of ``node_type``
        (``None`` node_type = root)."""
        if node_type is None:
            if self.root_fields is None:
                return None  # open roots
            # a present-but-empty dict is a CLOSED root: every key
            # not listed is forbidden
            return self.root_fields.get(key, FieldSchema(FORBIDDEN))
        ns = self.nodes.get(node_type)
        if ns is None:
            return None  # untyped node: unconstrained
        fs = ns.fields.get(key)
        if fs is None:
            return None if ns.extra_fields else FieldSchema(FORBIDDEN)
        return fs

    def validate_node(self, node: dict) -> None:
        ntype = node.get("type")
        ns = self.nodes.get(ntype)
        if ns is None:
            if self.nodes:
                raise SchemaViolation(
                    f"node type {ntype!r} not in stored schema"
                )
            return
        if not _VALUE_CHECK[ns.value](node.get("value")):
            raise SchemaViolation(
                f"{ntype}: value {node.get('value')!r} violates "
                f"value kind {ns.value!r}"
            )
        for key, children in (node.get("fields") or {}).items():
            fs = self.field_schema(ntype, key)
            self._validate_field(fs, ntype, key, children)
            for child in children:
                self.validate_node(child)

    def _validate_field(self, fs: Optional[FieldSchema],
                        owner: Any, key: str, children: list) -> None:
        if fs is None:
            return
        if fs.kind == FORBIDDEN and children:
            raise SchemaViolation(
                f"{owner}: field {key!r} is forbidden"
            )
        if fs.kind == VALUE and len(children) != 1:
            raise SchemaViolation(
                f"{owner}.{key}: value field needs exactly one node, "
                f"got {len(children)}"
            )
        if fs.kind == OPTIONAL and len(children) > 1:
            raise SchemaViolation(
                f"{owner}.{key}: optional field holds at most one "
                f"node, got {len(children)}"
            )
        if fs.allowed_types is not None:
            for child in children:
                if child.get("type") not in fs.allowed_types:
                    raise SchemaViolation(
                        f"{owner}.{key}: type {child.get('type')!r} "
                        f"not in {sorted(fs.allowed_types)}"
                    )

    def validate_tree(self, fields: dict) -> None:
        """Validate a whole forest (used when adopting a schema over
        existing content and when loading summaries)."""
        for key, children in fields.items():
            fs = self.field_schema(None, key)
            self._validate_field(fs, "<root>", key, children)
            for child in children:
                self.validate_node(child)

    def validate_value(self, node_type: Optional[str],
                       value: Any) -> None:
        """Value-kind check alone (set_value path: children were
        validated at insert and cannot change here)."""
        ns = self.nodes.get(node_type)
        if ns is None:
            if self.nodes:
                raise SchemaViolation(
                    f"node type {node_type!r} not in stored schema"
                )
            return
        if not _VALUE_CHECK[ns.value](value):
            raise SchemaViolation(
                f"{node_type}: value {value!r} violates value kind "
                f"{ns.value!r}"
            )

    def validate_insert(self, parent_type: Optional[str], key: str,
                        content: list, resulting_len: int) -> None:
        """Validate inserting ``content`` into field ``key`` of a
        ``parent_type`` node (cardinality checked on the resulting
        length)."""
        fs = self.field_schema(parent_type, key)
        if fs is None:
            for n in content:
                self.validate_node(n)
            return
        if fs.kind == FORBIDDEN:
            raise SchemaViolation(f"field {key!r} is forbidden")
        if fs.kind == VALUE and resulting_len != 1:
            raise SchemaViolation(
                f"value field {key!r} must hold exactly one node"
            )
        if fs.kind == OPTIONAL and resulting_len > 1:
            raise SchemaViolation(
                f"optional field {key!r} overfilled"
            )
        if fs.allowed_types is not None:
            for n in content:
                if n.get("type") not in fs.allowed_types:
                    raise SchemaViolation(
                        f"field {key!r}: {n.get('type')!r} not allowed"
                    )
        for n in content:
            self.validate_node(n)
