"""Editable tree: the typed, path-free reading/editing surface.

Reference: packages/dds/tree/src/feature-libraries/editable-tree/
(proxy-based typed reading/editing, 1,964 LoC). The TPU build keeps
the same shape — fields index like sequences, nodes expose value and
child fields, every mutation routes through the SharedTree editor (so
schema validation, transactions and anchors all apply) — with explicit
wrapper classes instead of JS proxies.

    root = tree.editable()
    items = root.field("items")
    items.insert(0, [node("item", value=1)])
    items[0].value = 2
    items[0].field("tags").append([node("tag", value="x")])
    del items[0:1]
"""
from __future__ import annotations

from typing import Any, Iterator, Sequence


class EditableField:
    """One sequence field, live against the tree (reads always reflect
    the current view)."""

    def __init__(self, tree, path: Sequence):
        self._tree = tree
        self._path = tuple(path)

    # -- reads -----------------------------------------------------------

    def _nodes(self) -> list:
        return self._tree.get_field(self._path)

    def __len__(self) -> int:
        return len(self._nodes())

    def __iter__(self) -> Iterator["EditableNode"]:
        for i in range(len(self)):
            yield EditableNode(self._tree, self._path, i)

    def __getitem__(self, i):
        n = len(self._nodes())
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(n))]
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        return EditableNode(self._tree, self._path, i)

    @property
    def key(self) -> str:
        return self._path[-1]

    # -- edits -----------------------------------------------------------

    def insert(self, index: int, content: list) -> None:
        self._tree.insert_nodes(self._path, index, content)

    def append(self, content: list) -> None:
        self.insert(len(self), content)

    def delete(self, index: int, count: int = 1) -> None:
        self._tree.delete_nodes(self._path, index, count)

    def move(self, src: int, dst: int, *, count: int = 1) -> None:
        """count is keyword-only: SharedTree.move_nodes orders
        (src, count, dst) and a positionally transposed call would be
        valid-but-wrong."""
        self._tree.move_nodes(self._path, src, count, dst)

    def set(self, content) -> None:
        """Register-field write (value/optional kinds): replace the
        field's single node; concurrent sets converge LWW."""
        self._tree.set_register(self._path, content)

    def clear(self) -> None:
        """Clear an optional register field."""
        self._tree.set_register(self._path, None)

    def __delitem__(self, i) -> None:
        if isinstance(i, slice):
            start, stop, step = i.indices(len(self))
            if step != 1:
                raise ValueError("only contiguous deletion")
            if stop > start:
                self.delete(start, stop - start)
            return
        self.delete(i if i >= 0 else i + len(self))


class EditableNode:
    """One node; ``value`` writes route through the tree editor."""

    def __init__(self, tree, field_path: Sequence, index: int):
        self._tree = tree
        self._field_path = tuple(field_path)
        self._index = index

    def _node(self) -> dict:
        return self._tree.get_field(self._field_path)[self._index]

    @property
    def type(self) -> str:
        return self._node().get("type")

    @property
    def value(self) -> Any:
        return self._node().get("value")

    @value.setter
    def value(self, v: Any) -> None:
        self._tree.set_value(self._field_path, self._index, v)

    def field(self, key: str) -> EditableField:
        return EditableField(
            self._tree, self._field_path + (self._index, key)
        )

    def field_keys(self) -> list:
        return sorted((self._node().get("fields") or {}).keys())

    def anchor(self):
        """Stable reference to this node (survives sibling edits)."""
        return self._tree.track_anchor(self._field_path, self._index)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"<EditableNode {self.type!r} value={self.value!r} "
                f"at {self._field_path}[{self._index}]>")


class EditableRoot:
    """The document root: a map of named root fields."""

    def __init__(self, tree):
        self._tree = tree

    def field(self, key: str) -> EditableField:
        return EditableField(self._tree, (key,))

    def field_keys(self) -> list:
        return sorted(self._tree.root().keys())
