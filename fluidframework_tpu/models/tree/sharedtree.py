"""SharedTree: the op-based tree DDS with rebasing merge semantics.

Reference: packages/dds/tree/src/shared-tree-core/sharedTreeCore.ts:73
(SharedObject glue: ``processCore`` -> ``editManager.addSequencedChange``
:209,:234; summaries from pluggable indexes — here a forest index and an
edit-manager index, mirroring feature-libraries/editManagerIndex.ts) and
shared-tree/ (the public editing facade).

TPU-native re-design: edits are path-addressed mark-list changesets
(``changeset.py``); the per-client path runs the EditManager replay;
the service-side batched path (totally ordered, no sandwich needed)
runs in ``fluidframework_tpu.ops.tree_kernel``.

Paths: a field is addressed by alternating (field_key, node_index)
pairs ending in a field key, e.g. ``("children",)`` is the root field
"children" and ``("children", 2, "items")`` is field "items" of the
third root child.
"""
from __future__ import annotations

import copy
from typing import Any, Optional, Sequence

from ...protocol.messages import SequencedMessage
from ...protocol.tree_payload import (
    tree_change_from_json,
    tree_change_to_json,
)
from ...runtime.shared_object import SharedObject
from ...utils.events import EventEmitter
from . import changeset as cs
from .changeset import FieldChanges
from .editmanager import Commit, EditManager
from .forest import Forest, node
from .schema import SchemaViolation, StoredSchema


def wrap_path(path: Sequence, leaf_marks: list) -> FieldChanges:
    """Nest a mark list under a (field, index, field, index, ...) path
    by wrapping it in ``mod`` marks."""
    if len(path) % 2 != 1:
        raise ValueError("path must end on a field key")
    changes: FieldChanges = {path[-1]: leaf_marks}
    for i in range(len(path) - 3, -1, -2):
        key, idx = path[i], path[i + 1]
        changes = {key: [cs.skip(idx), cs.mod(fields=changes)]
                   if idx else [cs.mod(fields=changes)]}
    return changes


class SharedTree(SharedObject, EventEmitter):
    type_name = "sharedtree"

    def __init__(self, channel_id: str):
        SharedObject.__init__(self, channel_id)
        EventEmitter.__init__(self)
        self._em = EditManager(session_id="detached")
        # stored schema (core/schema-stored): None = unconstrained
        self._schema: Optional[StoredSchema] = None
        # open transaction: list of local revision tags (core/
        # transaction; edits buffer locally, commit squashes + submits)
        self._txn: Optional[list] = None

    # ------------------------------------------------------------------

    def _on_connect(self) -> None:
        if self.client_id:
            self._em.session_id = self.client_id

    # ------------------------------------------------------------------
    # reading

    @property
    def forest(self) -> Forest:
        return self._em.forest()

    def root(self) -> dict:
        """Canonical content: {field: [nodes]}."""
        return self._em.forest().content()

    def get_field(self, path: Sequence) -> list:
        fields = self._em.forest().fields
        i = 0
        while i < len(path) - 1:
            fields = fields[path[i]][path[i + 1]].get("fields", {})
            i += 2
        return fields.get(path[-1], [])

    def _parent_type(self, path: Sequence) -> Optional[str]:
        """Node type owning field ``path[-1]`` (None at the root)."""
        if len(path) == 1:
            return None
        from .schema import SchemaViolation

        fields = self._em.forest().fields
        i = 0
        try:
            while i < len(path) - 3:
                fields = fields[path[i]][path[i + 1]].get("fields", {})
                i += 2
            return fields[path[i]][path[i + 1]].get("type")
        except (KeyError, IndexError):
            raise SchemaViolation(
                f"edit path {tuple(path)!r} does not resolve to an "
                "existing node under the stored schema"
            ) from None

    def editable(self):
        """Typed editing surface (feature-libraries/editable-tree)."""
        from .editable import EditableRoot

        return EditableRoot(self)

    # ------------------------------------------------------------------
    # stored schema (modular-schema / schema-stored)

    @property
    def stored_schema(self) -> Optional[StoredSchema]:
        return self._schema

    def set_stored_schema(self, schema: StoredSchema) -> None:
        """Propose a stored schema: current content must conform; the
        schema activates when its op SEQUENCES (on every client,
        deterministically) — adopting it optimistically would let a
        concurrent edit that sequences first leave replicas holding a
        schema the document violates. If the tree no longer conforms
        at sequencing time the op is dropped everywhere
        (schemaRejected event) — the same deterministic-outcome rule
        consensus DDSes use."""
        schema.validate_tree(self._em.forest().fields)
        self.submit_local_message({
            "type": "tree-schema", "schema": schema.to_json(),
        })

    # ------------------------------------------------------------------
    # transactions (core/transaction + core/checkout)

    def begin_transaction(self) -> None:
        assert self._txn is None, "transactions do not nest"
        self._txn = []

    def commit_transaction(self) -> None:
        assert self._txn is not None, "no open transaction"
        tags, self._txn = self._txn, None
        if not tags:
            return
        composed, tag = self._em.squash_local(tags)
        self.submit_local_message(
            tree_change_to_json(composed), metadata={"tag": tag},
        )
        self.emit("changed", local=True)

    def abort_transaction(self) -> None:
        """Roll every edit of the transaction back (repair data makes
        deleted subtrees reattachable — forestRepairDataStore)."""
        assert self._txn is not None, "no open transaction"
        tags, self._txn = self._txn, None
        if tags:
            self._em.drop_local(tags)
        self.emit("changed", local=True)

    class _Transaction:
        def __init__(self, tree: "SharedTree"):
            self._tree = tree

        def __enter__(self):
            self._tree.begin_transaction()
            return self._tree

        def __exit__(self, exc_type, exc, tb):
            if exc_type is None:
                self._tree.commit_transaction()
            else:
                self._tree.abort_transaction()
            return False

    def transaction(self) -> "SharedTree._Transaction":
        """``with tree.transaction(): ...`` — commits on success,
        aborts (exact rollback) on exception."""
        return SharedTree._Transaction(self)

    # ------------------------------------------------------------------
    # anchors (core/tree/anchorSet.ts)

    def track_anchor(self, path: Sequence, index: int):
        """Stable reference to the node at ``path``[``index``]; use
        ``locate_anchor`` to read its current position (None once the
        node is deleted)."""
        return self._em.anchors.track(tuple(path) + (index,))

    def locate_anchor(self, anchor):
        return self._em.anchors.locate(anchor)

    def forget_anchor(self, anchor) -> None:
        self._em.anchors.forget(anchor)

    # ------------------------------------------------------------------
    # editing (the sequence-field editor surface)

    def insert_nodes(self, path: Sequence, index: int,
                     content: list) -> None:
        if self._schema is not None:
            self._schema.validate_insert(
                self._parent_type(path), path[-1], content,
                len(self.get_field(path)) + len(content),
            )
        marks = ([cs.skip(index)] if index else []) + [cs.ins(content)]
        self._apply_local(wrap_path(path, marks))

    def delete_nodes(self, path: Sequence, index: int, count: int) -> None:
        if self._schema is not None:
            self._schema.validate_insert(
                self._parent_type(path), path[-1], [],
                len(self.get_field(path)) - count,
            )
        marks = ([cs.skip(index)] if index else []) + [cs.dele(count)]
        self._apply_local(wrap_path(path, marks))

    def move_nodes(self, path: Sequence, src: int, count: int,
                   dst: int) -> None:
        """Move ``count`` nodes within the field at ``path`` from
        input position ``src`` to input position ``dst`` (expressed
        against the CURRENT view; dst outside the moved range).
        Same-field, so the stored schema's type/cardinality
        constraints are unaffected. Concurrency: delete wins — see
        changeset.move."""
        self._apply_local(wrap_path(path, cs.move(src, count, dst)))

    def set_register(self, path: Sequence, content: Optional[dict]
                     ) -> None:
        """Write a value/optional REGISTER field (modular-schema's
        second field kind): replace the field's single node with
        ``content`` (None clears an optional field). Concurrent
        writes are LWW by sequencing — two clients filling the same
        optional field converge to ONE winner, closing the
        concurrent-fill drift the sequence-kind collapse had
        (schema.py's old known-limitation note)."""
        kind = None
        if self._schema is not None:
            fs = self._schema.field_schema(
                self._parent_type(path), path[-1])
            kind = fs.kind if fs is not None else None
            if kind not in (None, "value", "optional"):
                raise SchemaViolation(
                    f"set_register on a {kind!r} field")
            if content is None and kind == "value":
                raise SchemaViolation("value field cannot be cleared")
            if content is not None:
                self._schema.validate_insert(
                    self._parent_type(path), path[-1], [content], 1,
                )
        current = self.get_field(path)
        old = current[0] if current else None
        change = cs.reg_set(content, old,
                            optional=(kind != "value"))
        self._apply_local(wrap_path(path, change))

    def set_value(self, path: Sequence, index: int, value: Any) -> None:
        seq = self.get_field(path)
        old = seq[index].get("value") if index < len(seq) else None
        if self._schema is not None and index < len(seq):
            self._schema.validate_value(seq[index].get("type"), value)
        m = cs.mod(value={"new": value, "old": old})
        marks = ([cs.skip(index)] if index else []) + [m]
        self._apply_local(wrap_path(path, marks))

    def apply_changeset(self, changes: FieldChanges) -> None:
        """Escape hatch: submit a raw changeset."""
        self._apply_local(copy.deepcopy(changes))

    def _apply_local(self, changes: FieldChanges) -> None:
        tag = self._em.add_local_change(changes)
        if self._txn is not None:
            # buffered: commit_transaction squashes + submits once
            self._txn.append(tag)
        else:
            self.submit_local_message(
                tree_change_to_json(changes), metadata={"tag": tag},
            )
        self.emit("changed", local=True)

    # ------------------------------------------------------------------
    # SharedObject contract

    def process_core(self, msg: SequencedMessage, local: bool,
                     local_op_metadata: Any = None) -> None:
        op = msg.contents
        if isinstance(op, dict) and op.get("type") == "tree-schema":
            # stored-schema evolution: sequenced-order LWW, applied
            # only if the tree conforms AT SEQUENCING TIME (every
            # replica evaluates the same state -> same outcome)
            from .schema import SchemaViolation

            schema = StoredSchema.from_json(op["schema"])
            try:
                schema.validate_tree(self._em.forest().fields)
            except SchemaViolation:
                self.emit("schemaRejected", local=local)
                return
            self._schema = schema
            self.emit("schemaChanged", local=local)
            return
        changes = tree_change_from_json(op)
        if changes is None:
            raise ValueError(f"unexpected tree op: {op!r}")
        commit = Commit(session_id=msg.client_id or "",
                        seq=msg.sequence_number,
                        ref_seq=msg.reference_sequence_number,
                        changes=changes)
        self._em.add_sequenced_change(commit, is_local=local)
        if msg.minimum_sequence_number > self._em.min_seq:
            self._em.advance_minimum_sequence_number(
                msg.minimum_sequence_number)
        self.emit("changed", local=local)

    def resubmit_core(self, contents: Any, metadata: Any = None) -> None:
        """Reconnect rebase (sharedObject.ts:378): the EditManager keeps
        local changes rebased against the trunk tip, so resubmit sends
        the *current* form, found by its local revision tag."""
        if isinstance(contents, dict) and \
                contents.get("type") == "tree-schema":
            self.submit_local_message(contents, metadata)
            return
        tag = (metadata or {}).get("tag")
        for change, t in self._em.local_changes:
            if t == tag:
                self.submit_local_message(tree_change_to_json(change),
                                          metadata={"tag": tag})
                return
        # Unknown tag: the op was already sequenced; nothing to resend.

    def apply_stashed_op(self, contents: Any) -> Any:
        if contents.get("type") == "tree-schema":
            # a stashed schema proposal re-validates and resubmits;
            # activation still happens only at sequencing
            return None
        changes = contents["changes"]
        tag = self._em.add_local_change(changes)
        return {"tag": tag}

    def summarize_core(self) -> dict:
        """Forest index + edit-manager index
        (sharedTreeCore.ts:73 summary composed of indexes)."""
        em = self._em
        return {
            "forest": em.base_forest.content(),
            # repair data for deletes already evicted into the base
            # forest — without it a summary-loaded replica cannot honor
            # rev marks older than min_seq and diverges from live ones
            "repair": [[u, i, copy.deepcopy(n)]
                       for (u, i), n in sorted(
                           em.base_forest.repair.items(),
                           key=lambda kv: (str(kv[0][0]), kv[0][1]))],
            "trunk": [{"session": c.session_id, "seq": c.seq,
                       "ref": c.ref_seq, "changes": c.changes}
                      for c in em.trunk],
            "min_seq": em.min_seq,
            "schema": self._schema.to_json()
            if self._schema is not None else None,
        }

    def load_core(self, summary: dict) -> None:
        em = EditManager(session_id=self._em.session_id,
                         base=Forest(copy.deepcopy(summary["forest"])))
        for u, i, n in summary.get("repair", []):
            em.base_forest.repair[(u, i)] = copy.deepcopy(n)
        for c in summary["trunk"]:
            em.trunk.append(Commit(c["session"], c["seq"], c["ref"],
                                   c["changes"]))
        em.min_seq = summary["min_seq"]
        self._em = em
        schema = summary.get("schema")
        self._schema = (
            StoredSchema.from_json(schema) if schema else None
        )

    def signature(self) -> Any:
        return self._em.forest().signature()
