"""EditManager: trunk + per-session branch bookkeeping for SharedTree.

Reference semantics: packages/dds/tree/src/core/edit-manager/
editManager.ts:30 — a trunk of sequenced commits (each rebased onto its
predecessor), a branch per peer session holding that peer's in-flight
changes in original form, and the local session's unsequenced changes
kept rebased against the trunk tip:

- ``addSequencedChange`` (:142): own commits shift from localChanges to
  the trunk verbatim (:155-176); peer commits are rebased from their
  branch to the trunk (``rebaseChangeFromBranchToTrunk`` :223) and the
  local branch is rebased over the result (``rebaseLocalBranch`` :241,
  the inverse/trunk/rebased sandwich).
- ``addLocalChange`` (:208), ``advanceMinimumSequenceNumber`` (:71)
  evicting trunk commits below the collab window.

TPU-native re-design: instead of threading incremental deltas into a
mutable forest (which forces repair-data plumbing through composed
changesets), the manager keeps a *base forest* at the trunk eviction
point and recomputes the current forest by replaying trunk + local
changes. The collab window bounds the replay; the hot batched path
(thousands of docs, totally ordered) runs in the tree kernel instead,
where no sandwich rebasing is needed at all.
"""
from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any, Optional

from . import changeset as cs
from .anchors import AnchorSet
from .changeset import FieldChanges
from .forest import Forest


@dataclass
class Commit:
    """editManager.ts Commit<TChangeset>."""

    session_id: str
    seq: int
    ref_seq: int
    changes: FieldChanges


@dataclass
class _Branch:
    """A peer session's in-flight commits, in original (unrebased) form,
    based on trunk state at ``ref_seq``."""

    local_changes: list[Commit] = dc_field(default_factory=list)
    ref_seq: int = 0
    is_divergent: bool = False


class EditManager:
    """Rebases every arriving commit into a convergent trunk."""

    def __init__(self, session_id: str, base: Optional[Forest] = None):
        self.session_id = session_id
        self.trunk: list[Commit] = []
        self.branches: dict[str, _Branch] = {}
        # (change, local_revision_tag) pairs, rebased to the trunk tip
        self.local_changes: list[tuple[FieldChanges, Any]] = []
        self._next_local_rev = -1
        self.min_seq = 0
        # forest state at the trunk eviction point (all evicted commits
        # applied); current state = base + trunk + local_changes replay
        self.base_forest = base.clone() if base else Forest()
        self._current: Optional[Forest] = None
        # anchors rebase over exactly the deltas the VIEW experiences
        # (core/tree/anchorSet.ts)
        self.anchors = AnchorSet()

    # ------------------------------------------------------------------
    # state

    def forest(self) -> Forest:
        """Current state: base + trunk + local changes."""
        if self._current is None:
            f = self.base_forest.clone()
            for c in self.trunk:
                f.apply(c.changes, c.seq)
            for change, tag in self.local_changes:
                f.apply(change, tag)
            self._current = f
        return self._current

    # ------------------------------------------------------------------
    # edits

    def add_local_change(self, change: FieldChanges) -> Any:
        """editManager.ts:208 — record an unsequenced local change;
        returns its temporary (negative) revision tag. Freshly authored
        marks get birth identities here (``changeset.stamp``) so their
        dels/inserts stay identifiable across rebasing and the wire."""
        tag = self._next_local_rev
        self._next_local_rev -= 1
        cs.stamp(change, f"{self.session_id}:{-tag}")
        self.local_changes.append((change, tag))
        if self._current is not None:
            self._current.apply(change, tag)
        self.anchors.apply(change)
        return tag

    def add_sequenced_change(self, commit: Commit,
                             is_local: Optional[bool] = None) -> None:
        """editManager.ts:142. ``is_local`` overrides the session-id
        comparison (the runtime knows; client ids change on reconnect)."""
        if self.trunk and commit.seq <= self.trunk[-1].seq:
            raise ValueError(
                f"out-of-order sequenced change {commit.seq} after "
                f"{self.trunk[-1].seq}")
        if is_local is None:
            is_local = commit.session_id == self.session_id
        if is_local:
            # Our own op round-tripped: its rebased form is the head of
            # local_changes; move it to the trunk (editManager.ts:155).
            if not self.local_changes:
                raise ValueError("sequenced local edit with no local change")
            change, _tag = self.local_changes.pop(0)
            self.trunk.append(Commit(commit.session_id, commit.seq,
                                     commit.ref_seq, change))
            # state unchanged, but re-tag the replay so repair data is
            # captured under the final revision next time
            self._current = None
            return

        branch = self._get_or_create_branch(commit.session_id,
                                            commit.ref_seq)
        self._update_branch(branch, commit.ref_seq)
        rebased = self._rebase_branch_commit_to_trunk(commit, branch)
        self._add_commit_to_branch(branch, commit)
        self.trunk.append(Commit(commit.session_id, commit.seq,
                                 commit.ref_seq, rebased))
        old_locals = list(self.local_changes)
        self._rebase_local_branch(rebased, commit.seq)
        # anchor delta = the view's sandwich: retract old locals,
        # apply the rebased peer commit, replay the new locals
        for change, tag in reversed(old_locals):
            self.anchors.apply(cs.invert(change, tag))
        self.anchors.apply(rebased)
        for change, _tag in self.local_changes:
            self.anchors.apply(change)
        self._current = None

    def squash_local(self, tags: list) -> tuple[FieldChanges, Any]:
        """Replace the (contiguous, trailing) local changes with the
        given tags by ONE composed change — transaction commit
        (core/transaction: a transaction's edits squash to a single
        commit). Returns (composed_change, new_tag). The composed form
        uses the CURRENT (rebased) shapes, so peer commits landing
        mid-transaction are already accounted for."""
        tagset = set(tags)
        items = [(c, t) for c, t in self.local_changes if t in tagset]
        keep = [(c, t) for c, t in self.local_changes
                if t not in tagset]
        assert keep + items == self.local_changes, (
            "transaction changes must be the trailing local changes"
        )
        composed = cs.compose([c for c, _ in items])
        tag = self._next_local_rev
        self._next_local_rev -= 1
        self.local_changes = keep + [(composed, tag)]
        # state is unchanged (compose law) but replay tags differ
        self._current = None
        return composed, tag

    def drop_local(self, tags: list) -> None:
        """Remove local changes by tag — transaction abort. Repair
        data makes the rollback exact: the view is recomputed without
        the dropped changes (transaction + forestRepairDataStore)."""
        tagset = set(tags)
        dropped = [(c, t) for c, t in self.local_changes
                   if t in tagset]
        self.local_changes = [
            (c, t) for c, t in self.local_changes if t not in tagset
        ]
        for change, tag in reversed(dropped):
            self.anchors.apply(cs.invert(change, tag))
        self._current = None

    def advance_minimum_sequence_number(self, min_seq: int) -> None:
        """editManager.ts:71 — evict trunk commits below the collab
        window into the base forest. Every lazily-rebased peer branch is
        fast-forwarded to the eviction point first, because
        ``_update_branch`` can only rebase over trunk commits that still
        exist."""
        if min_seq < self.min_seq:
            raise ValueError("minimum sequence number moved backwards")
        self.min_seq = min_seq
        evict_to = None
        for c in self.trunk:
            if c.seq >= min_seq:
                break
            evict_to = c.seq
        if evict_to is None:
            return
        for branch in self.branches.values():
            if branch.ref_seq < evict_to:
                self._update_branch(branch, evict_to)
        evicted = 0
        while evicted < len(self.trunk) and self.trunk[evicted].seq < min_seq:
            c = self.trunk[evicted]
            self.base_forest.apply(c.changes, c.seq)
            evicted += 1
        if evicted:
            del self.trunk[:evicted]

    # ------------------------------------------------------------------
    # rebasing machinery

    def _get_or_create_branch(self, session: str, ref_seq: int) -> _Branch:
        if session not in self.branches:
            self.branches[session] = _Branch(ref_seq=ref_seq)
        return self.branches[session]

    def _trunk_after(self, pred: int, last: Optional[int] = None
                     ) -> list[Commit]:
        out = [c for c in self.trunk if c.seq > pred]
        if last is not None:
            out = [c for c in out if c.seq <= last]
        return out

    @staticmethod
    def _rebase_sandwich(items: list[tuple[FieldChanges, Any]],
                         trunk_changes: list[FieldChanges],
                         keep) -> list[tuple[FieldChanges, Any]]:
        """The inverse/trunk/rebased sandwich shared by branch updates
        (editManager.ts:277) and local-branch rebasing (:241): each kept
        item is rebased over the inverses of the items before it, then
        the new trunk changes, then the already-rebased kept items.
        ``items`` are (change, uid) pairs in commit order; dropped items
        (now covered by the trunk) still contribute their inverses."""
        new_items: list[tuple[FieldChanges, Any]] = []
        inverses: list[FieldChanges] = []
        for change, uid in items:
            if keep(uid):
                c = change
                for inv in inverses:
                    c = cs.rebase(c, inv)
                for t in trunk_changes:
                    c = cs.rebase(c, t)
                for nc, _u in new_items:
                    c = cs.rebase(c, nc)
                new_items.append((c, uid))
            inverses.insert(0, cs.invert(change, uid))
        return new_items

    def _update_branch(self, branch: _Branch, new_ref: int) -> None:
        """editManager.ts:277 — rebase the branch over trunk commits up
        to ``new_ref``; drop branch commits now covered by the trunk."""
        trunk_changes = [c.changes
                         for c in self._trunk_after(branch.ref_seq, new_ref)]
        if not trunk_changes:
            branch.local_changes = [c for c in branch.local_changes
                                    if c.seq > new_ref]
            branch.ref_seq = max(branch.ref_seq, new_ref)
            return
        by_seq = {c.seq: c for c in branch.local_changes}
        rebased = self._rebase_sandwich(
            [(c.changes, c.seq) for c in branch.local_changes],
            trunk_changes, keep=lambda seq: seq > new_ref)
        branch.local_changes = [
            Commit(by_seq[seq].session_id, seq, by_seq[seq].ref_seq, change)
            for change, seq in rebased]
        branch.ref_seq = new_ref

    def _rebase_branch_commit_to_trunk(self, commit: Commit,
                                       branch: _Branch) -> FieldChanges:
        """editManager.ts:223."""
        last = self.trunk[-1] if self.trunk else None
        if (not branch.is_divergent and last is not None
                and commit.session_id == last.session_id):
            return commit.changes
        change = commit.changes
        for bc in reversed(branch.local_changes):
            change = cs.rebase(change, cs.invert(bc.changes, bc.seq))
        for t in self._trunk_after(branch.ref_seq):
            change = cs.rebase(change, t.changes)
        return change

    def _add_commit_to_branch(self, branch: _Branch,
                              commit: Commit) -> None:
        """editManager.ts:197 addCommitToBranch."""
        branch.local_changes.append(commit)
        last = self.trunk[-1] if self.trunk else None
        if last is None or commit.ref_seq == last.seq:
            branch.is_divergent = False
        else:
            branch.is_divergent = (branch.is_divergent
                                   or commit.session_id != last.session_id)

    def _rebase_local_branch(self, trunk_change: FieldChanges,
                             trunk_seq: int) -> None:
        """editManager.ts:241 — the inverse/trunk/new-locals sandwich."""
        if not self.local_changes:
            return
        self.local_changes = self._rebase_sandwich(
            self.local_changes, [trunk_change], keep=lambda _tag: True)
