"""SharedTree changeset algebra: compose / invert / rebase over mark lists.

Reference semantics (not code): the ``ChangeRebaser`` contract at
packages/dds/tree/src/core/rebase/rebaser.ts:138-170 — ``compose(changes)``,
``invert(change)``, ``rebase(change, over)`` with the algebraic laws

- ``rebase(a, compose([b, c])) == rebase(rebase(a, b), c)``
- ``rebase(a, compose([])) == a`` and ``rebase(compose([]), a) == a``
- ``compose([a, invert(a)])`` is a no-op

and the concrete sequence-field mark algebra at
packages/dds/tree/src/feature-libraries/sequence-field/{format.ts,
compose.ts:56, invert.ts:21, rebase.ts:44}.

TPU-native re-design: marks are flat JSON-safe dicts (so changesets ship
on the wire unmodified, land in summaries, and pack into the
``[docs, marks, fields]`` int tensors the batched tree kernel consumes).
A changeset is a *field-change map* ``{field_key: [mark, ...]}``;
node-level changes (``mod`` marks) recurse with the same structure —
the modular-schema composition collapsed to one field kind: sequence.

Mark vocabulary (``t`` discriminates):

- ``{"t": "skip", "n": k}``                 — leave k nodes untouched
- ``{"t": "ins",  "content": [nodes], "iid": [uid, a]}`` — attach new
    subtrees; ``iid`` is the mark's *birth identity* (creating session's
    unique changeset uid + attach-mark walk index), stable across
    rebasing and the wire
- ``{"t": "del",  "n": k, "did": [uid, d]}`` — detach k nodes; ``did``
    is the birth identity (uid + cumulative detached-node walk count)
- ``{"t": "rev",  "n": k, "rev": uid, "idx": d}`` — reattach k nodes
    detached by the del with identity ``[uid, d]`` (the product of
    inverting a del; content comes from the forest's repair store,
    mirroring the reference's ForestRepairDataStore)
- ``{"t": "mod", "value": {"new": v, "old": u} | None,
     "fields": {key: [marks]} | None}``     — change one node in place
- ``{"t": "tomb", "n": k, "key": [...], "was": mark}`` — a *muted*
    mark (0 input, 0 output): ``was`` rebased over a delete covering
    the k nodes identified by ``key``; unmutes if those nodes return

Tombstones are what make the EditManager's inverse/trunk/rebased
sandwich (editManager.ts:241,:277) an exact round-trip — the
reference's equivalent is the ``tomb``/lineage machinery in
sequence-field/format.ts. Node-range identity keys:

- ``["d", uid, i]`` — nodes detached by the del whose birth identity is
  ``[uid, i]`` (matches a ``rev`` with ``rev=uid, idx=i``)
- ``["i", uid, a, j]`` — node j of the ins mark ``[uid, a]``, removed by
  rolling that insert back (matches the ins itself on re-application)

Concurrency decisions (each deterministic, hence convergent through the
EditManager's total-order rebasing):

- concurrent attaches at one position: the later-sequenced attach keeps
  the left slot (merge-tree ``breakTie`` convention, mergeTree.ts:1705)
- a change inside a concurrently-deleted range mutes to a tomb;
  attaches survive, anchored at the collapse point between tombs
- concurrent revives of the same detached range: the second revive
  drops the overlap (nodes are already back)
- concurrent value sets: later sequence number wins (LWW), recording
  the overwritten value as its ``old`` so its inverse restores it
"""
from __future__ import annotations

import copy
import itertools
from typing import Any, Optional

_PAIR_COUNTER = itertools.count()

Mark = dict
MarkList = list
FieldChanges = dict  # {field_key: MarkList}


# ---------------------------------------------------------------------------
# mark constructors

def skip(n: int) -> Mark:
    return {"t": "skip", "n": n}


def ins(content: list) -> Mark:
    return {"t": "ins", "content": content}


def dele(n: int) -> Mark:
    return {"t": "del", "n": n}


def rev(n: int, revision: Any, idx: int, mods: Optional[dict] = None) -> Mark:
    m = {"t": "rev", "n": n, "rev": revision, "idx": idx}
    if mods:
        m["mods"] = mods
    return m


def mod(value: Optional[dict] = None,
        fields: Optional[FieldChanges] = None) -> Mark:
    m: Mark = {"t": "mod"}
    if value is not None:
        m["value"] = value
    if fields:
        m["fields"] = fields
    return m


def tomb(n: int, key: list, was: Mark) -> Mark:
    return {"t": "tomb", "n": n, "key": key, "was": was}


# ---------------------------------------------------------------------------
# register field kind (modular-schema: value / optional fields)
#
# The second FieldKind in the algebra (reference:
# packages/dds/tree/src/feature-libraries/modular-schema/ — FieldKind-
# indexed composition; the value/optional kinds there are LWW
# registers). A register field holds at most one node; its change is a
# DICT (sequence-kind changes are lists, so the kind dispatches on the
# change's own shape):
#
#   {"k": "reg", "opt": bool,
#    "mods": [marks]?,    # changes to the CURRENT node (a <=1-node
#                         # sequence — the whole sequence mark algebra
#                         # is reused for the nested piece)
#    "set":  {"new": node|None, "old": node|None,
#             "sid": [uid, n]?, "undoes": [uid, n]?}?,
#    "post": [marks]?,    # changes to the NEW node (arises from
#                         # inversion/composition; applies after set)
#    "muted": [{"mods": [...], "by": [uid, n]}, ...]?}
#
# Order of application: mods, set, post. Concurrency: sets are LWW by
# sequencing (the later-sequenced set wins — both apply, last writer's
# node stands); nested mods whose target node a concurrent set
# replaced MUTE under the set's identity and unmute when that set's
# inverse rebases over them (the same tombstone discipline the
# sequence kind uses, which is what keeps the EditManager's
# invert/rebase sandwich exact).
#
# Composition note: composing "set A then interior churn then set B"
# collapses the interior churn (net effect preserved through the
# old/new chain — the reference's register kinds likewise do not
# support reviving register-replaced content across a composite).


def is_reg(change: Any) -> bool:
    return isinstance(change, dict) and change.get("k") == "reg"


def reg_set(new: Optional[dict], old: Optional[dict],
            optional: bool = True) -> dict:
    """Author a register write: replace the field's node with ``new``
    (None clears an optional field). ``old`` is the author's current
    view — the inverse restores it."""
    if new is None and not optional:
        raise ValueError("value field cannot be cleared")
    return {"k": "reg", "opt": bool(optional),
            "set": {"new": copy.deepcopy(new),
                    "old": copy.deepcopy(old)}}


def reg_mods(marks: MarkList, optional: bool = True) -> dict:
    """Nested changes to the register field's current node."""
    return {"k": "reg", "opt": bool(optional), "mods": marks}


def _reg_normalize(r: dict) -> Optional[dict]:
    out = {"k": "reg", "opt": r.get("opt", True)}
    mods = normalize(r.get("mods") or [])
    if mods:
        out["mods"] = mods
    if r.get("set") is not None:
        out["set"] = r["set"]
    post = normalize(r.get("post") or [])
    if post:
        out["post"] = post
    muted = [e for e in (r.get("muted") or []) if normalize(
        e.get("mods") or [])]
    if muted:
        out["muted"] = muted
    if len(out) == 2:  # only k + opt: no effect
        return None
    return out


def _reg_lower(r: dict) -> MarkList:
    """Lower a register change to sequence marks over the author's
    view (old tells whether a node was present). CONVERGENCE VALVE for
    mixed-kind concurrent editing of one field (one client used the
    sequence surface, another the register surface — an application
    modeling error, but it must merge deterministically, never wedge
    the document): once kinds clash, the register change joins the
    sequence algebra as delete-then-insert."""
    marks: MarkList = list(r.get("mods") or [])
    s = r.get("set")
    if s is not None:
        lowered: MarkList = []
        if s.get("old") is not None:
            lowered.append(dele(1))
        new = s.get("new")
        if new is not None:
            if r.get("post"):
                for pm in r["post"]:
                    if pm["t"] == "mod":
                        new = _mod_node(new, pm)
            lowered.append(ins([copy.deepcopy(new)]))
        marks = _compose_marks(marks, lowered) if marks else lowered
    # muted pieces stay muted (tomb-equivalent: nothing to lower)
    return normalize(marks)


def _compose_reg(a: Any, b: Any) -> Optional[dict]:
    """Net effect of register change ``a`` followed by ``b``."""
    if (a and not is_reg(a)) or (b and not is_reg(b)):
        # mixed kinds: lower the register side and compose as sequence
        am = _reg_lower(a) if is_reg(a) else (a or [])
        bm = _reg_lower(b) if is_reg(b) else (b or [])
        return _compose_marks(am, bm) or None
    a = a or {"k": "reg"}
    b = b or {"k": "reg"}
    opt = a.get("opt", b.get("opt", True))
    muted = list(a.get("muted") or []) + list(b.get("muted") or [])
    if b.get("set") is not None:
        if a.get("set") is not None:
            # interior churn (a.post, b.mods) is replaced by b's set;
            # the old/new chain preserves the net effect
            out = {"k": "reg", "opt": opt, "mods": a.get("mods"),
                   "set": dict(b["set"], old=a["set"]["old"]),
                   "post": b.get("post")}
        else:
            out = {"k": "reg", "opt": opt,
                   "mods": _compose_marks(a.get("mods") or [],
                                          b.get("mods") or []),
                   "set": b["set"], "post": b.get("post")}
    elif a.get("set") is not None:
        out = {"k": "reg", "opt": opt, "mods": a.get("mods"),
               "set": a["set"],
               "post": _compose_marks(a.get("post") or [],
                                      b.get("mods") or [])}
    else:
        out = {"k": "reg", "opt": opt,
               "mods": _compose_marks(a.get("mods") or [],
                                      b.get("mods") or [])}
    if muted:
        out["muted"] = muted
    return _reg_normalize(out)


def _invert_reg(r: dict, uid: Any, counters: dict) -> Optional[dict]:
    """Pieces invert in reverse order: invert(post), set-back,
    invert(mods). Muted intent never applied — its inverse is
    nothing (same rule as tombs)."""
    out = {"k": "reg", "opt": r.get("opt", True)}
    if r.get("post"):
        out["mods"] = _invert_marks(r["post"], uid, counters)
    if r.get("set") is not None:
        s = r["set"]
        inv = {"new": copy.deepcopy(s.get("old")),
               "old": copy.deepcopy(s.get("new"))}
        if s.get("sid") is not None:
            inv["undoes"] = s["sid"]
        out["set"] = inv
    if r.get("mods"):
        out["post"] = _invert_marks(r["mods"], uid, counters)
    return _reg_normalize(out)


def _rebase_reg(c: Any, o: Any) -> Optional[dict]:
    """Re-express register change ``c`` to apply after ``o``."""
    if (c and not is_reg(c)) or (o and not is_reg(o)):
        # mixed kinds: lower to the sequence algebra (see _reg_lower)
        cm = _reg_lower(c) if is_reg(c) else (c or [])
        om = _reg_lower(o) if is_reg(o) else (o or [])
        return _rebase_marks(cm, om) or None
    c = c or {"k": "reg"}
    o = o or {"k": "reg"}
    out = {"k": "reg", "opt": c.get("opt", o.get("opt", True))}
    o_set = o.get("set")
    muted: list = []
    unmuted: MarkList = []
    # unmute entries whose killer o's set undoes (the node is back);
    # they target the node o RESTORED, so they stay active past the
    # muting step below
    for e in c.get("muted") or []:
        if o_set is not None and o_set.get("undoes") is not None \
                and e.get("by") == o_set["undoes"]:
            back = e.get("mods") or []
            # the restored node may have been touched by o.post
            back = _rebase_marks(back, o.get("post") or [])
            unmuted = _compose_marks(unmuted, back) \
                if unmuted else back
        else:
            muted.append(e)
    active_mods = c.get("mods") or []
    if o_set is not None:
        # o replaced (or cleared) the node c's mods targeted: mute
        # them under o's set identity; c's own set still applies (LWW
        # by sequencing) and c.post rides c's own new node
        if active_mods:
            muted.append({"mods": active_mods, "by": o_set.get("sid")})
            active_mods = []
    else:
        active_mods = _rebase_marks(active_mods, o.get("mods") or [])
    if unmuted:
        active_mods = _compose_marks(active_mods, unmuted) \
            if active_mods else unmuted
    if active_mods:
        out["mods"] = active_mods
    if c.get("set") is not None:
        out["set"] = c["set"]
    if c.get("post"):
        out["post"] = c["post"]
    if muted:
        out["muted"] = muted
    return _reg_normalize(out)


def _reg_apply(seq: list, r: dict, apply_marks) -> list:
    """Apply a register change to the field's (<=1 node) content.
    ``apply_marks(seq, marks)`` applies a nested mark list (callers
    supply their walker so repair hooks ride along)."""
    out = seq
    if r.get("mods"):
        out = apply_marks(out, r["mods"])
    if r.get("set") is not None:
        new = r["set"].get("new")
        out = [copy.deepcopy(new)] if new is not None else []
    if r.get("post"):
        out = apply_marks(out, r["post"])
    return out


def move(src: int, count: int, dst: int, pair: Any = None) -> MarkList:
    """Same-field move of ``count`` nodes from input position ``src``
    to input position ``dst`` (outside the moved range), expressed as
    a paired detach+revive: the del detaches the nodes under a birth
    identity and the rev reattaches exactly those nodes at ``dst``
    (MoveOut/MoveIn, sequence-field/format.ts — here the pairing rides
    the existing del/rev identity machinery, so compose, invert —
    a move's inverse is the move back — and rebasing, including
    muting/unmuting through tombstones, need no new mark kind).
    ``stamp`` resolves the pairing token into real identities.

    Concurrency: DELETE WINS — if another client concurrently deletes
    the source nodes, both halves mute (the nodes stay deleted; they
    return, moved, only if that delete is itself undone)."""
    if not (dst <= src or dst >= src + count):
        raise ValueError("move destination inside the moved range")
    token = pair if pair is not None else (
        f"__pair{next(_PAIR_COUNTER)}"  # unique per authored move:
        # geometry-based tokens collide across fields (stamp resolves
        # pairings changeset-wide)
    )
    d = {"t": "del", "n": count, "mv": token}
    r = {"t": "rev", "n": count, "rev": None, "idx": 0, "mv": token}
    if dst <= src:
        return normalize(
            [skip(dst), r, skip(src - dst), d]
        )
    return normalize(
        [skip(src), d, skip(dst - src - count), r]
    )


# ---------------------------------------------------------------------------
# mark measurements

def in_len(m: Mark) -> int:
    """How many nodes of the input sequence the mark consumes."""
    t = m["t"]
    if t in ("skip", "del"):
        return m["n"]
    if t == "mod":
        return 1
    return 0  # ins / rev attach; tomb is muted


def out_len(m: Mark) -> int:
    """How many nodes the mark contributes to the output sequence."""
    t = m["t"]
    if t == "skip":
        return m["n"]
    if t == "ins":
        return len(m["content"])
    if t == "rev":
        return m["n"]
    if t == "mod":
        return 1
    return 0  # del / tomb


def is_attach(m: Mark) -> bool:
    return m["t"] in ("ins", "rev")


def _split(m: Mark, k: int) -> tuple[Mark, Mark]:
    """Split ``m`` so the first piece covers k of its units, advancing
    every identity the second piece carries."""
    t = m["t"]
    if t in ("skip", "del"):
        a, b = {**m, "n": k}, {**m, "n": m["n"] - k}
        if t == "del" and "did" in m:
            b["did"] = [m["did"][0], m["did"][1] + k]
        if t == "del" and "rbof" in m:
            r = m["rbof"]
            b["rbof"] = [r[0], r[1], (r[2] if len(r) > 2 else 0) + k]
        return a, b
    if t == "ins":
        a = {**m, "content": m["content"][:k]}
        b = {**m, "content": m["content"][k:]}
        if "iid" in m:
            b["ioff"] = m.get("ioff", 0) + k
        return a, b
    if t == "rev":
        a = {**m, "n": k}
        b = {**m, "n": m["n"] - k, "idx": m["idx"] + k}
        for piece, rng in ((a, range(0, k)), (b, range(k, m["n"]))):
            if "mods" in m:
                base = rng.start
                sel = {str(int(o) - base): mm for o, mm in m["mods"].items()
                       if int(o) in rng}
                if sel:
                    piece["mods"] = sel
                else:
                    piece.pop("mods", None)
        return a, b
    if t == "tomb":
        wa, wb = _split(m["was"], k) if m["was"]["t"] != "skip" \
            else (skip(k), skip(m["n"] - k))
        key_b = list(m["key"])
        key_b[-1] += k
        return ({**m, "n": k, "was": wa},
                {**m, "n": m["n"] - k, "key": key_b, "was": wb})
    raise ValueError(f"cannot split mark {t!r}")


class _Queue:
    """A mark stream with piecewise consumption (inputs are deep-copied
    so emitted marks are always fresh — ``normalize`` merges in place)."""

    def __init__(self, marks: MarkList):
        self._marks = [copy.deepcopy(m) for m in marks]
        self._i = 0

    def peek(self) -> Optional[Mark]:
        return self._marks[self._i] if self._i < len(self._marks) else None

    def pop(self) -> Mark:
        m = self._marks[self._i]
        self._i += 1
        return m

    def split_head(self, k: int) -> None:
        first, rest = _split(self._marks[self._i], k)
        self._marks[self._i] = first
        self._marks.insert(self._i + 1, rest)

    def take_input(self, k: int) -> Mark:
        """Pop a piece consuming min(k, in_len(head)) input units."""
        if in_len(self._marks[self._i]) > k:
            self.split_head(k)
        return self.pop()

    def take_output(self, k: int) -> Mark:
        """Pop a piece contributing min(k, out_len(head)) output units."""
        if out_len(self._marks[self._i]) > k:
            self.split_head(k)
        return self.pop()

    @property
    def empty(self) -> bool:
        return self._i >= len(self._marks)


def normalize(marks: MarkList) -> MarkList:
    """Merge adjacent same-kind contiguous marks, drop empties and
    trailing skips (incl. muted skips — implicit position)."""
    out: MarkList = []
    for m in marks:
        t = m["t"]
        if t in ("skip", "del", "rev", "tomb") and m["n"] == 0:
            continue
        if t == "ins" and not m["content"]:
            continue
        if t == "mod" and "value" not in m and not m.get("fields"):
            m = skip(1)
            t = "skip"
        if out:
            p = out[-1]
            if p["t"] == t == "skip":
                p["n"] += m["n"]
                continue
            if (p["t"] == t == "del" and "did" not in p and "did" not in m
                    and "rbof" not in p and "rbof" not in m
                    and "mv" not in p and "mv" not in m):
                p["n"] += m["n"]
                continue
            if (p["t"] == t == "del" and "did" in p and "did" in m
                    and p["did"][0] == m["did"][0]
                    and p["did"][1] + p["n"] == m["did"][1]
                    and "rbof" not in p and "rbof" not in m):
                p["n"] += m["n"]
                continue
            if (p["t"] == t == "rev" and p["rev"] == m["rev"]
                    and p["rev"] is not None
                    and p["idx"] + p["n"] == m["idx"]
                    and "mods" not in p and "mods" not in m):
                p["n"] += m["n"]
                continue
            if (p["t"] == t == "ins" and "iid" not in p and "iid" not in m):
                p["content"] = p["content"] + m["content"]
                continue
            if (p["t"] == t == "tomb"
                    and p["was"]["t"] == m["was"]["t"] == "skip"
                    and p["key"][:-1] == m["key"][:-1]
                    and p["key"][-1] + p["n"] == m["key"][-1]):
                p["n"] += m["n"]
                p["was"]["n"] = p["n"]
                continue
        out.append(m)
    while out and (out[-1]["t"] == "skip"
                   or (out[-1]["t"] == "tomb"
                       and out[-1]["was"]["t"] == "skip")):
        out.pop()
    return out


def normalize_fields(changes: FieldChanges) -> FieldChanges:
    out = {}
    for key, marks in changes.items():
        nm = _reg_normalize(marks) if is_reg(marks) else \
            normalize(marks)
        if nm:
            out[key] = nm
    return out


# ---------------------------------------------------------------------------
# birth identity stamping

def stamp(changes: FieldChanges, uid: str) -> FieldChanges:
    """Stamp birth identities (``iid`` on ins, ``did`` on del) into a
    freshly authored changeset, in the canonical walk order (marks in
    list order, ``mod`` nested fields sorted by key). Already-stamped
    marks keep their identity (resubmits must not re-identify).
    Move pairings (``mv`` tokens from :func:`move`) resolve here: the
    rev half adopts its del half's freshly assigned identity."""
    counters = {"a": 0, "d": 0}
    pairs: dict = {}
    _stamp_fields(changes, uid, counters, pairs)
    _resolve_moves(changes, pairs)
    return changes


def _resolve_moves(changes: FieldChanges, pairs: dict) -> None:
    for key in sorted(changes):
        if is_reg(changes[key]):
            for piece in ("mods", "post"):
                if changes[key].get(piece):
                    _resolve_moves({key: changes[key][piece]}, pairs)
            continue
        for m in changes[key]:
            if m["t"] == "rev" and m.get("rev") is None:
                did = pairs.get(m.get("mv"))
                if did is None:
                    raise ValueError(
                        f"unpaired move revive {m.get('mv')!r}"
                    )
                m["rev"], m["idx"] = did[0], did[1]
            elif m["t"] == "mod" and m.get("fields"):
                _resolve_moves(m["fields"], pairs)


def _stamp_fields(changes: FieldChanges, uid: str, counters: dict,
                  pairs: Optional[dict] = None) -> None:
    for key in sorted(changes):
        if is_reg(changes[key]):
            r = changes[key]
            if r.get("set") is not None and "sid" not in r["set"]:
                r["set"]["sid"] = [uid, counters.setdefault("s", 0)]
                counters["s"] += 1
            for piece in ("mods", "post"):
                if r.get(piece):
                    _stamp_fields({key: r[piece]}, uid, counters,
                                  pairs)
            continue
        for m in changes[key]:
            t = m["t"]
            if t == "ins":
                if "iid" not in m:
                    m["iid"] = [uid, counters["a"]]
                counters["a"] += 1
            elif t == "del":
                if "did" not in m and "rbof" not in m:
                    m["did"] = [uid, counters["d"]]
                if pairs is not None and "mv" in m:
                    pairs[m["mv"]] = m["did"]
                counters["d"] += m["n"]
            elif t == "mod" and m.get("fields"):
                _stamp_fields(m["fields"], uid, counters, pairs)


# ---------------------------------------------------------------------------
# compose

def compose(changes: list[FieldChanges]) -> FieldChanges:
    """rebaser.ts:143 — fold changesets into one with the same net
    effect. ``compose([])`` is the identity changeset ``{}``."""
    acc: FieldChanges = {}
    for c in changes:
        acc = _compose2(acc, c)
    return acc


def _compose2(a: FieldChanges, b: FieldChanges) -> FieldChanges:
    out: FieldChanges = {}
    for key in sorted(set(a) | set(b)):
        av, bv = a.get(key), b.get(key)
        if is_reg(av) or is_reg(bv):
            reg = _compose_reg(av, bv)
            if reg:
                out[key] = reg
            continue
        marks = _compose_marks(av or [], bv or [])
        if marks:
            out[key] = marks
    return out


def _merge_mod(am: Mark, bm: Mark) -> Mark:
    """Net effect of node change ``am`` followed by ``bm``."""
    value = None
    if "value" in bm and "value" in am:
        value = {"new": bm["value"]["new"], "old": am["value"]["old"]}
    elif "value" in bm:
        value = bm["value"]
    elif "value" in am:
        value = am["value"]
    fields = _compose2(am.get("fields") or {}, bm.get("fields") or {})
    return mod(value=value, fields=fields or None)


def _mod_node(node: dict, m: Mark) -> dict:
    """Apply a mod mark directly to a fresh (inserted) subtree."""
    node = copy.deepcopy(node)
    if "value" in m:
        node["value"] = m["value"]["new"]
    for key, marks in (m.get("fields") or {}).items():
        seq = node.setdefault("fields", {}).get(key, [])
        if is_reg(marks):
            node["fields"][key] = _reg_apply(
                seq, marks, _apply_marks_to_content)
        else:
            node["fields"][key] = _apply_marks_to_content(seq, marks)
    return node


def walk_apply(seq: list, marks: MarkList, *,
               on_del=None, on_rev=None, mod_node=None) -> list:
    """The one mark-list interpreter: apply ``marks`` to node sequence
    ``seq``. Hooks let callers attach side effects without a second
    hand-synchronized walker (Forest captures/fetches repair data;
    content application inside compose needs neither):

    - ``on_del(mark, nodes)`` — observe detached nodes (repair capture)
    - ``on_rev(mark) -> [nodes]`` — produce restored nodes; revives are
      invalid where no repair source exists (fresh inserted content)
    - ``mod_node(node, mark) -> node`` — apply a mod to one node
    """
    mod_node = mod_node or _mod_node
    out: list = []
    pos = 0
    for m in marks:
        t = m["t"]
        if t == "skip":
            out.extend(seq[pos:pos + m["n"]])
            pos += m["n"]
        elif t == "ins":
            out.extend(copy.deepcopy(m["content"]))
        elif t == "del":
            if on_del is not None:
                on_del(m, seq[pos:pos + m["n"]])
            pos += m["n"]
        elif t == "rev":
            if on_rev is None:
                raise ValueError("revive inside inserted content")
            for i, restored in enumerate(on_rev(m)):
                mm = (m.get("mods") or {}).get(str(i))
                out.append(mod_node(restored, mm) if mm else restored)
        elif t == "mod":
            target = copy.deepcopy(seq[pos]) if pos < len(seq) \
                else {"type": "repair-missing"}
            out.append(mod_node(target, m))
            pos += 1
        elif t == "tomb":
            pass  # muted: no effect
        else:
            raise ValueError(f"unknown mark {t!r}")
    out.extend(seq[pos:])
    return out


def _apply_marks_to_content(seq: list, marks: MarkList) -> list:
    """Apply a mark list to literal content (no repair store)."""
    return walk_apply(seq, marks)


def _compose_marks(a_marks: MarkList, b_marks: MarkList) -> MarkList:
    """``a`` then ``b``: b consumes a's output sequence."""
    a = _Queue(a_marks)
    out: MarkList = []
    # b-del erasing an a-attach (ins+del -> never existed; rev+del ->
    # stays detached) also erases that del's IDENTITY — a rev in b
    # paired to it (b moving nodes a just attached) would orphan.
    # Record what each erased-del node really was so the post-pass can
    # rewrite such revs into direct attaches of the source.
    erased: dict = {}
    for bm in copy.deepcopy(b_marks):
        if bm["t"] == "tomb" or is_attach(bm):
            out.append(bm)
            continue
        need = in_len(bm)
        while need > 0:
            am = a.peek()
            if am is None:
                # b extends past a's explicit output: applies verbatim
                out.append(bm)
                need = 0
                break
            if out_len(am) == 0:  # a's del / tomb: pass through
                out.append(a.pop())
                continue
            apiece = a.take_output(need)
            m = out_len(apiece)
            if in_len(bm) > m:
                bpiece, bm = _split(bm, m)
            else:
                bpiece, bm = bm, None
            out.extend(_compose_pair(apiece, bpiece, erased))
            need -= in_len(bpiece)
            if bm is None:
                break
    while not a.empty:
        out.append(a.pop())
    if erased:
        out = _reroute_erased_revs(out, erased)
    return normalize(out)


def _reroute_erased_revs(marks: MarkList, erased: dict) -> MarkList:
    """Rewrite rev pieces whose source del was erased in composition:
    nodes born of an erased ins attach as fresh content; nodes that
    were a re-detach of an older revive re-attach under the ORIGINAL
    detach identity."""
    out: MarkList = []
    for m in marks:
        if m["t"] != "rev":
            out.append(m)
            continue
        i = 0
        while i < m["n"]:
            src = erased.get((m["rev"], m["idx"] + i))
            if src is None:
                j = i
                while j < m["n"] and erased.get(
                    (m["rev"], m["idx"] + j)
                ) is None:
                    j += 1
                keep = {**m, "n": j - i, "idx": m["idx"] + i}
                if "mods" in m:
                    sel = {str(int(o) - i): mm
                           for o, mm in m["mods"].items()
                           if i <= int(o) < j}
                    if sel:
                        keep["mods"] = sel
                    else:
                        keep.pop("mods", None)
                out.append(keep)
                i = j
                continue
            kind, payload = src[0], src[1:]
            if kind == "content":
                nd = copy.deepcopy(payload[0])
                mm = (m.get("mods") or {}).get(str(i))
                out.append(ins([_mod_node(nd, mm) if mm else nd]))
            else:  # ("rev", orig_u, orig_idx)
                piece = {"t": "rev", "n": 1, "rev": payload[0],
                         "idx": payload[1]}
                mm = (m.get("mods") or {}).get(str(i))
                if mm is not None:
                    piece["mods"] = {"0": mm}
                out.append(piece)
            i += 1
    return out


def _compose_pair(am: Mark, bm: Mark,
                  erased: Optional[dict] = None) -> MarkList:
    """Net marks for an aligned (a output piece, b sized piece)."""
    bt = bm["t"]
    at = am["t"]
    if bt == "skip":
        return [am]
    if bt == "del":
        if at == "skip":
            return [bm]
        if at == "ins":
            # inserted then deleted: never existed — but record the
            # erased identity's true content for paired revs (moves)
            if erased is not None and "did" in bm:
                u, b0 = bm["did"]
                for j, nd in enumerate(am["content"]):
                    erased[(u, b0 + j)] = ("content", nd)
            return []
        if at == "rev":
            # revived then re-deleted: stays detached under the
            # ORIGINAL identity; paired revs re-point there
            if erased is not None and "did" in bm:
                u, b0 = bm["did"]
                for j in range(am["n"]):
                    erased[(u, b0 + j)] = (
                        "rev", am["rev"], am["idx"] + j
                    )
            return []
        if at == "mod":
            return [{**bm, "n": 1}]  # changed then deleted: net delete
    if bt == "mod":
        if at == "skip":
            return [bm]
        if at == "ins":
            return [{**am, "content": [_mod_node(am["content"][0], bm)]}]
        if at == "rev":
            mods = dict(am.get("mods") or {})
            prior = mods.get("0")
            mods["0"] = _merge_mod(prior, bm) if prior else bm
            return [rev(am["n"], am["rev"], am["idx"], mods=mods)]
        if at == "mod":
            return [_merge_mod(am, bm)]
    raise ValueError(f"unhandled compose pair {at}/{bt}")


# ---------------------------------------------------------------------------
# invert

def invert(changes: FieldChanges, uid: Any) -> FieldChanges:
    """rebaser.ts:151 — the changeset undoing ``changes``. ``uid``
    names the inverse itself (its dels fall back to it when the source
    mark carries no birth identity). Dels become revs pointing at the
    source del's birth identity; inserts become rollback-dels carrying
    ``rbof`` (the ins identity) so marks muted by the rollback unmute
    when the insert is re-applied."""
    counters = {"d": 0, "a": 0}
    return _invert_fields(changes, uid, counters)


def _invert_fields(changes: FieldChanges, uid: Any,
                   counters: dict) -> FieldChanges:
    out: FieldChanges = {}
    for key in sorted(changes):
        if is_reg(changes[key]):
            inv = _invert_reg(changes[key], uid, counters)
            if inv:
                out[key] = inv
            continue
        out[key] = _invert_marks(changes[key], uid, counters)
    return normalize_fields(out)


def _invert_marks(marks: MarkList, uid: Any, counters: dict) -> MarkList:
    out: MarkList = []
    for m in marks:
        t = m["t"]
        if t == "skip":
            out.append(skip(m["n"]))
        elif t == "ins":
            iid = m.get("iid", [uid, counters["a"]])
            base = m.get("ioff", 0)
            d = dele(len(m["content"]))
            d["rbof"] = [iid[0], iid[1], base]
            out.append(d)
            counters["a"] += 1
        elif t == "del":
            if "did" in m:
                u, i = m["did"]
            else:
                u, i = uid, counters["d"]
            out.append(rev(m["n"], u, i))
            counters["d"] += m["n"]
        elif t == "rev":
            d = dele(m["n"])
            d["did"] = [m["rev"], m["idx"]]  # re-detach the same nodes
            out.append(d)
        elif t == "mod":
            value = None
            if "value" in m:
                value = {"new": m["value"]["old"], "old": m["value"]["new"]}
            fields = _invert_fields(m.get("fields") or {}, uid, counters) \
                if m.get("fields") else None
            out.append(mod(value=value, fields=fields))
        elif t == "tomb":
            pass  # muted intent never applied; its inverse is nothing
    return normalize(out)


# ---------------------------------------------------------------------------
# rebase

def rebase(change: FieldChanges, over: FieldChanges) -> FieldChanges:
    """rebaser.ts:156 — re-express ``change`` (authored against the
    same base as ``over``) so it applies after ``over``."""
    out: FieldChanges = {}
    for key in sorted(set(change) | set(over)):
        cv, ov = change.get(key), over.get(key)
        if is_reg(cv) or is_reg(ov):
            reg = _rebase_reg(cv, ov)
            if reg:
                out[key] = reg
            continue
        marks = _rebase_marks(cv or [], ov or [])
        if marks:
            out[key] = marks
    return out


def _attach_identity(om: Mark) -> Optional[list]:
    """Identity key base for the nodes an over-attach (re)creates."""
    if om["t"] == "rev":
        return ["d", om["rev"], om["idx"]]
    if om["t"] == "ins" and "iid" in om:
        return ["i", om["iid"][0], om["iid"][1], om.get("ioff", 0)]
    return None


def _del_identity(om: Mark, offset: int) -> list:
    """Identity key for node ``offset`` within an over-delete."""
    if "rbof" in om:
        r = om["rbof"]
        return ["i", r[0], r[1], (r[2] if len(r) > 2 else 0) + offset]
    if "did" in om:
        return ["d", om["did"][0], om["did"][1] + offset]
    return ["d", None, offset]  # unstamped: unmatchable but harmless


def _mute(cpiece: Mark, om: Mark, offset: int) -> Mark:
    """Mute a sized change piece whose target nodes ``over`` deleted."""
    k = in_len(cpiece)
    was = cpiece if cpiece["t"] != "skip" else skip(k)
    return tomb(k, _del_identity(om, offset), was)


def _rebase_marks(c_marks: MarkList, o_marks: MarkList) -> MarkList:
    c = _Queue(c_marks)
    out: MarkList = []
    # (uid, idx) of change-del nodes muted by an over-delete -> the
    # over-delete's identity for that node; a rev half paired to them
    # (a move whose source was concurrently deleted) mutes too —
    # DELETE WINS — keyed so undoing the over-delete unmutes the move
    dead: dict = {}
    for om in copy.deepcopy(o_marks):
        t = om["t"]
        if t == "tomb":
            continue  # over's muted marks changed nothing
        if is_attach(om):
            _rebase_over_attach(c, om, out)
            continue
        total = in_len(om)
        need = total
        while need > 0:
            cm = c.peek()
            if cm is None:
                break  # change's implicit trailing skip
            if cm["t"] == "tomb":
                out.append(c.pop())
                continue
            if is_attach(cm):
                # change's attach binds here; the later-sequenced change
                # keeps the left slot at a tied position (breakTie)
                out.append(c.pop())
                continue
            cpiece = c.take_input(need)
            k = in_len(cpiece)
            if t == "skip":
                out.append(cpiece)
            elif t == "del":
                offset = total - need
                if cpiece["t"] == "del" and "did" in cpiece:
                    u, base = cpiece["did"]
                    for i in range(k):
                        dead[(u, base + i)] = _del_identity(
                            om, offset + i
                        )
                out.append(_mute(cpiece, om, offset))
            elif t == "mod":
                if cpiece["t"] == "mod":
                    out.append(_rebase_mod(cpiece, om))
                else:
                    out.append(cpiece)
            else:
                raise ValueError(f"unhandled rebase over {t}")
            need -= k
    while not c.empty:
        out.append(c.pop())
    if dead:
        out = _mute_paired_revs(out, dead)
    return normalize(out)


def _mute_paired_revs(marks: MarkList, dead: dict) -> MarkList:
    """Mute rev pieces whose source nodes an over-delete took (the
    rev half of a move whose del half just muted): tomb them under the
    over-delete's identity so a revive of THOSE nodes unmutes the move
    too."""
    out: MarkList = []
    for m in marks:
        if m["t"] != "rev":
            out.append(m)
            continue
        i = 0
        while i < m["n"]:
            key = dead.get((m["rev"], m["idx"] + i))
            j = i
            while j < m["n"] and (
                (dead.get((m["rev"], m["idx"] + j)) is None)
                == (key is None)
            ):
                j += 1
            piece = {**m, "n": j - i, "idx": m["idx"] + i}
            if "mods" in m:
                sel = {str(int(o) - i): mm
                       for o, mm in m["mods"].items()
                       if i <= int(o) < j}
                if sel:
                    piece["mods"] = sel
                else:
                    piece.pop("mods", None)
            if key is None:
                out.append(piece)
            else:
                # per-node tombs: the over-delete identities need not
                # be contiguous across the run
                for off in range(i, j):
                    p1 = {**piece, "n": 1, "idx": m["idx"] + off}
                    mm = (m.get("mods") or {}).get(str(off))
                    if mm is not None:
                        p1["mods"] = {"0": mm}
                    else:
                        p1.pop("mods", None)
                    out.append(tomb(
                        1, dead[(m["rev"], m["idx"] + off)], p1
                    ))
            i = j
    return out


def _tomb_match_offset(cm: Mark, ident: Optional[list],
                       width: int) -> Optional[int]:
    """If tomb ``cm`` names nodes the over-attach restores, return the
    tomb's start offset within the attach span."""
    if ident is None or cm["t"] != "tomb":
        return None
    key = cm["key"]
    if key[:-1] != ident[:-1]:
        return None
    off = key[-1] - ident[-1]
    if 0 <= off < width:
        return off
    return None


def _rebase_over_attach(c: _Queue, om: Mark, out: MarkList) -> None:
    """Over attached ``out_len(om)`` nodes here. The rebased change
    steps over them — except tombs matching the restored nodes unmute
    back into live marks, and the change's own attaches keep their
    position among the tombs."""
    width = out_len(om)
    ident = _attach_identity(om)
    pos = 0
    while pos < width:
        cm = c.peek()
        if cm is None:
            break
        if is_attach(cm):
            if (cm["t"] == "rev" and om["t"] == "rev"
                    and cm["rev"] == om["rev"]):
                # concurrent revive of the same detached range: drop
                # the overlap (those nodes are already back)
                lo = max(cm["idx"], om["idx"])
                hi = min(cm["idx"] + cm["n"], om["idx"] + om["n"])
                if hi > lo:
                    cm = c.pop()
                    if cm["idx"] < lo:
                        out.append(_split(cm, lo - cm["idx"])[0])
                    if cm["idx"] + cm["n"] > hi:
                        out.append(_split(cm, hi - cm["idx"])[1])
                    continue
            out.append(c.pop())
            continue
        off = _tomb_match_offset(cm, ident, width)
        if off is not None and off >= pos:
            if off > pos:
                out.append(skip(off - pos))
                pos = off
            k = min(cm["n"], width - off)
            if cm["n"] > k:
                c.split_head(k)
            t = c.pop()
            out.append(t["was"])  # unmute
            pos += k
            continue
        if cm["t"] == "tomb":
            out.append(c.pop())  # unrelated mute: carry it along
            continue
        break  # sized mark: belongs after the attach span
    if pos < width:
        out.append(skip(width - pos))


def _rebase_mod(cm: Mark, om: Mark) -> Mark:
    value = cm.get("value")
    if value is not None and "value" in om:
        # over set the value first; our set still wins (later seq) but
        # must record over's value as the one it overwrote.
        value = {"new": value["new"], "old": om["value"]["new"]}
    fields = None
    if cm.get("fields"):
        fields = rebase(cm["fields"], om.get("fields") or {}) or None
    return mod(value=value, fields=fields)
